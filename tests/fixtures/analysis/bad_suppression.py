"""Seeded violation for the suppression-audit pass: one suppression
that still matches a real determinism finding (quiet), one that
matches nothing (stale -> finding), and one naming an unknown pass id
(always a finding)."""
import time


def now():
    # Load-bearing: the determinism pass fires here and is suppressed.
    return time.time()  # swtpu-check: ignore[determinism]


def stale():
    return 1.0  # swtpu-check: ignore[determinism]  # SEEDED


def typo():
    return 2.0  # swtpu-check: ignore[determinsm]  # SEEDED
