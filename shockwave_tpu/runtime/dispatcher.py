"""Per-worker job dispatcher: launches training processes on chips.

Constructs the launch command (appending step budget, checkpoint dir, and
the lease-iterator flag), injects the SWTPU_* environment, runs the
process, scrapes progress from the iterator log, and notifies the
scheduler (reference: runtime/rpc/dispatcher.py).

TPU-native differences:
- a "GPU id" becomes a chip index; single-chip jobs get exclusive use of
  one chip via JAX_VISIBLE_DEVICES (no CUDA MPS equivalent on TPU, so no
  space sharing on real hardware);
- multi-chip jobs receive coordinator address/rank env for
  `jax.distributed.initialize` instead of torch master_addr/port args.
"""
from __future__ import annotations

import logging
import os
import queue
import re
import signal
import subprocess
import threading
import time
from typing import Dict, List, Optional

import grpc

from ..obs import names as obs_names
from . import faults
from .resilience import RpcUnavailableError

logger = logging.getLogger("shockwave_tpu.runtime")

_PROGRESS_RE = {
    "steps": re.compile(r"\[PROGRESS\] \[STEPS\] (\d+)"),
    "duration": re.compile(r"\[PROGRESS\] \[DURATION\] ([-+]?\d*\.\d+|\d+)"),
}


class Dispatcher:
    def __init__(self, round_duration: float, chip_ids: List[int],
                 worker_rpc_client, sched_addr: str, sched_port: int,
                 run_dirs: Dict[str, str], data_dir: Optional[str],
                 checkpoint_dir: str, span_shard=None,
                 trace_dir: Optional[str] = None):
        # Fleet tracing (opt-in): the daemon's span shard — every
        # dispatched process gets a `launch` span parented under the
        # scheduler-propagated RunJob context, and the launch context
        # is exported into the trainer's environment (runtime/spans.py)
        # so the job-side LeaseIterator continues the same trace.
        self._span_shard = span_shard
        self._trace_dir = trace_dir
        self._round_duration = round_duration
        self._worker_rpc_client = worker_rpc_client
        self._sched_addr = sched_addr
        self._sched_port = sched_port
        self._run_dirs = run_dirs  # mode -> root of training scripts
        self._data_dir = data_dir
        self._checkpoint_dir = checkpoint_dir
        self._chip_queue: "queue.Queue[int]" = queue.Queue()
        for chip_id in chip_ids:
            self._chip_queue.put(chip_id)
        self._lock = threading.Lock()
        self._processes: Dict[int, subprocess.Popen] = {}  # job_id -> proc
        self._shutdown = threading.Event()
        # RunJob is delivered at-least-once (the scheduler retries on
        # UNAVAILABLE, which gRPC can return even after the handler ran):
        # remember accepted (job_ids, worker_id, round_id) triples so a
        # replay cannot spawn a second trainer for the same micro-task.
        self._accepted_dispatches: Dict[tuple, int] = {}  # key -> round_id

    # -- command construction ---------------------------------------------

    def _construct_command(self, job: dict, chip_id: int, worker_id: int) -> str:
        command = job["command"]
        if job["needs_data_dir"] and self._data_dir and "%s" in command:
            command = command % (self._data_dir,)
        command = (
            f"{command} --local_rank {chip_id} "
            f"{job['num_steps_arg']} {job['num_steps']} "
            f"--checkpoint_dir {self._job_checkpoint_dir(job['job_id'])} "
            f"--enable_lease_iterator"
        )
        return command

    def _job_checkpoint_dir(self, job_id: int) -> str:
        path = os.path.join(self._checkpoint_dir, f"job_id={job_id}")
        os.makedirs(path, exist_ok=True)
        return path

    def _job_env(self, job: dict, worker_id: int, round_id: int,
                 chip_id: int) -> dict:
        env = dict(os.environ)
        env.update({
            "SWTPU_JOB_ID": str(job["job_id"]),
            "SWTPU_WORKER_ID": str(worker_id),
            "SWTPU_ROUND_ID": str(round_id),
            "SWTPU_SCHED_ADDR": self._sched_addr,
            "SWTPU_SCHED_PORT": str(self._sched_port),
            # Adaptation mode (static / accordion / gns): Trainer selects
            # its batch-size monitor from this. The reference selects mode
            # by dispatching from a different script tree per mode
            # (runtime/rpc/dispatcher.py:385-390); here one tree serves
            # all modes and the env var switches behavior.
            "SWTPU_MODE": job.get("mode", "static") or "static",
            # Restrict the training process to its chip.
            "JAX_VISIBLE_DEVICES": str(chip_id),
            "TPU_VISIBLE_CHIPS": str(chip_id),
        })
        # RPC deadline for the job's lease iterator: InitJob can
        # legitimately block at the scheduler until the round boundary
        # (early dispatch), so the deadline must cover a full round —
        # and the total retry budget must cover the deadline, or the
        # first expiry would exhaust it and no retry would ever run.
        # Operator-set values win.
        deadline = max(60.0, 2 * self._round_duration + 60.0)
        env.setdefault("SWTPU_RPC_DEADLINE_S", str(deadline))
        env.setdefault("SWTPU_RPC_BUDGET_S", str(1.5 * deadline))
        return env

    # -- progress scraping -------------------------------------------------

    def _read_progress(self, job_id: int, round_id: int, worker_id: int):
        log_path = os.path.join(
            self._job_checkpoint_dir(job_id), ".swtpu",
            f"round={round_id}", f"worker={worker_id}.log")
        steps, duration, lines = 0, 0.0, []
        try:
            with open(log_path) as f:
                for line in f:
                    lines.append(line.rstrip("\n"))
                    if m := _PROGRESS_RE["steps"].search(line):
                        steps = int(m.group(1))
                    if m := _PROGRESS_RE["duration"].search(line):
                        duration = float(m.group(1))
        except FileNotFoundError:
            logger.warning("no iterator log for job %d round %d", job_id, round_id)
        return steps, duration, "\n".join(lines)

    # -- dispatch ----------------------------------------------------------

    def dispatch_jobs(self, jobs: List[dict], worker_id: int, round_id: int,
                      trace_parent=None):
        key = (tuple(j["job_id"] for j in jobs), worker_id, round_id)
        with self._lock:
            if key in self._accepted_dispatches:
                logger.warning("dropping duplicate RunJob %s (retry of an "
                               "already-accepted dispatch)", key)
                return
            self._accepted_dispatches[key] = round_id
            # Bounded memory: anything two rounds stale can no longer be
            # replayed (the scheduler's retry budget is well under two
            # rounds).
            for old in [k for k, r in self._accepted_dispatches.items()
                        if r < round_id - 2]:
                del self._accepted_dispatches[old]
        # Daemon thread, deliberately unreferenced: nothing ever joined
        # the old `_pool` list, so keeping thread handles was dead state
        # mutated concurrently by RunJob handlers (race-detector
        # finding) — removed rather than locked.
        threading.Thread(
            target=self._dispatch_jobs_helper,
            args=(jobs, worker_id, round_id, trace_parent),
            daemon=True).start()

    def _dispatch_jobs_helper(self, jobs: List[dict], worker_id: int,
                              round_id: int, trace_parent=None):
        from . import spans as spans_mod
        chip_id = self._chip_queue.get()
        results = []
        try:
            for job in jobs:
                if faults.get_injector().should_freeze("dispatch"):
                    # Injected wedge: hold the chip, launch nothing,
                    # report nothing — exactly what a hung process looks
                    # like to the scheduler's watchdogs.
                    logger.warning("[job %d] frozen by fault injection",
                                   job["job_id"])
                    self._shutdown.wait()
                    return
                command = self._construct_command(job, chip_id, worker_id)
                env = self._job_env(job, worker_id, round_id, chip_id)
                slowdown = faults.get_injector().slowdown("dispatch")
                if slowdown < 1.0:
                    # Gray-failure drill: the process runs, leases renew,
                    # Ping answers — only step throughput shrinks. The
                    # training side reads this to throttle itself (the
                    # stub workers scale their simulated rate by it).
                    env["SWTPU_DEGRADE_FACTOR"] = f"{slowdown:.6f}"
                launch_span = None
                if self._span_shard is not None:
                    # One `launch` span per trainer process (its whole
                    # lifetime), parented under the RunJob context; the
                    # trainer continues the trace from the env export.
                    launch_span = self._span_shard.open_span(
                        obs_names.SPAN_LAUNCH, parent=trace_parent,
                        job=job["job_id"], round=round_id,
                        worker=worker_id, chip=chip_id)
                    spans_mod.export_trace_env(
                        env, launch_span.context, self._trace_dir)
                cwd = self._run_dirs.get(job["mode"], ".")
                if job["working_directory"]:
                    cwd = os.path.join(cwd, job["working_directory"])
                logger.info("[job %d round %d chip %d] launching: %s",
                            job["job_id"], round_id, chip_id, command)
                start = time.time()
                proc = subprocess.Popen(
                    command, shell=True, cwd=cwd, env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    start_new_session=True)
                with self._lock:
                    self._processes[job["job_id"]] = proc
                output, _ = proc.communicate()
                elapsed = time.time() - start
                with self._lock:
                    self._processes.pop(job["job_id"], None)
                steps, duration, iterator_log = self._read_progress(
                    job["job_id"], round_id, worker_id)
                if proc.returncode != 0:
                    logger.error("[job %d] exited %d:\n%s", job["job_id"],
                                 proc.returncode,
                                 output.decode(errors="replace")[-2000:])
                if duration <= 0 and steps > 0:
                    # Iterator made progress but its duration line is
                    # missing; fall back to wall clock. A (0 steps, 0 s)
                    # report must stay zeroed — it is the scheduler's
                    # micro-task-failure signal (reference:
                    # scheduler.py:4536-4568).
                    duration = elapsed
                if launch_span is not None:
                    self._span_shard.close_span(
                        launch_span, steps=steps,
                        returncode=proc.returncode)
                results.append((job["job_id"], steps, duration, iterator_log))
        finally:
            self._chip_queue.put(chip_id)
        from contextlib import nullcontext
        done_span = (self._span_shard.span(
            obs_names.SPAN_DONE_REPORT, parent=trace_parent,
            round=round_id, worker=worker_id,
            jobs=[r[0] for r in results])
            if self._span_shard is not None else nullcontext())
        try:
            with done_span:
                self._worker_rpc_client.notify_done(
                    job_ids=[r[0] for r in results], worker_id=worker_id,
                    num_steps=[r[1] for r in results],
                    execution_times=[r[2] for r in results],
                    iterator_logs=[r[3] for r in results])
            if self._span_shard is not None:
                self._span_shard.flush()
        except (RpcUnavailableError, grpc.RpcError) as e:
            # The scheduler stayed unreachable through the retry budget
            # — and, under control-plane HA, through the whole failover
            # window too (notify_done holds the report and redelivers
            # to a promoted leader re-resolved from the lease file
            # before this path is reached). Progress is durable in the
            # iterator log / checkpoint; the scheduler's round watchdog
            # synthesizes a failed micro-task and requeues the job, so
            # dropping the report is safe — and far better than a
            # dispatch thread wedged forever.
            logger.error("dropping Done report for jobs %s (round %d): %s",
                         [r[0] for r in results], round_id, e)

    # -- control -----------------------------------------------------------

    def kill_job(self, job_id: int, grace_s: float = 15.0):
        with self._lock:
            proc = self._processes.get(job_id)
        if proc is not None and proc.poll() is None:
            logger.info("killing job %d (pid %d)", job_id, proc.pid)
            # SIGTERM first so the job's handler (train_common.parse_args)
            # can run its finally/atexit cleanup — on relayed TPU backends
            # a SIGKILLed client wedges the chip grant for minutes and
            # every subsequent dispatch hangs behind it.
            try:
                pgid = os.getpgid(proc.pid)
                os.killpg(pgid, signal.SIGTERM)
            except ProcessLookupError:
                return

            def escalate():
                try:
                    proc.wait(timeout=grace_s)
                except subprocess.TimeoutExpired:
                    logger.warning("job %d survived SIGTERM for %.0fs; "
                                   "SIGKILL", job_id, grace_s)
                    try:
                        os.killpg(pgid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    return
                # The group leader exited, but a forked helper (data
                # loader) may have ignored SIGTERM and still hold the
                # chip. Probe the group: killpg(pgid, 0) succeeds iff
                # members remain (the leader's exit is known, so the
                # pgid cannot have been recycled while the group lives —
                # a pgid persists until its last member dies).
                try:
                    os.killpg(pgid, 0)
                except ProcessLookupError:
                    return  # whole group gone: clean exit
                logger.warning("job %d leader exited but group %d has "
                               "survivors; SIGKILL group", job_id, pgid)
                try:
                    os.killpg(pgid, signal.SIGKILL)
                except ProcessLookupError:
                    pass

            # Escalate off-thread: the KillJob RPC handler (and with it the
            # scheduler's _kill_job, which holds its condition variable
            # across the RPC) must not block for the grace window.
            threading.Thread(target=escalate, daemon=True).start()

    def reset(self):
        with self._lock:
            job_ids = list(self._processes)
        for job_id in job_ids:
            self.kill_job(job_id)

    def shutdown(self):
        self._shutdown.set()
        self.reset()
