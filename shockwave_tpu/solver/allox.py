"""AlloX policy: min-cost bipartite matching of jobs to workers.

Builds the AlloX cost matrix q[i, j*k] = k * processing_time(i, j) +
wait_time(i) (a job assigned k-th from the end on a worker delays k jobs)
and solves the assignment with scipy's Hungarian method. Non-preemptive:
previously placed jobs keep their allocation
(reference: scheduler/policies/allox.py).
"""
from __future__ import annotations

import copy

import numpy as np
from scipy.optimize import linear_sum_assignment

from .policy import Policy


class AlloXPolicy(Policy):
    name = "AlloX_Perf"

    def __init__(self, alpha: float = 1.0):
        super().__init__()
        self._alpha = alpha
        self._prev_allocation = {}

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       times_since_start, num_steps_remaining,
                       per_round_schedule, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        job_ids, worker_types = index

        # Jobs holding a full allocation keep it; the rest queue for matching.
        unallocated, held = [], []
        for job_id in unflattened_throughputs:
            prev = self._prev_allocation.get(job_id)
            if prev is not None and sum(prev.values()) == 1.0:
                held.append(job_id)
            else:
                unallocated.append(job_id)

        # Free worker slots (workers not pinned by held jobs).
        slot_types = []
        for wt in worker_types:
            free = cluster_spec[wt] - sum(
                1 for j in held if self._prev_allocation[j][wt] == 1.0)
            slot_types.extend([wt] * free)
        n = len(slot_types)

        unallocated.sort(key=lambda j: -times_since_start[j])
        unallocated = unallocated[:max(int(self._alpha * len(unallocated)), n)]
        m = len(unallocated)

        allocation = {j: {wt: 0.0 for wt in cluster_spec} for j in job_ids}
        for job_id in job_ids:
            if job_id in self._prev_allocation:
                allocation[job_id] = copy.copy(self._prev_allocation[job_id])

        if m > 0 and n > 0:
            proc = np.zeros((m, n))
            for i, job_id in enumerate(unallocated):
                for j, wt in enumerate(slot_types):
                    tput = unflattened_throughputs[job_id][wt] or 1e-10
                    proc[i, j] = num_steps_remaining[job_id] / tput
            # Tile: position k from the end multiplies processing time by k.
            q = np.concatenate([k * proc for k in range(1, m + 1)], axis=1)
            wait = np.tile(
                np.array([[times_since_start[j]] for j in unallocated]), (1, n * m))
            q = q + wait

            rows, cols = linear_sum_assignment(q)
            per_slot = {j: [] for j in range(n)}
            for r, c in zip(rows, cols):
                per_slot[c % n].append((unallocated[r], c // n))
            for slot, entries in per_slot.items():
                if not entries:
                    continue
                # Highest order index = runs first on this slot.
                entries = [(job, len(entries) - 1 - order) for job, order in entries]
                entries.sort(key=lambda e: e[1])
                job_id = entries[0][0]
                allocation[job_id][slot_types[slot]] = 1.0 / scale_factors[job_id]

        self._prev_allocation = copy.copy(allocation)
        return allocation
