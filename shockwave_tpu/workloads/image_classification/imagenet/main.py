#!/usr/bin/env python3
"""ResNet-50 / ImageNet workload (trace: "ResNet-50 (batch size N)").

CLI parity with the reference's imagenet main.py — the trace command is
`python3 main.py -j 4 -a resnet50 -b N %s/imagenet/` with
`--num_minibatches` appended by the dispatcher.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.models import data
from shockwave_tpu.models.resnet import ResNet50
from shockwave_tpu.models.train_common import Trainer, common_parser, parse_args


def main():
    p = common_parser("ResNet-50 on ImageNet", steps_args=("--num_minibatches",))
    p.add_argument("data", nargs="?", default=None)
    p.add_argument("-j", "--workers", type=int, default=4)
    p.add_argument("-a", "--arch", default="resnet50")
    p.add_argument("-b", "--batch_size", type=int, default=64)
    args = parse_args(p)

    model = ResNet50()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init(rng, sample, train=True)
    init_state = {"params": variables["params"],
                  "batch_stats": variables["batch_stats"]}

    def loss_fn(params, state, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": state["batch_stats"]},
            images, train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, {"batch_stats": mutated["batch_stats"]}

    trainer = Trainer(
        args, loss_fn, init_state,
        data.imagenet(args.batch_size, data_dir=args.data),
        initial_bs=args.batch_size, max_bs=128, learning_rate=0.1)
    trainer.run()


if __name__ == "__main__":
    main()
