"""Version compatibility shims for shard_map collective typing.

Newer jax tracks device-variance types through `shard_map`: values that
differ per-device along a mesh axis must be marked so scan carries and
collective operands type-check. The marker has been spelled three ways
across releases:

- jax >= 0.7:  ``lax.pcast(x, axes, to="varying")``
- jax ~ 0.5-0.6: ``lax.pvary(x, axes)``
- older jax (e.g. the 0.4.x line this image ships): neither exists —
  shard_map is untyped there, so no annotation is needed at all and the
  marker degrades to the identity. ``pvary`` is purely a type-system
  hint; on a single-host CPU mesh it lowers to a no-op either way, so
  the identity fallback is a correctness no-op, not an approximation.
"""
from __future__ import annotations

from typing import Sequence, Union

from jax import lax

if hasattr(lax, "pcast"):
    def _mark_varying(x, axes):
        return lax.pcast(x, axes, to="varying")
elif hasattr(lax, "pvary"):
    def _mark_varying(x, axes):
        return lax.pvary(x, axes)
else:  # pre-varying-types jax: untyped shard_map needs no marker
    def _mark_varying(x, axes):
        return x


def to_varying(x, axes: Union[str, Sequence[str]]):
    """Mark `x` device-varying over mesh `axes` (string or sequence),
    degrading to the identity on jax versions whose shard_map has no
    variance typing (see module docstring)."""
    return _mark_varying(x, axes)


__all__ = ["to_varying"]
