#!/usr/bin/env python3
"""Cold-start scheduling study on a mixed two-generation cluster.

The acceptance methodology for the learned throughput oracle
(shockwave_tpu/oracle/, README "Learned throughput oracle"):

1. **Derive a mixed-generation truth table.** The committed v5e
   profile (data/v5e_throughputs.json) becomes the ``v5-lite`` rates;
   the newer ``v5`` generation is derived analytically: SPEEDUP x the
   single-chip rate, scaled by the v5-lite key's relative multi-chip
   efficiency raised to COMM_EXPONENT < 1 — the newer interconnect
   loses less to communication at the same scale factor (the
   generation-specific comm-scaling term the oracle's feature vector
   carries).
2. **Fabricate a training history** (an obs/history.py payload):
   noisy observations of every profiled family on both generations —
   except the COLD family, which appears only at scale factor 1 on
   ``v5-lite`` (the "one staging run" story). Train the model with
   ``python -m shockwave_tpu.oracle.train``.
3. **Phase A (baseline):** simulate the trace with the FULL truth
   table as the profiled oracle, learned oracle disabled — every job's
   rate is known exactly. Per-job JCTs are the reference.
4. **Phase B (cold start):** the scheduler sees the truth table MINUS
   every cold-family key; the oracle chain predicts the cold jobs'
   rates (learned provenance), the sim executes them at the held-out
   TRUTH rate (``truth_file``), and the planning view converges
   online from observed completions.
5. **Gate:** every cold job's phase-B JCT must land within
   --envelope (default 15%) of its phase-A JCT.

Everything is a pure function of --seed: the artifacts under
--out (reproduce/oracle/) are byte-reproducible and cmp'd in CI.
Exits nonzero when the envelope is violated.
"""
import argparse
import copy
import json
import os
import random
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.constants import DEFAULT_BS  # noqa: E402
from shockwave_tpu.core.job import Job, JobIdPair  # noqa: E402
from shockwave_tpu.core.oracle import (read_throughputs,  # noqa: E402
                                       write_throughputs)
from shockwave_tpu.obs import names as obs_names  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402
from shockwave_tpu.oracle import train as oracle_train  # noqa: E402

import driver_common  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

#: The two generations of the study cluster. v5-lite carries the
#: committed v5e rates verbatim; v5 is derived (see module docstring).
LITE, NEW = "v5-lite", "v5"
SPEEDUP = 2.25        # v5 single-chip rate multiple of v5-lite
COMM_EXPONENT = 0.6   # v5 keeps more of its scaling efficiency

#: The never-profiled family: held out of the scheduler-visible table
#: and of the training history except scale-factor-1 rows on v5-lite.
COLD_FAMILY = "ResNet-50"


def batch_size_of(job_type: str) -> int:
    m = re.search(r"batch size (\d+)\)", job_type)
    if m is not None:
        return int(m.group(1))
    return DEFAULT_BS[job_type.split(" ", 1)[0]]


def derive_truth(lite_table: dict) -> dict:
    """{worker_type: {(job_type, sf): {"null": rate}}} for both
    generations. No __meta__ key: the study must not flip the
    scheduler's deployment-faithful round mechanics."""
    truth = {LITE: {}, NEW: {}}
    for key in sorted(lite_table):
        job_type, sf = key
        rate = float(lite_table[key]["null"])
        truth[LITE][key] = {"null": rate}
        base = float(lite_table.get((job_type, 1), {}).get("null", 0.0))
        if rate <= 0.0 or base <= 0.0:
            truth[NEW][key] = {"null": 0.0}
            continue
        rel_eff = rate / (sf * base)
        truth[NEW][key] = {
            "null": round(SPEEDUP * base * sf * rel_eff ** COMM_EXPONENT, 4)}
    return truth


def fabricate_history(truth: dict, seed: int) -> dict:
    """An obs/history.py payload whose observation rows cover every
    warm family on both generations, and the cold family ONLY at scale
    factor 1 on v5-lite."""
    rng = random.Random(seed + 17)
    rows = []
    rnd = 0
    for wt in (LITE, NEW):
        for key in sorted(truth[wt]):
            job_type, sf = key
            rate = truth[wt][key]["null"]
            if rate <= 0.0:
                continue
            cold = job_type.split(" ", 1)[0] == COLD_FAMILY
            if cold and (wt != LITE or sf != 1):
                continue
            for _ in range(2):
                rnd += 1
                noisy = round(rate * rng.lognormvariate(0.0, 0.03), 6)
                rows.append([rnd, job_type, batch_size_of(job_type),
                             int(sf), wt, noisy])
    return {"schema": 1, "observations_schema": 1, "rounds": [],
            "observations": rows, "serving": [], "alerts": {}}


def build_trace(truth: dict, seed: int, num_jobs: int,
                cold_positions: tuple):
    """Deterministic trace: `num_jobs` jobs, the cold-family ones at
    `cold_positions` (mid-trace). Durations are the job's ISOLATED
    v5-lite runtime (steps = duration x v5-lite rate), so phase-A JCTs
    are queueing + contention on top of a known floor."""
    rng = random.Random(seed)
    warm = sorted(
        key for key, entry in truth[LITE].items()
        if entry["null"] > 0.0 and key[1] in (1, 2, 4)
        and key[0].split(" ", 1)[0] != COLD_FAMILY)
    cold = sorted(
        key for key, entry in truth[LITE].items()
        if entry["null"] > 0.0 and key[1] in (1, 2, 4)
        and key[0].split(" ", 1)[0] == COLD_FAMILY)
    jobs, arrivals, t = [], [], 0.0
    for i in range(num_jobs):
        job_type, sf = (rng.choice(cold) if i in cold_positions
                        else rng.choice(warm))
        duration = float(round(rng.uniform(1800.0, 7200.0)))
        steps = int(duration * truth[LITE][(job_type, sf)]["null"])
        assert steps > 0
        jobs.append(Job(
            job_id=None, job_type=job_type,
            command=f"python train.py --model {job_type.split(' ', 1)[0]} "
                    f"{batch_size_of(job_type)}",
            total_steps=steps, duration=duration, scale_factor=sf,
            mode="static"))
        arrivals.append(round(t, 2))
        t += rng.expovariate(1.0 / 240.0)
    return jobs, arrivals


def run_phase(jobs, arrivals, cluster_spec, throughputs_file, *,
              policy: str, round_duration: float, seed: int,
              oracle_config=None):
    sched = driver_common.build_scheduler(
        policy, throughputs_file, None, round_duration=round_duration,
        seed=seed, oracle_config=oracle_config)
    makespan = sched.simulate(dict(cluster_spec), list(arrivals),
                              copy.deepcopy(jobs))
    jcts = {}
    for i in range(len(jobs)):
        jcts[i] = sched.acct.completion_times.get(JobIdPair(i))
    reg = sched._obs.registry
    counters = {
        "predictions_profiled": reg.value(
            obs_names.ORACLE_PREDICTIONS_TOTAL, provenance="profiled"),
        "predictions_learned": reg.value(
            obs_names.ORACLE_PREDICTIONS_TOTAL, provenance="learned"),
        "predictions_prior": reg.value(
            obs_names.ORACLE_PREDICTIONS_TOTAL, provenance="prior"),
        "online_updates": reg.value(
            obs_names.ORACLE_ONLINE_UPDATES_TOTAL),
    }
    return makespan, jcts, counters


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(REPO, "reproduce/oracle"))
    p.add_argument("--throughputs",
                   default=os.path.join(REPO, "data/v5e_throughputs.json"))
    p.add_argument("--policy", default="max_min_fairness_perf")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--num_jobs", type=int, default=16)
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--lite_chips", type=int, default=8)
    p.add_argument("--new_chips", type=int, default=8)
    p.add_argument("--min_confidence", type=float, default=0.3)
    p.add_argument("--envelope", type=float, default=0.15,
                   help="max per-cold-job |JCT_B - JCT_A| / JCT_A")
    args = p.parse_args(argv)
    setup_logging("warning")
    os.makedirs(args.out, exist_ok=True)

    lite_table = read_throughputs(args.throughputs)["v5e"]
    truth = derive_truth(lite_table)
    truth_path = os.path.join(args.out, "truth_mixed.json")
    write_throughputs(truth_path, truth)

    visible = {
        wt: {key: entry for key, entry in sorted(per_type.items())
             if key[0].split(" ", 1)[0] != COLD_FAMILY}
        for wt, per_type in truth.items()}
    visible_path = os.path.join(args.out, "profiled_minus_cold.json")
    write_throughputs(visible_path, visible)

    history = fabricate_history(truth, args.seed)
    history_path = os.path.join(args.out, "history_train.json")
    with open(history_path, "w") as f:
        json.dump(history, f, sort_keys=True, indent=2)
        f.write("\n")

    model_path = os.path.join(args.out, "model.json")
    rc = oracle_train.main(["--history", history_path,
                            "--out", model_path,
                            "--seed", str(args.seed)])
    if rc != 0:
        return rc

    cold_positions = (args.num_jobs // 2,
                      args.num_jobs // 2 + 3,
                      args.num_jobs - 2)
    jobs, arrivals = build_trace(truth, args.seed, args.num_jobs,
                                 cold_positions)
    cluster_spec = {LITE: args.lite_chips, NEW: args.new_chips}

    makespan_a, jct_a, _ = run_phase(
        jobs, arrivals, cluster_spec, truth_path, policy=args.policy,
        round_duration=args.round_duration, seed=args.seed)
    makespan_b, jct_b, counters = run_phase(
        jobs, arrivals, cluster_spec, visible_path, policy=args.policy,
        round_duration=args.round_duration, seed=args.seed,
        oracle_config={"model": model_path,
                       "min_confidence": args.min_confidence,
                       "truth_file": truth_path})

    per_job, worst = [], 0.0
    for i, job in enumerate(jobs):
        a, b = jct_a[i], jct_b[i]
        rel = (abs(b - a) / a if a and b else None)
        cold = i in cold_positions
        if cold and rel is not None:
            worst = max(worst, rel)
        per_job.append({
            "id": i, "job_type": job.job_type,
            "scale_factor": job.scale_factor,
            "duration_s": job.duration,
            "arrival_s": arrivals[i],
            "cold": cold,
            "jct_baseline_s": round(a, 2) if a else None,
            "jct_coldstart_s": round(b, 2) if b else None,
            "rel_delta": round(rel, 4) if rel is not None else None,
        })
    within = worst <= args.envelope
    result = {
        "meta": {
            "seed": args.seed, "num_jobs": args.num_jobs,
            "policy": args.policy,
            "round_duration_s": args.round_duration,
            "cluster_spec": cluster_spec,
            "cold_family": COLD_FAMILY,
            "cold_positions": list(cold_positions),
            "v5_speedup": SPEEDUP, "comm_exponent": COMM_EXPONENT,
            "min_confidence": args.min_confidence,
            "envelope": args.envelope,
        },
        "makespan_baseline_s": round(makespan_a, 2),
        "makespan_coldstart_s": round(makespan_b, 2),
        "oracle_counters": counters,
        "cold_start": {"max_rel_delta": round(worst, 4),
                       "within_envelope": within},
        "jobs": per_job,
    }
    result_path = os.path.join(args.out, "coldstart_mixed_study.json")
    with open(result_path, "w") as f:
        json.dump(result, f, sort_keys=True, indent=2)
        f.write("\n")
    print(json.dumps({
        "makespan_baseline_s": result["makespan_baseline_s"],
        "makespan_coldstart_s": result["makespan_coldstart_s"],
        "max_cold_rel_delta": result["cold_start"]["max_rel_delta"],
        "within_envelope": within,
        "out": result_path}, sort_keys=True))
    return 0 if within else 1


if __name__ == "__main__":
    sys.exit(main())
