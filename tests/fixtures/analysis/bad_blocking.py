"""Seeded violations for the hold-discipline pass: a gRPC stub call
and a time.sleep, both inside the spawned thread's critical section —
every other thread wanting the lock stalls behind the network/sleep.
One finding per (function, kind), each anchored at its blocking line."""
import threading
import time


class BlockyDispatcher:
    def __init__(self, stub):
        self._lock = threading.Lock()
        self._stub = stub
        self._sent = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._stub.run_job("job")  # SEEDED
                time.sleep(0.1)  # SEEDED
                self._sent += 1
