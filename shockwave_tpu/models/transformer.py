"""Encoder-decoder Transformer for translation (Multi30k-class workloads).

Standard pre-LN Transformer with tied output projection (the reference
trains "Attention is All You Need" on multi30k with -proj_share_weight;
workloads/pytorch/translation/train.py). TPU-native choices: bf16
activations, static sequence lengths, einsum attention that XLA maps to
the MXU, and an optional ring-attention path (parallel/ring_attention.py)
for sequence-parallel long-context runs.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def sinusoidal_positions(length: int, dim: int) -> np.ndarray:
    pos = np.arange(length)[:, None]
    div = np.exp(np.arange(0, dim, 2) / dim * -np.log(10000.0))
    table = np.zeros((length, dim), dtype=np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    return table


class MultiHeadAttention(nn.Module):
    num_heads: int
    dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, q_in, kv_in, mask: Optional[jnp.ndarray] = None):
        head_dim = self.dim // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype, name=name)
        q = dense("query")(q_in)
        k = dense("key")(kv_in)
        v = dense("value")(kv_in)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(head_dim)
        if mask is not None:
            scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        weights = nn.softmax(scores.astype(jnp.float32)).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        return nn.DenseGeneral(self.dim, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class TransformerLayer(nn.Module):
    num_heads: int
    dim: int
    mlp_dim: int
    decoder: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, enc_out=None, self_mask=None, cross_mask=None):
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + MultiHeadAttention(self.num_heads, self.dim, self.dtype,
                                   name="self_attn")(y, y, self_mask)
        if self.decoder:
            y = nn.LayerNorm(dtype=jnp.float32)(x)
            x = x + MultiHeadAttention(self.num_heads, self.dim, self.dtype,
                                       name="cross_attn")(y, enc_out, cross_mask)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype)(y)
        return x + y


class Seq2SeqTransformer(nn.Module):
    vocab_size: int = 9521  # multi30k shared vocab size ballpark
    dim: int = 512
    num_heads: int = 8
    num_layers: int = 6
    mlp_dim: int = 2048
    max_len: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, src_tokens, tgt_tokens):
        embed = nn.Embed(self.vocab_size, self.dim,
                         embedding_init=nn.initializers.normal(0.02),
                         name="shared_embedding")
        positions = jnp.asarray(sinusoidal_positions(self.max_len, self.dim))

        src = embed(src_tokens).astype(self.dtype)
        src = src + positions[: src_tokens.shape[1]]
        src_mask = (src_tokens != 0)[:, None, None, :]
        for i in range(self.num_layers):
            src = TransformerLayer(self.num_heads, self.dim, self.mlp_dim,
                                   dtype=self.dtype, name=f"enc_{i}")(
                src, self_mask=src_mask)
        src = nn.LayerNorm(dtype=jnp.float32, name="enc_norm")(src)

        tgt = embed(tgt_tokens).astype(self.dtype)
        tgt = tgt + positions[: tgt_tokens.shape[1]]
        tgt_len = tgt_tokens.shape[1]
        causal = jnp.tril(jnp.ones((tgt_len, tgt_len), bool))[None, None]
        tgt_mask = causal & (tgt_tokens != 0)[:, None, None, :]
        for i in range(self.num_layers):
            tgt = TransformerLayer(self.num_heads, self.dim, self.mlp_dim,
                                   decoder=True, dtype=self.dtype,
                                   name=f"dec_{i}")(
                tgt, enc_out=src, self_mask=tgt_mask, cross_mask=src_mask)
        tgt = nn.LayerNorm(dtype=jnp.float32, name="dec_norm")(tgt)
        # Tied output projection (-proj_share_weight).
        logits = jnp.einsum("bld,vd->blv", tgt.astype(jnp.float32),
                            embed.embedding.astype(jnp.float32))
        return logits
