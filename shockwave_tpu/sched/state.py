"""Scheduler bookkeeping state, grouped by concern.

The reference keeps ~60 ad-hoc dicts on one object
(scheduler.py:84-484); here the per-job accounting lives in one dataclass
per concern so invariants are visible.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core.job import Job, JobIdPair


@dataclass
class WorkerState:
    """Registry of workers (one entry per accelerator chip)."""

    worker_ids: List[int] = field(default_factory=list)
    worker_types: Set[str] = field(default_factory=set)
    id_to_type: Dict[int, str] = field(default_factory=dict)
    # worker_type -> list of per-server lists of chip ids (for strided
    # assignment that minimizes the number of servers a job spans).
    type_to_server_ids: Dict[str, List[List[int]]] = field(default_factory=dict)
    cluster_spec: Dict[str, int] = field(default_factory=dict)
    start_times: Dict[int, float] = field(default_factory=dict)
    cumulative_time: Dict[int, float] = field(default_factory=dict)
    next_worker_id: int = 0
    # -- liveness (physical mode; always empty in simulation) ----------
    # Chips whose daemon is presumed dead: removed from capacity and
    # from sticky placement, retained in id_to_type so historical
    # accounting (run time, utilization) stays resolvable. A rejoining
    # daemon revives its ids (idempotent RegisterWorker).
    dead: Set[int] = field(default_factory=set)
    # Last time each chip's daemon was heard from — stamped at
    # registration and piggybacked on every Done / UpdateLease RPC.
    last_seen: Dict[int, float] = field(default_factory=dict)
    # Chips held out of capacity by the gray-failure layer: the daemon
    # is ALIVE (it answers Ping and renews leases) but its host was
    # classified degraded — thermal throttling, flaky interconnect,
    # slow disk — so its chips must not anchor another round. Invariant:
    # quarantined is a subset of dead (quarantine removes capacity
    # through the same deregister path); the marker distinguishes
    # "alive, probed, will be released on probation" from "presumed
    # dead, revived only by rejoin/heal". revive_workers clears the
    # marker for any id it readmits.
    quarantined: Set[int] = field(default_factory=set)


@dataclass
class JobAccounting:
    """Per-job progress and fair-share accounting."""

    jobs: Dict[JobIdPair, Job] = field(default_factory=dict)
    # steps run per worker type and in total (adaptation rescales these).
    steps_run: Dict[JobIdPair, Dict[str, int]] = field(default_factory=dict)
    total_steps_run: Dict[JobIdPair, int] = field(default_factory=dict)
    # wall-clock run time per job per worker id (for deadline enforcement).
    run_time_per_worker: Dict[JobIdPair, Dict[int, float]] = field(default_factory=dict)
    # time accounting since the last fair-share reset.
    job_time: Dict[JobIdPair, Dict[str, float]] = field(default_factory=dict)
    worker_type_time: Dict[str, float] = field(default_factory=dict)
    # lifecycle timestamps and outcomes.
    start_timestamps: Dict[JobIdPair, float] = field(default_factory=dict)
    latest_timestamps: Dict[JobIdPair, Optional[float]] = field(default_factory=dict)
    completion_times: Dict[JobIdPair, Optional[float]] = field(default_factory=dict)
    priority_weights_archive: Dict[JobIdPair, float] = field(default_factory=dict)
    failures: Dict[JobIdPair, int] = field(default_factory=dict)
    # original (pre-adaptation) shape of each job.
    original_bs: Dict[JobIdPair, int] = field(default_factory=dict)
    original_num_steps: Dict[JobIdPair, int] = field(default_factory=dict)
    original_job_type: Dict[JobIdPair, str] = field(default_factory=dict)


@dataclass
class RoundState:
    """State of the round-based mechanism."""

    current_assignments: "collections.OrderedDict[JobIdPair, Tuple[int, ...]]" = field(
        default_factory=collections.OrderedDict)
    next_assignments: Optional[dict] = None
    completed_in_round: Set[JobIdPair] = field(default_factory=set)
    extended_leases: Set[JobIdPair] = field(default_factory=set)
    num_lease_extensions: int = 0
    num_lease_opportunities: int = 0
    num_completed_rounds: int = 0
    per_round_schedule: List[dict] = field(default_factory=list)
    jobs_in_round: List[int] = field(default_factory=list)
    job_start_round: Dict[int, int] = field(default_factory=dict)
    job_end_round: Dict[int, int] = field(default_factory=dict)
    num_scheduled_rounds: Dict[int, int] = field(default_factory=dict)
    num_queued_rounds: Dict[int, int] = field(default_factory=dict)

    def abandon_in_flight(self) -> None:
        """Drop every in-flight round structure, keeping history.

        Crash recovery re-plans the round from scratch: assignments and
        leases referenced workers/processes the restarted scheduler no
        longer controls, while the per-round history (schedules, counts,
        start/end rounds) stays valid and is preserved.
        """
        self.current_assignments = collections.OrderedDict()
        self.next_assignments = None
        self.completed_in_round = set()
        self.extended_leases = set()
