#!/usr/bin/env python3
"""Generate the committed mixed serving+training trace.

Takes the first N training jobs of the canonical 120-job trace verbatim
(arrivals kept) and appends latency-SLO serving services:

- service A (arrives at t=0, 4 h lifetime): diurnal 8->16 req/s with a
  seeded 10x spike — the SLO-attainment-under-burst scenario of
  EXPERIMENTS.md "Serving tier".
- service B (arrives at t=1800, 3 h lifetime): trough-starting 0->6
  req/s curve — exercises scale-to-zero.

Deterministic; rerun after changing parameters and commit the result:

    python scripts/utils/make_serving_trace.py > data/serving_mixed.trace
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.trace import job_to_trace_line, make_serving_job

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
CANONICAL = os.path.join(REPO, "data", "canonical_120job.trace")
NUM_TRAINING_JOBS = 10


def main():
    with open(CANONICAL) as f:
        lines = [next(f).rstrip("\n") for _ in range(NUM_TRAINING_JOBS)]

    service_a = make_serving_job(
        base_rps=8.0, peak_rps=16.0, period_s=14400.0, lifetime_s=14400.0,
        slo_p99_s=0.5, tokens_per_request=64, decode_tokens_per_s=1600.0,
        max_replicas=12, spike_seed=7, num_spikes=1, spike_mult=10.0,
        spike_duration_s=1800.0)
    # Period = 2x lifetime: the service lives through the curve's rise
    # from a true trough (several rounds under the scale-to-zero
    # threshold) to its peak.
    service_b = make_serving_job(
        base_rps=0.0, peak_rps=6.0, period_s=21600.0, lifetime_s=10800.0,
        slo_p99_s=1.0, tokens_per_request=64, decode_tokens_per_s=1600.0,
        max_replicas=4)
    lines.append(job_to_trace_line(service_a, 0.0))
    lines.append(job_to_trace_line(service_b, 1800.0))
    # simulate() admits in file order gated on the head arrival, and
    # job ids / the positional profiles list follow file order — the
    # trace MUST be arrival-sorted or late lines are admitted late.
    lines.sort(key=lambda line: float(line.rsplit("\t", 1)[1]))
    print("\n".join(lines))


if __name__ == "__main__":
    main()
