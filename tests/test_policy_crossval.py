"""Randomized per-policy allocation cross-validation.

Counterpart of the reference's solver-equivalence harness
(reference: scheduler/scripts/tests/solver.py:230-285): random job sets
and clusters, with every policy's allocation checked two ways —

1. feasibility invariants (nonnegative, per-job time <= 1, per-type
   worker-seconds within capacity) for all registry policies, and
2. for the max-min family (incl. the water-filling probe-LP redesign,
   250 LoC replacing the reference's 718), the achieved fairness
   objective is compared against an INDEPENDENT optimum computed here
   with scipy.optimize.linprog from a from-scratch formulation sharing
   no code with solver/lp.py — so a compensating-errors bug in the
   in-repo LP stack shows up as an objective gap, which end-to-end
   trace parity cannot detect.

Instances are seeded; throughputs are real oracle rows over the
heterogeneous {v100, p100, k80} cluster types.
"""
import json
import os
import re

import numpy as np
import pytest
from scipy.optimize import linprog

from shockwave_tpu.core.job import JobIdPair
from shockwave_tpu.solver import get_policy

DATA = os.path.join(os.path.dirname(__file__), "..", "data")
WORKER_TYPES = ["v100", "p100", "k80"]

# Policies whose allocation must satisfy the feasibility invariants.
FEASIBILITY_POLICIES = [
    "isolated", "proportional", "gandiva_fair", "max_min_fairness",
    "max_min_fairness_perf", "max_min_fairness_water_filling",
    "max_min_fairness_water_filling_perf", "max_sum_throughput_perf",
    "min_total_duration", "min_total_duration_perf",
    "finish_time_fairness", "finish_time_fairness_perf",
]


def load_oracle_rates():
    """{(job_type, sf): {worker_type: rate}} from the reference oracle,
    keeping only rows measured (> 0) on all three cluster types."""
    with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
        raw = json.load(f)
    rates = {}
    for key_str, entry in raw["v100"].items():
        m = re.match(r"\('(.*)', (\d+)\)", key_str)
        if not m:
            continue
        key = (m.group(1), int(m.group(2)))
        per_wt = {}
        for wt in WORKER_TYPES:
            r = raw.get(wt, {}).get(key_str, {}).get("null", 0.0)
            if r and r > 0:
                per_wt[wt] = r
        if len(per_wt) == len(WORKER_TYPES):
            rates[key] = per_wt
    return rates


ORACLE_RATES = load_oracle_rates()


def random_instance(seed):
    """A seeded random (jobs, throughputs, sfs, priorities, cluster)."""
    rng = np.random.RandomState(seed)
    keys = sorted(ORACLE_RATES)
    m = int(rng.randint(4, 11))
    job_ids = [JobIdPair(i) for i in range(m)]
    throughputs, sfs, priorities = {}, {}, {}
    for j in job_ids:
        key = keys[rng.randint(len(keys))]
        throughputs[j] = dict(ORACLE_RATES[key])
        sfs[j] = key[1]
        priorities[j] = float(rng.choice([1.0, 2.0]))
    cluster = {wt: int(rng.randint(4, 13)) for wt in WORKER_TYPES}
    return job_ids, throughputs, sfs, priorities, cluster


def check_feasible(alloc, job_ids, sfs, cluster, tol=1e-4, capacity=True):
    """capacity=False for the closed-form share baselines (proportional,
    gandiva_fair): like the reference's, they are time-share normalizers
    that ignore scale factors — worker-seconds capacity with sf > 1 is
    the round scheduler's job, not theirs."""
    assert alloc is not None
    used = {wt: 0.0 for wt in cluster}
    for j in job_ids:
        row_sum = 0.0
        for wt, x in alloc[j].items():
            assert x >= -tol, (j, wt, x)
            row_sum += x
            used[wt] += x * sfs[j]
        assert row_sum <= 1.0 + tol, (j, row_sum)
    if capacity:
        for wt in cluster:
            assert used[wt] <= cluster[wt] + tol, (wt, used[wt], cluster[wt])


def normalizers(job_ids, throughputs, priorities, cluster):
    """Reference-spec proportional-share normalizer: every job's
    effective throughput under the equal split x_w = c_w / sum(c)
    (reference: policies/proportional.py), scaled by priority."""
    total = sum(cluster.values())
    prop = {
        j: sum(throughputs[j][wt] * cluster[wt] / total for wt in cluster)
        for j in job_ids}
    return {j: priorities[j] * prop[j] for j in job_ids}


def achieved_min_ratio(alloc, job_ids, throughputs, sfs, norm):
    return min(
        sum(throughputs[j][wt] * alloc[j].get(wt, 0.0) for wt in
            throughputs[j]) * sfs[j] / norm[j]
        for j in job_ids)


def time_and_capacity_rows(job_ids, sfs, cluster, nv):
    """Shared feasibility rows for every independent formulation:
    per-job time <= 1 and per-type worker-seconds capacity, over
    x[j, w] row-major in an nv-variable LP."""
    m, n = len(job_ids), len(WORKER_TYPES)
    A_ub, b_ub = [], []
    for i in range(m):
        row = np.zeros(nv)
        row[i * n:(i + 1) * n] = 1.0
        A_ub.append(row)
        b_ub.append(1.0)
    for w, wt in enumerate(WORKER_TYPES):
        row = np.zeros(nv)
        for i, j in enumerate(job_ids):
            row[i * n + w] = sfs[j]
        A_ub.append(row)
        b_ub.append(float(cluster[wt]))
    return A_ub, b_ub


def independent_max_min_optimum(job_ids, throughputs, sfs, norm, cluster):
    """From-scratch LP: maximize t s.t. per-job normalized effective
    throughput >= t, per-job time <= 1, per-type capacity in
    worker-seconds. Variables: x[j, w] row-major, then t."""
    m, n = len(job_ids), len(WORKER_TYPES)
    nv = m * n + 1
    A_ub, b_ub = time_and_capacity_rows(job_ids, sfs, cluster, nv)
    for i, j in enumerate(job_ids):
        row = np.zeros(nv)
        for w, wt in enumerate(WORKER_TYPES):
            row[i * n + w] = -throughputs[j][wt] * sfs[j] / norm[j]
        row[-1] = 1.0
        A_ub.append(row)
        b_ub.append(0.0)
    c = np.zeros(nv)
    c[-1] = -1.0
    bounds = [(0.0, 1.0)] * (m * n) + [(None, None)]
    res = linprog(c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                  bounds=bounds, method="highs")
    assert res.status == 0, res.message
    return -res.fun


class TestFeasibilityInvariants:
    @pytest.mark.parametrize("seed", range(5))
    @pytest.mark.parametrize("policy_name", FEASIBILITY_POLICIES)
    def test_allocation_feasible(self, policy_name, seed):
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        policy = get_policy(policy_name, seed=0)
        capacity = True
        if policy_name == "proportional":
            alloc = policy.get_allocation(tputs, cluster)
            capacity = False
        elif policy_name == "gandiva_fair":
            alloc = policy.get_allocation(tputs, sfs, cluster)
            capacity = False
        elif policy_name == "isolated":
            alloc = policy.get_allocation(tputs, sfs, cluster)
        elif policy_name == "max_sum_throughput_perf":
            alloc = policy.get_allocation(tputs, sfs, cluster)
        elif policy_name.startswith("min_total_duration"):
            num_steps = {j: 10000.0 for j in job_ids}
            alloc = policy.get_allocation(tputs, sfs, num_steps, cluster)
        elif policy_name.startswith("finish_time_fairness"):
            times = {j: 100.0 for j in job_ids}
            steps = {j: 10000.0 for j in job_ids}
            alloc = policy.get_allocation(
                tputs, sfs, prios, times, steps, cluster)
        else:
            alloc = policy.get_allocation(tputs, sfs, prios, cluster)
        check_feasible(alloc, job_ids, sfs, cluster, capacity=capacity)


class TestMaxMinOptimality:
    """The in-repo LP stack's max-min optimum must match the independent
    scipy formulation on every random instance."""

    @pytest.mark.parametrize("seed", range(5))
    def test_perf_policy_is_optimal(self, seed):
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        alloc = get_policy("max_min_fairness_perf").get_allocation(
            tputs, sfs, prios, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        norm = normalizers(job_ids, tputs, prios, cluster)
        got = achieved_min_ratio(alloc, job_ids, tputs, sfs, norm)
        want = independent_max_min_optimum(job_ids, tputs, sfs, norm,
                                           cluster)
        assert got == pytest.approx(want, rel=1e-3)

    @pytest.mark.parametrize("seed", range(5))
    def test_throughput_agnostic_policy_is_optimal(self, seed):
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        ones = {j: {wt: 1.0 for wt in tputs[j]} for j in job_ids}
        alloc = get_policy("max_min_fairness").get_allocation(
            tputs, sfs, prios, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        norm = normalizers(job_ids, ones, prios, cluster)
        got = achieved_min_ratio(alloc, job_ids, ones, sfs, norm)
        want = independent_max_min_optimum(job_ids, ones, sfs, norm,
                                           cluster)
        assert got == pytest.approx(want, rel=1e-3)

    @pytest.mark.parametrize("seed", range(5))
    def test_water_filling_first_level_is_optimal(self, seed):
        """The water-filling probe-LP redesign must be max-min optimal
        at its first level: its worst-off job does exactly as well as
        the single-level LP optimum allows."""
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        alloc = get_policy(
            "max_min_fairness_water_filling_perf").get_allocation(
            tputs, sfs, prios, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        norm = normalizers(job_ids, tputs, prios, cluster)
        got = achieved_min_ratio(alloc, job_ids, tputs, sfs, norm)
        want = independent_max_min_optimum(job_ids, tputs, sfs, norm,
                                           cluster)
        assert got == pytest.approx(want, rel=5e-3)


class TestFinishTimeFairnessOptimality:
    """Themis minimizes the max finish-time-fairness ratio rho; compare
    the achieved rho against an independent scipy bisection over
    feasibility LPs (formula shared, code not)."""

    def _independent_iso_tput(self, job_ids, tputs, sfs, cluster):
        # Reference-spec isolated share: c_w/m workers of each type,
        # scaled by 1/sf, row capped at a full time share.
        m = len(job_ids)
        iso = {}
        for j in job_ids:
            x = {wt: cluster[wt] / m / sfs[j] for wt in WORKER_TYPES}
            row = sum(x.values())
            if row > 1.0:
                x = {wt: v / row for wt, v in x.items()}
            iso[j] = sum(tputs[j][wt] * x[wt] for wt in WORKER_TYPES)
        return iso

    def _independent_min_rho(self, job_ids, tputs, sfs, steps, iso_time,
                             cluster):
        m, n = len(job_ids), len(WORKER_TYPES)

        def feasible(rho):
            A_ub, b_ub = time_and_capacity_rows(job_ids, sfs, cluster, m * n)
            for i, j in enumerate(job_ids):
                row = np.zeros(m * n)
                for w, wt in enumerate(WORKER_TYPES):
                    row[i * n + w] = -tputs[j][wt]
                A_ub.append(row)
                b_ub.append(-steps[j] / (rho * iso_time[j]))
            res = linprog(np.zeros(m * n), A_ub=np.array(A_ub),
                          b_ub=np.array(b_ub),
                          bounds=[(0.0, 1.0)] * (m * n), method="highs")
            return res.status == 0

        lo, hi = 1e-3, 10.0
        while not feasible(hi) and hi < 1e7:
            lo, hi = hi, hi * 10
        while hi > lo * 1.01:
            mid = (lo + hi) / 2
            if feasible(mid):
                hi = mid
            else:
                lo = mid
        return hi

    @pytest.mark.parametrize("seed", range(5))
    def test_fresh_jobs_min_max_rho_matches(self, seed):
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        steps = {j: 10000.0 for j in job_ids}
        times = {j: 0.0 for j in job_ids}
        alloc = get_policy("finish_time_fairness_perf").get_allocation(
            tputs, sfs, prios, times, steps, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        iso = self._independent_iso_tput(job_ids, tputs, sfs, cluster)
        iso_time = {j: steps[j] / iso[j] for j in job_ids}
        achieved = max(
            steps[j] / max(sum(tputs[j][wt] * alloc[j].get(wt, 0.0)
                               for wt in WORKER_TYPES), 1e-12) / iso_time[j]
            for j in job_ids)
        want = self._independent_min_rho(job_ids, tputs, sfs, steps,
                                        iso_time, cluster)
        # Both sides bisect to ~1%; allow the combined tolerance.
        assert achieved == pytest.approx(want, rel=0.05)


class TestMinTotalDurationOptimality:
    """OSSP minimizes the makespan horizon T via binary search on
    feasibility LPs; compare the achieved horizon against an
    independent scipy bisection."""

    def _independent_min_T(self, job_ids, tputs, sfs, steps, cluster):
        m, n = len(job_ids), len(WORKER_TYPES)

        def feasible(T):
            A_ub, b_ub = time_and_capacity_rows(job_ids, sfs, cluster, m * n)
            for i, j in enumerate(job_ids):
                row = np.zeros(m * n)
                for w, wt in enumerate(WORKER_TYPES):
                    row[i * n + w] = -tputs[j][wt]
                A_ub.append(row)
                b_ub.append(-steps[j] / T)
            res = linprog(np.zeros(m * n), A_ub=np.array(A_ub),
                          b_ub=np.array(b_ub),
                          bounds=[(0.0, 1.0)] * (m * n), method="highs")
            return res.status == 0

        lo, hi = 1.0, 1e6
        while not feasible(hi):
            lo, hi = hi, hi * 10
        while hi > lo * 1.01:
            mid = (lo + hi) / 2
            if feasible(mid):
                hi = mid
            else:
                lo = mid
        return hi

    @pytest.mark.parametrize("seed", range(5))
    def test_achieved_horizon_matches_independent(self, seed):
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        steps = {j: 10000.0 for j in job_ids}
        alloc = get_policy("min_total_duration_perf").get_allocation(
            tputs, sfs, steps, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        achieved = max(
            steps[j] / max(sum(tputs[j][wt] * alloc[j].get(wt, 0.0)
                               for wt in WORKER_TYPES), 1e-12)
            for j in job_ids)
        want = self._independent_min_T(job_ids, tputs, sfs, steps, cluster)
        # The policy bisects to 5%, the independent side to 1%.
        assert achieved == pytest.approx(want, rel=0.08)


class TestMaxSumThroughputOptimality:
    @pytest.mark.parametrize("seed", range(5))
    def test_total_effective_throughput_is_optimal(self, seed):
        """max_sum_throughput_perf maximizes total effective throughput;
        compare against the independent LP optimum of that objective."""
        job_ids, tputs, sfs, prios, cluster = random_instance(seed)
        alloc = get_policy("max_sum_throughput_perf").get_allocation(
            tputs, sfs, cluster)
        check_feasible(alloc, job_ids, sfs, cluster)
        m, n = len(job_ids), len(WORKER_TYPES)
        c = np.zeros(m * n)
        for i, j in enumerate(job_ids):
            for w, wt in enumerate(WORKER_TYPES):
                c[i * n + w] = -tputs[j][wt]
        A_ub, b_ub = time_and_capacity_rows(job_ids, sfs, cluster, m * n)
        res = linprog(c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                      bounds=[(0.0, 1.0)] * (m * n), method="highs")
        assert res.status == 0
        got = sum(
            sum(tputs[j][wt] * alloc[j].get(wt, 0.0)
                for wt in tputs[j]) for j in job_ids)
        assert got == pytest.approx(-res.fun, rel=1e-3)
