"""Observability subsystem: metrics registry, span tracing, health
endpoint.

Three parts (see README "Observability"):

- `registry` — thread-safe labeled counters/gauges/histograms, declared
  centrally in `obs/names.py` (the `obs-discipline` swtpu-check pass
  bans inline name literals at call sites).
- `tracing` — nestable spans exported as Chrome-trace JSON; summarize
  with ``python -m shockwave_tpu.obs.report``.
- `exporter` — HTTP ``/metrics`` (Prometheus text) + ``/healthz``
  (JSON), opt-in via ``SchedulerConfig.obs_port``.

`Observability` bundles a registry and tracer around one injected clock:
the scheduler constructs it with ``get_current_timestamp`` so the same
instrumentation runs on the simulator's virtual clock (bit-identical
replay preserved — recording never feeds back into scheduling) and on
wall clocks in the physical control plane.

``SWTPU_OBS=0`` disables recording globally (used by the overhead
measurements in EXPERIMENTS.md and the obs-on/off determinism tests).
"""
from __future__ import annotations

import os
import threading
import weakref
from contextlib import contextmanager
from typing import Optional

from . import names
from .clock import Clock, wall_clock
from .registry import MetricsRegistry
from .tracing import Tracer

__all__ = ["Observability", "MetricsRegistry", "Tracer", "names",
           "get_observability", "dump_all", "obs_enabled_by_env"]

#: Every live Observability, for end-of-session artifact dumps
#: (dump_all). Weak so short-lived test schedulers don't accumulate.
_ALL_OBS: "weakref.WeakSet[Observability]" = weakref.WeakSet()
_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional["Observability"] = None


def obs_enabled_by_env() -> bool:
    return os.environ.get("SWTPU_OBS", "1") not in ("", "0")


class Observability:
    """One registry + one tracer sharing an injected clock, plus the
    convenience delegates instrumentation call sites use."""

    def __init__(self, clock: Optional[Clock] = None,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = obs_enabled_by_env()
        self.enabled = enabled
        self.clock: Clock = clock or wall_clock
        self.registry = MetricsRegistry(clock=self.clock, enabled=enabled)
        self.tracer = Tracer(clock=self.clock, enabled=enabled)
        self._bind_delegates()
        _ALL_OBS.add(self)

    def _bind_delegates(self) -> None:
        # Hot-path aliases bound as instance attributes: the simulator
        # calls inc/observe thousands of times per wall second, and the
        # extra delegate frame + kwargs repack measurably shows up
        # (EXPERIMENTS.md "Observability overhead").
        self.inc = self.registry.inc
        self.set_gauge = self.registry.set_gauge
        self.observe = self.registry.observe
        self.timed = self.registry.timed
        self.remove_series = self.registry.remove_series
        self.span = self.tracer.span

    def __getstate__(self):
        # The bound delegates would pickle whole object subgraphs;
        # rebind from the unpickled registry/tracer instead.
        state = dict(self.__dict__)
        for name in ("inc", "set_gauge", "observe", "timed",
                     "remove_series", "span"):
            state.pop(name, None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._bind_delegates()
        _ALL_OBS.add(self)

    @contextmanager
    def phase(self, name: str, parent=None, **args):
        """A round-pipeline phase: one trace span plus one observation
        into the shared phase histogram, so the trace timeline and the
        /metrics scrape tell the same story. `parent` splices the span
        under a remote/manual SpanContext (the physical scheduler's
        per-round root), wiring the phase into the fleet trace."""
        if not self.enabled:
            yield None
            return
        t0 = self.clock()
        with self.tracer.span(name, parent=parent, **args) as ctx:
            try:
                yield ctx
            finally:
                self.registry.observe(names.ROUND_PHASE_SECONDS,
                                      max(self.clock() - t0, 0.0),
                                      phase=name)


def get_observability() -> Observability:
    """Process-global wall-clock Observability (job-side runtime and
    components without a scheduler-injected handle)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Observability()
        return _GLOBAL


def dump_all(directory: str) -> list:
    """Write every live Observability's metrics (.prom) and trace
    (.json) into `directory`; returns the written paths. Used by the CI
    failure-artifact hook (tests/conftest.py) so a distributed-test
    flake arrives with a timeline attached."""
    os.makedirs(directory, exist_ok=True)
    written = []
    for i, obs in enumerate(sorted(_ALL_OBS, key=id)):
        text = obs.registry.render_prometheus()
        if text.strip():
            path = os.path.join(directory, f"metrics-{i}.prom")
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
            written.append(path)
        if obs.tracer.events():
            path = os.path.join(directory, f"trace-{i}.json")
            obs.tracer.export_chrome_trace(path)
            written.append(path)
    return written
