"""Negative controls for the deadlock and hold-discipline passes: the
same cross-thread shapes as bad_deadlock.py / bad_blocking.py, but with
the exemptions that must all stay quiet — consistent nesting order, the
@requires_lock entry contract plus the own-condition wait rule, and
both documented-verdict registries (whose entries must also NOT be
reported stale)."""
import threading

from shockwave_tpu.core.locking import requires_lock


class OrderedNest:
    """Two threads, two locks, ONE order everywhere: edges but no
    cycle."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        threading.Thread(target=self._loop_one, daemon=True).start()
        threading.Thread(target=self._loop_two, daemon=True).start()

    def _loop_one(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def _loop_two(self):
        with self._lock_a:
            with self._lock_b:
                pass


class Waiter:
    """@requires_lock callee + own-cv wait: the helper enters with the
    receiver's lock by contract, and its timeout-less wait on the
    condition WRAPPING that same lock releases it while blocked — no
    hold-discipline finding for caller or callee."""

    def __init__(self):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._ready = False
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._cv:
            self._wait_ready()
            self._ready = False

    @requires_lock
    def _wait_ready(self):
        while not self._ready:
            self._cv.wait()


class JustifiedOrder:
    """Registry verdict for an order inversion: the backward path runs
    only during single-threaded construction in the real pattern this
    models, so the edge is sanctioned with a written justification."""

    #: Justified: _backward executes before the _forward thread is
    #: spawned; the inversion cannot interleave with the forward order.
    _LOCK_ORDER_JUSTIFIED = frozenset({
        "JustifiedOrder._lock_a->JustifiedOrder._lock_b",
    })

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        threading.Thread(target=self._forward, daemon=True).start()
        threading.Thread(target=self._backward, daemon=True).start()

    def _forward(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def _backward(self):
        with self._lock_b:
            with self._lock_a:
                pass


class JustifiedHold:
    """Registry verdict for blocking under a lock: a bounded-deadline
    ping that the (modeled) lease protocol requires to be atomic with
    the guarded state update."""

    #: Justified: the ping carries a short deadline and must observe
    #: the same lease epoch the guarded counter records.
    _HOLD_DISCIPLINE_JUSTIFIED = frozenset({"_loop:rpc"})

    def __init__(self, stub):
        self._lock = threading.Lock()
        self._stub = stub
        self._pings = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self._stub.ping()
            self._pings += 1
