"""Cross-process span context: the fleet-trace propagation primitive.

A `SpanContext` is a (trace_id, span_id) pair in the W3C traceparent
shape (``00-<32 hex>-<16 hex>-01``). One round's
solve -> dispatch -> launch -> trainer-step -> Done chain shares a
single trace id across three or more processes:

- the scheduler opens a per-round root context and nests its phase and
  per-dispatch RPC spans under it (obs/tracing.py keeps the in-process
  parent stack);
- every scheduler->worker RunJob carries the active span's traceparent
  as gRPC metadata (`names.TRACEPARENT_METADATA_KEY` — the same channel
  the HA epoch fence rides) plus a send timestamp for clock alignment;
- the worker daemon adopts it as the remote parent of its `runjob` /
  `launch` spans, and the dispatcher forwards the launch context into
  the trainer subprocess as `names.TRACEPARENT_ENV` (the
  SWTPU_DEGRADE_FACTOR pattern);
- the job-side LeaseIterator adopts the env context for its `trainer`
  span, written into the process's span shard (obs/shard.py) and fused
  back into one timeline by ``python -m shockwave_tpu.obs.merge``.

Ids are generated from one `os.urandom` seed per process plus a
counter — no wall-clock reads (obs-discipline), no per-span entropy
syscall on the hot path, and no cross-process collisions.
"""
from __future__ import annotations

import itertools
import os
import re
from dataclasses import dataclass
from typing import Iterable, Optional, Tuple

from . import names

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

#: Per-process id material: 12 random bytes (24 hex) for the trace-id
#: head, 4 (8 hex) for the span-id head; the tail is a counter.
_TRACE_BASE = os.urandom(12).hex()
_SPAN_BASE = os.urandom(4).hex()
_COUNTER = itertools.count(1)


@dataclass(frozen=True)
class SpanContext:
    """One span's identity within a trace. Immutable and hashable so it
    can ride thread-local stacks, RPC metadata and env vars alike."""
    trace_id: str
    span_id: str


def new_trace_id() -> str:
    return f"{_TRACE_BASE}{next(_COUNTER) & 0xFFFFFFFF:08x}"


def new_span_id() -> str:
    return f"{_SPAN_BASE}{next(_COUNTER) & 0xFFFFFFFF:08x}"


def new_root_context() -> SpanContext:
    return SpanContext(trace_id=new_trace_id(), span_id=new_span_id())


def child_context(parent: SpanContext) -> SpanContext:
    """A fresh span id inside the parent's trace."""
    return SpanContext(trace_id=parent.trace_id, span_id=new_span_id())


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent string; malformed input yields None (a
    telemetry channel must never take a dispatch down)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    return SpanContext(trace_id=m.group(1), span_id=m.group(2))


# -- gRPC metadata ------------------------------------------------------

def rpc_metadata(ctx: Optional[SpanContext],
                 send_ts: Optional[float] = None) -> Tuple[Tuple[str, str], ...]:
    """Metadata entries carrying `ctx` (and the sender's clock) on an
    RPC; empty when tracing is off so fenceless historical behavior is
    byte-identical."""
    if ctx is None:
        return ()
    entries = [(names.TRACEPARENT_METADATA_KEY, format_traceparent(ctx))]
    if send_ts is not None:
        entries.append((names.TRACE_SENDTS_METADATA_KEY,
                        repr(float(send_ts))))
    return tuple(entries)


def from_rpc_metadata(metadata: Optional[Iterable[Tuple[str, str]]]
                      ) -> Tuple[Optional[SpanContext], Optional[float]]:
    """(remote parent context, sender send-timestamp) from invocation
    metadata; (None, None) when absent or malformed."""
    ctx, send_ts = None, None
    for key, value in (metadata or ()):
        if key == names.TRACEPARENT_METADATA_KEY:
            ctx = parse_traceparent(value)
        elif key == names.TRACE_SENDTS_METADATA_KEY:
            try:
                send_ts = float(value)
            except (TypeError, ValueError):
                send_ts = None
    return ctx, send_ts


# -- environment (dispatcher -> trainer subprocess) ---------------------

def to_environ(ctx: Optional[SpanContext], env: dict) -> dict:
    """Export `ctx` into a subprocess environment dict (in place)."""
    if ctx is not None:
        env[names.TRACEPARENT_ENV] = format_traceparent(ctx)
    return env


def from_environ(environ=None) -> Optional[SpanContext]:
    source = os.environ if environ is None else environ
    return parse_traceparent(source.get(names.TRACEPARENT_ENV))
