"""Shockwave planner: owns job metadata, solve cadence, and round schedules.

Wraps the EG MILP (milp.py) with: uniform-share finish-time estimation,
schedule caching between re-solves, and work-conserving backfill of idle
chips (reference: scheduler/shockwave.py:20-285).

The solve is split into three phases so the physical scheduler can
pipeline it off the round-loop critical path (the same pattern as its
`_allocation_thread`):

- `prepare_solve()` — under the scheduler lock: refresh estimates and
  snapshot every solve input into an immutable PlanRequest (per-job
  `_JobView`s, copied share series).
- `solve_prepared(request)` — lock-free: the MILP + schedule
  construction, a pure function of the request.
- `commit_result(result)` — under the lock: install the schedules,
  record telemetry, journal the solve outcome.

The simulator runs all three inline inside `round_schedule()` (single
thread, bit-identical to the historical monolithic path); the physical
scheduler runs the middle phase on a background thread and falls back
to the cached schedule / work-conserving backfill when the solve is not
done at the re-solve round (`_fallback_round_schedule`).
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import names as obs_names
from .metadata import JobMetadata
from .milp import MilpOptions, plan_schedule

logger = logging.getLogger("shockwave_tpu.shockwave")


class _JobView:
    """Immutable per-job snapshot of the MILP's inputs, captured under
    the scheduler lock so `solve_prepared` can run without it. Exposes
    the same accessors plan_schedule / _relaxation_priorities /
    _greedy_fallback call on live JobMetadata; the values are identical
    because metadata memoizes them (calibration is fingerprint-cached)
    and no new measurements land between snapshot and solve in the
    single-threaded simulator."""

    __slots__ = ("nworkers", "epochs", "epoch_progress",
                 "_epoch_duration", "_remaining")

    def __init__(self, meta: JobMetadata):
        meta.calibrate_profiled_epoch_duration()
        self.nworkers = meta.nworkers
        self.epochs = meta.epochs
        self.epoch_progress = meta.epoch_progress
        self._epoch_duration = meta.interpolated_epoch_duration()
        self._remaining = meta.dirichlet_posterior_remaining_runtime()

    def interpolated_epoch_duration(self) -> float:
        return self._epoch_duration

    def dirichlet_posterior_remaining_runtime(self, progress=None) -> float:
        return self._remaining

    def calibrate_profiled_epoch_duration(self) -> None:
        pass  # snapshot is already calibrated


@dataclass
class PlanRequest:
    """Everything one MILP solve reads, snapshotted under the lock."""
    round_ptr: int
    job_ids: List[int]
    jobs: List[_JobView]
    share_series: List[list]
    generation: int
    #: Capacity row for this solve: cluster chips minus whatever the
    #: serving tier reserved ahead of the planner (== ngpus when no
    #: serving jobs exist, keeping training-only replays bit-identical).
    #: -1 = unset (hand-built request): solve with the full cluster.
    ngpus: int = -1
    #: Per-worker-type capacity rows for heterogeneous clusters
    #: ({worker_type: chips}, net of serving reservations). None on
    #: single-generation clusters — and on requests predating the
    #: field (old pickles), which solve_prepared reads via getattr —
    #: keeping the scalar backfill arithmetic bit-identical.
    capacity_rows: Optional[Dict[str, int]] = None


@dataclass
class PlanResult:
    """One finished solve, ready to commit under the lock."""
    round_ptr: int
    schedules: "OrderedDict[int, List[int]]"
    stats: list = field(default_factory=list)
    generation: int = 0


class ShockwavePlanner:
    #: Planner state is mutated from the scheduler round loop, the
    #: job-lifecycle paths (add/remove via gRPC handlers) and
    #: `commit_result` — every one of those call sites holds the OWNING
    #: scheduler's lock (sched/physical.py `_LOCK_PROTECTED` covers the
    #: planner handoff), which a per-class static lockset cannot see,
    #: so the verdict is documented here. The solve thread deliberately
    #: touches none of these: `solve_prepared` is a pure function of an
    #: immutable PlanRequest plus init-frozen config (ngpus/opts/...).
    #: Checked dynamically by the sanitizer + interleaving explorer.
    _EXTERNALLY_SYNCHRONIZED = frozenset({
        "metadata", "completed", "schedules", "round_ptr", "_resolve",
        "_resolve_gen", "_reestimate_share", "share_series",
        "solve_stats", "reserved_gpus", "capacity_rows", "pipelined",
        "journal", "obs",
    })

    def __init__(self, ngpus: int, future_nrounds: int, round_duration: float,
                 opts: Optional[MilpOptions] = None):
        assert ngpus > 0 and future_nrounds > 0 and round_duration > 0
        self.ngpus = ngpus
        self.future_nrounds = future_nrounds
        self.round_duration = round_duration
        self.opts = opts or MilpOptions()

        # Chips the serving tier has reserved ahead of the planner this
        # round (shockwave_tpu/serving/tier.py): the capacity row every
        # solve and fallback sees is ngpus - reserved_gpus. Stays 0 for
        # training-only traces.
        self.reserved_gpus = 0

        # Per-worker-type capacity rows ({worker_type: chips}, net of
        # serving reservations), refreshed by the owning scheduler
        # every round on heterogeneous clusters. None (single
        # generation) keeps every code path on the historical scalar
        # arithmetic, so canonical replays stay bit-identical.
        self.capacity_rows: Optional[Dict[str, int]] = None

        self.metadata: "OrderedDict[int, JobMetadata]" = OrderedDict()
        self.completed: "OrderedDict[int, JobMetadata]" = OrderedDict()
        self.schedules: "OrderedDict[int, List[int]]" = OrderedDict()
        self.round_ptr = 0
        self._resolve = True
        self._reestimate_share = True
        # Monotone re-solve request counter: a commit only clears
        # `_resolve` when no new request (job add/remove, reopt cadence)
        # arrived after its inputs were snapshotted — a stale pipelined
        # result is still installed (fresher than nothing) but the next
        # re-solve round solves again.
        self._resolve_gen = 0
        # Physical pipelined mode (set by the owning PhysicalScheduler):
        # round_schedule never solves inline; it serves committed
        # results or the deadline fallback. Simulation keeps this False
        # so the canonical replay stays bit-identical.
        self.pipelined = False
        self.share_series: Dict[int, list] = {}
        # Per-solve quality telemetry (milp.SolveStats), appended by
        # every plan_schedule call; drivers persist it so scale runs
        # can prove the fallback chain stays cold.
        self.solve_stats: list = []
        # Durability hook: callable(event_type, data_dict) wired by the
        # scheduler when a write-ahead journal is attached, so progress
        # marks, waiting delays, round advances and solve outcomes are
        # journaled at their source and replay rebuilds the planner's
        # estimate state exactly. None = no journaling.
        self.journal = None
        # Observability handle, wired by the owning scheduler so spans
        # ride its injected clock (virtual in simulation). None falls
        # back to the process-global wall-clock bundle.
        self.obs = None

    def _journal_event(self, etype: str, data: dict) -> None:
        if self.journal is not None:
            self.journal(etype, data)

    def _obs_handle(self):
        if self.obs is None:
            from ..obs import get_observability
            return get_observability()
        return self.obs

    # The simulator checkpoints pickle the whole planner; the obs
    # handle's clock and the journal hook are bound methods of the
    # owning scheduler, so neither may ride along (each would drag a
    # ghost scheduler copy into the pickle). The resume path
    # (Scheduler._load_simulation_checkpoint) re-wires both.
    def __getstate__(self):
        state = dict(self.__dict__)
        state["obs"] = None
        state["journal"] = None
        return state

    @classmethod
    def from_config(cls, config: dict) -> "ShockwavePlanner":
        opts = MilpOptions(
            rel_gap=config.get("solver_rel_gap", 1e-3),
            timeout=config.get("solver_timeout", 15),
            rhomax=config.get("rhomax", 1.0),
            k=config.get("k", 1e-3),
            lam=config.get("lambda", 12.0),
            logapx_bases=tuple(config.get(
                "log_approximation_bases", (0.0, 0.2, 0.4, 0.6, 0.8, 1.0))),
            budget_cap_rounds=config.get("solver_budget_cap_rounds", 0.5),
        )
        return cls(
            ngpus=config["num_gpus"],
            future_nrounds=config.get("future_rounds", 20),
            round_duration=config["time_per_iteration"],
            opts=opts,
        )

    # -- job lifecycle -----------------------------------------------------

    def add_job(self, job_id: int, meta: JobMetadata) -> None:
        assert job_id not in self.metadata
        self.metadata[job_id] = meta
        self.request_resolve()
        self._reestimate_share = True

    def remove_job(self, job_id: int) -> None:
        assert job_id in self.metadata and job_id not in self.completed
        self.completed[job_id] = self.metadata.pop(job_id)
        self.request_resolve()
        self._reestimate_share = True

    def mark_progress(self, job_id: int, epoch_progress: int) -> None:
        meta = self.metadata.get(job_id) or self.completed.get(job_id)
        if meta is None:
            return
        meta.set_epoch_progress(min(epoch_progress, meta.epochs))
        meta.reset_waiting_delay()
        self._journal_event("planner_progress",
                            {"int_id": job_id, "epoch": epoch_progress})

    def add_waiting_delay(self, job_id: int, delay: float) -> None:
        if job_id in self.metadata:
            self.metadata[job_id].add_waiting_delay(delay)
            self._journal_event("planner_waiting",
                                {"int_id": job_id, "delay": delay})

    def increment_round(self) -> None:
        self.round_ptr += 1
        self._journal_event("planner_round", {})

    def request_resolve(self) -> None:
        self._resolve = True
        self._resolve_gen += 1

    # -- share estimation --------------------------------------------------

    def _estimate_uniform_share_finish_times(self) -> None:
        """Record each job's finish-time estimate under a uniform 1/n share;
        the momentumed average of these is the FTF target
        (reference: shockwave.py:88-120)."""
        if not self._reestimate_share:
            return
        njobs = len(self.metadata)
        with self._obs_handle().span(obs_names.SPAN_ESTIMATE_REFRESH,
                                     njobs=njobs, round=self.round_ptr):
            for job_id, job in self.metadata.items():
                share = min(1.0, self.ngpus / njobs)
                job.calibrate_profiled_epoch_duration()
                estimate = job.timestamp_submit + (
                    sum(job.epoch_duration[:job.epoch_progress])
                    + job.dirichlet_posterior_remaining_runtime(
                        job.epoch_progress)
                ) / share
                self.share_series.setdefault(job_id, []).append(
                    (self.round_ptr, estimate))
        self._reestimate_share = False

    # -- scheduling --------------------------------------------------------

    def needs_resolve(self) -> bool:
        """Whether serving the current round requires a fresh solve."""
        return self._resolve or self.round_ptr not in self.schedules

    def prepare_solve(self) -> Optional[PlanRequest]:
        """Phase 1 (under the scheduler lock): refresh the uniform-share
        estimates and snapshot the solve inputs. None when idle."""
        if not self.metadata:
            return None
        self._estimate_uniform_share_finish_times()
        job_ids = list(self.metadata.keys())
        return PlanRequest(
            round_ptr=self.round_ptr,
            job_ids=job_ids,
            jobs=[_JobView(m) for m in self.metadata.values()],
            share_series=[list(self.share_series[j]) for j in job_ids],
            generation=self._resolve_gen,
            ngpus=max(self.ngpus - self.reserved_gpus, 0),
            capacity_rows=(dict(self.capacity_rows)
                           if self.capacity_rows else None))

    def solve_prepared(self, request: PlanRequest,
                       pipelined: bool = False) -> PlanResult:
        """Phase 2 (no lock required): the MILP + schedule construction,
        a pure function of the request snapshot."""
        stats: list = []
        obs = self._obs_handle()
        # Requests predating the ngpus field (old pickles, hand-built
        # tests) carry the -1 sentinel: solve with the full cluster.
        ngpus = getattr(request, "ngpus", -1)
        if ngpus < 0:
            ngpus = self.ngpus
        if ngpus <= 0:
            # Serving reserved the whole cluster this round: nothing to
            # solve — every horizon round schedules no training.
            schedules: "OrderedDict[int, List[int]]" = OrderedDict(
                (request.round_ptr + r, [])
                for r in range(self.future_nrounds))
            return PlanResult(round_ptr=request.round_ptr,
                              schedules=schedules, stats=stats,
                              generation=request.generation)
        with obs.span(obs_names.SPAN_PLANNER_SOLVE, njobs=len(request.jobs),
                      round=request.round_ptr):
            x = plan_schedule(request.jobs, request.round_ptr,
                              self.future_nrounds, self.round_duration,
                              ngpus, request.share_series, self.opts,
                              stats_out=stats, pipelined=pipelined)
        schedules = self._construct_schedules(
            x, request.job_ids, request.jobs, request.round_ptr,
            ngpus=ngpus,
            capacity_rows=getattr(request, "capacity_rows", None))
        return PlanResult(round_ptr=request.round_ptr, schedules=schedules,
                          stats=stats, generation=request.generation)

    def commit_result(self, result: PlanResult) -> None:
        """Phase 3 (under the scheduler lock): install the schedules and
        record the solve's telemetry + journal entry."""
        from dataclasses import asdict
        self.schedules = result.schedules
        if result.generation == self._resolve_gen:
            self._resolve = False
        obs = self._obs_handle()
        for stats in result.stats:
            self.solve_stats.append(stats)
            # The MILP's own wall time is already measured inside
            # plan_schedule (SolveStats.wall_s, journaled with the
            # outcome) — observe that rather than re-timing, so replay
            # and live runs histogram the same number.
            obs.observe(obs_names.MILP_SOLVE_SECONDS, stats.wall_s,
                        path=stats.path)
            obs.observe(obs_names.MILP_ASSEMBLY_SECONDS, stats.assembly_s,
                        path=stats.path)
            if stats.path != "ftf":
                obs.inc(obs_names.SOLVER_FALLBACKS_TOTAL, path=stats.path)
            self._journal_event("solve_outcome", asdict(stats))
            if self.pipelined:
                if not stats.pipelined:
                    outcome = "inline"
                elif result.round_ptr == self.round_ptr:
                    # Committed before the round it was solved for was
                    # served: the background solve beat its deadline.
                    outcome = "hit"
                else:
                    # The target round already ran on the fallback
                    # (counted there as a miss); this result still
                    # covers the rest of its horizon.
                    outcome = "late"
                obs.inc(obs_names.PIPELINED_SOLVES_TOTAL, outcome=outcome)

    def round_schedule(self) -> List[int]:
        """Job ids to run this round, re-solving the MILP if requested."""
        if not self._resolve and self.round_ptr in self.schedules:
            return self.schedules[self.round_ptr]
        if not self.metadata:
            return []
        if self.pipelined:
            # Physical pipelined mode: the background thread owns the
            # solve; a re-solve round reaching here means the result was
            # not committed in time — serve the deadline fallback, never
            # stall the round loop on the solver.
            return self._fallback_round_schedule()
        request = self.prepare_solve()
        self.commit_result(self.solve_prepared(request))
        return self.schedules[self.round_ptr]

    def _fallback_round_schedule(self) -> List[int]:
        """Deadline fallback: the cached horizon entry when the last
        committed solve still covers this round, else a work-conserving
        backfill-only schedule (longest remaining runtime first) over
        the live job set."""
        self._obs_handle().inc(obs_names.PIPELINED_SOLVES_TOTAL,
                               outcome="miss")
        cached = self.schedules.get(self.round_ptr)
        if cached is not None:
            return cached
        logger.warning("pipelined solve not ready at round %d and no "
                       "cached schedule covers it; serving backfill-only "
                       "schedule", self.round_ptr)
        selected: List[int] = []
        by_remaining = sorted(
            self.metadata.items(),
            key=lambda kv: kv[1].dirichlet_posterior_remaining_runtime(),
            reverse=True)
        if self.capacity_rows and len(self.capacity_rows) > 1:
            idle_rows = {wt: max(int(cap), 0)
                         for wt, cap in self.capacity_rows.items()}
            for job_id, meta in by_remaining:
                if self._fit_row(idle_rows, meta.nworkers) is not None:
                    selected.append(job_id)
                if all(cap <= 0 for cap in idle_rows.values()):
                    break
        else:
            idle = max(self.ngpus - self.reserved_gpus, 0)
            for job_id, meta in by_remaining:
                if meta.nworkers <= idle:
                    selected.append(job_id)
                    idle -= meta.nworkers
                if idle <= 0:
                    break
        # Pin the fallback for the round so repeated queries within the
        # same round stay consistent.
        self.schedules[self.round_ptr] = selected
        return selected

    @staticmethod
    def _fit_row(idle_rows: Dict[str, int], need: int) -> Optional[str]:
        """Place a job needing `need` chips of a single generation into
        the per-type idle rows: picks the worker type with the most idle
        chips that still fits (type name as deterministic tie-break),
        deducts in place, and returns it — or None when no single
        generation can host the job this round."""
        fit = [wt for wt, cap in idle_rows.items() if cap >= need]
        if not fit:
            return None
        wt = sorted(fit, key=lambda w: (-idle_rows[w], w))[0]
        idle_rows[wt] -= need
        return wt

    def _construct_schedules(self, x, job_ids, jobs, base_round: int,
                             ngpus: Optional[int] = None,
                             capacity_rows: Optional[Dict[str, int]] = None,
                             ) -> "OrderedDict[int, List[int]]":
        """Solution matrix -> per-round job lists, with work-conserving
        backfill of idle chips by longest remaining runtime
        (reference: shockwave.py:213-285). Operates purely on the
        request snapshot (job_ids + views) so it can run off-lock.
        `ngpus` is the request's (serving-shrunk) capacity row.

        On heterogeneous clusters (`capacity_rows` with >1 worker type)
        a training job occupies chips of exactly one generation, so
        MILP selections and backfill candidates are first-fit packed
        into the per-type rows instead of against the cluster total; a
        selected job that fits no single generation is deferred to a
        later round rather than oversubscribing a row."""
        if ngpus is None:
            ngpus = self.ngpus
        hetero = capacity_rows is not None and len(capacity_rows) > 1
        schedules: "OrderedDict[int, List[int]]" = OrderedDict()
        for r in range(self.future_nrounds):
            round_index = base_round + r
            sel = [j for j in range(len(job_ids)) if x[j, r]]
            selected = [job_ids[j] for j in sel]
            if not selected:
                logger.warning("no jobs scheduled in round %d", round_index)
            if hetero:
                idle_rows = {wt: max(int(cap), 0)
                             for wt, cap in sorted(capacity_rows.items())}
                kept: List[int] = []
                for j in sel:
                    if self._fit_row(idle_rows, jobs[j].nworkers) is not None:
                        kept.append(job_ids[j])
                others = [j for j in range(len(job_ids))
                          if job_ids[j] not in kept]
                others.sort(key=lambda j: jobs[j].dirichlet_posterior_remaining_runtime(),
                            reverse=True)
                for j in others:
                    if all(cap <= 0 for cap in idle_rows.values()):
                        break
                    if self._fit_row(idle_rows, jobs[j].nworkers) is not None:
                        kept.append(job_ids[j])
                schedules[round_index] = kept
                continue
            used = sum(jobs[j].nworkers for j in sel)
            idle = ngpus - used
            if idle > 0:
                others = [j for j in range(len(job_ids))
                          if job_ids[j] not in selected]
                others.sort(key=lambda j: jobs[j].dirichlet_posterior_remaining_runtime(),
                            reverse=True)
                for j in others:
                    if jobs[j].nworkers <= idle:
                        idle -= jobs[j].nworkers
                        selected.append(job_ids[j])
                    if idle <= 0:
                        break
            schedules[round_index] = selected
        return schedules
