"""Seeded violation for the race-detector pass: a field written by a
spawned thread's loop with no lock, read from the public (main-thread)
surface — the lockset intersection is empty."""
import threading


class UnlockedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        for _ in range(100):
            self._total += 1  # SEEDED

    def read(self):
        return self._total
