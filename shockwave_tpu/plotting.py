"""Result plotting: JCT / fairness CDFs, policy bar charts, and per-round
schedule heatmaps from metric pickles (reference: scheduler/plotting.py).

Every function takes `{label: metrics_dict}` where each metrics dict is
one driver-output pickle (simulate.py / run_physical.py / the sweep
scripts), and writes a PNG. Usable as a CLI:

    python -m shockwave_tpu.plotting --metric jct \
        --pickles shockwave=out/shockwave.pkl gavel=out/mmf.pkl \
        --output jct_cdf.png
"""
from __future__ import annotations

import argparse
import os
import pickle
from typing import Dict, List, Optional

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def _cdf_axes(ax, xlabel: str):
    ax.set_ylabel("CDF")
    ax.set_xlabel(xlabel)
    ax.set_ylim(0, 1)
    ax.grid(alpha=0.3)
    ax.legend()


def _plot_cdf(ax, values: List[float], label: str):
    xs = np.sort(np.asarray(values, dtype=float))
    ys = np.arange(1, len(xs) + 1) / len(xs)
    ax.plot(xs, ys, label=label, drawstyle="steps-post")


def plot_jct_cdf(results: Dict[str, dict], output: str,
                 hours: bool = True) -> str:
    """CDF of job completion times per policy (reference: plotting.py's
    JCT CDF figures)."""
    fig, ax = plt.subplots(figsize=(5, 3.5))
    for label, metrics in results.items():
        jcts = np.asarray(metrics["jct_list"], dtype=float)
        _plot_cdf(ax, jcts / 3600.0 if hours else jcts, label)
    _cdf_axes(ax, "JCT (hours)" if hours else "JCT (s)")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_ftf_cdf(results: Dict[str, dict], output: str,
                 themis: bool = False) -> str:
    """CDF of finish-time-fairness rho per policy; rho > 1 means the job
    did worse than its fair share (reference: plotting.py rho CDFs)."""
    key = ("finish_time_fairness_themis_list" if themis
           else "finish_time_fairness_list")
    fig, ax = plt.subplots(figsize=(5, 3.5))
    for label, metrics in results.items():
        _plot_cdf(ax, metrics[key], label)
    ax.axvline(1.0, color="k", linestyle="--", linewidth=0.8)
    _cdf_axes(ax, "finish-time fairness " + r"$\rho$")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_policy_bars(results: Dict[str, dict], output: str,
                     metric: str = "makespan", hours: bool = True) -> str:
    """Bar chart of a scalar metric (makespan / avg_jct / cluster_util)
    across policies."""
    labels = list(results)
    values = [float(results[k][metric]) for k in labels]
    if hours and metric in ("makespan", "avg_jct"):
        values = [v / 3600.0 for v in values]
        unit = " (hours)"
    else:
        unit = ""
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.bar(labels, values)
    ax.set_ylabel(metric + unit)
    ax.grid(alpha=0.3, axis="y")
    plt.xticks(rotation=20, ha="right")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def _schedule_key_members(key):
    """A per_round_schedule key is a bare int job id, or a tuple of
    member ids for a packed-pair dispatch; yield the member ids."""
    return tuple(key) if isinstance(key, tuple) else (int(key),)


def plot_schedule_heatmap(metrics: dict, output: str,
                          max_rounds: Optional[int] = None) -> str:
    """Rounds x jobs occupancy map from `per_round_schedule`
    (reference: plotting.py per-round schedule heatmaps)."""
    schedule = metrics["per_round_schedule"]
    if max_rounds:
        schedule = schedule[:max_rounds]
    job_ids = sorted({m for rnd in schedule for j in rnd
                      for m in _schedule_key_members(j)})
    if not job_ids:
        raise ValueError("empty per_round_schedule")
    col = {j: i for i, j in enumerate(job_ids)}
    grid = np.zeros((len(schedule), len(job_ids)))
    for r, rnd in enumerate(schedule):
        for j, worker_ids in rnd.items():
            # Values are the assigned worker-id tuples; plot chip counts.
            for m in _schedule_key_members(j):
                grid[r, col[m]] = (len(worker_ids)
                                   if hasattr(worker_ids, "__len__")
                                   else worker_ids)
    fig, ax = plt.subplots(figsize=(6, 4))
    im = ax.imshow(grid.T, aspect="auto", interpolation="nearest",
                   cmap="viridis", origin="lower")
    ax.set_xlabel("round")
    ax.set_ylabel("job")
    fig.colorbar(im, label="chips allocated")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_worker_gantt(metrics: Optional[dict] = None,
                      output: str = "gantt.png",
                      timeline_dir: Optional[str] = None) -> str:
    """Worker-occupancy Gantt: worker x time, one colored span per
    job lease (reference analog:
    scripts/utils/postprocess_simulator_log.py, which reconstructs
    per-job worker occupancy from run logs).

    Two sources:
    - a metric pickle (sim or physical): round-quantized spans from
      `per_round_schedule` x `time_per_iteration`;
    - a physical run's `--timeline_dir`: exact in-lease spans parsed
      from the iterator event logs (LOAD CHECKPOINT BEGIN ->
      SAVE CHECKPOINT END per dispatch), which also expose the
      dead time between leases that the round-quantized view hides.
    """
    # spans: {worker_id: [(start, length, job_id)]}
    spans: Dict[int, list] = {}
    if timeline_dir:
        import datetime
        import glob
        import re
        fmt = "%Y-%m-%d %H:%M:%S"
        events = []  # (job, worker, wall_ts, event, state)
        for path in glob.glob(os.path.join(timeline_dir, "job_id=*.log")):
            job = int(re.search(r"job_id=(\d+)", path).group(1))
            for line in open(path):
                m = re.match(
                    r"t=[\d.]+ ITERATOR worker=(\d+) \[(.*?)\] "
                    r"\[(.*?)\] \[(.*?)\]", line)
                if m:
                    ts = datetime.datetime.strptime(m.group(2), fmt)
                    events.append((job, int(m.group(1)), ts,
                                   m.group(3), m.group(4)))
        if not events:
            raise ValueError(f"no iterator events under {timeline_dir}")
        t0 = min(e[2] for e in events)
        open_spans: Dict[tuple, float] = {}
        last_seen: Dict[tuple, float] = {}
        for job, worker, ts, ev, st in sorted(events, key=lambda e: e[2]):
            rel = (ts - t0).total_seconds()
            key = (job, worker)
            last_seen[key] = rel
            if ev == "LOAD CHECKPOINT" and st == "BEGIN":
                open_spans[key] = rel
            elif ev == "SAVE CHECKPOINT" and st == "END":
                # Only the save end closes a span: LEASE COMPLETE
                # precedes the final checkpoint save, which belongs to
                # the lease's occupancy.
                start = open_spans.pop(key, None)
                if start is not None and rel > start:
                    spans.setdefault(worker, []).append(
                        (start, rel - start, job))
        # A dispatch that never reached its save (kill, crash, rank>0 of
        # a gang whose save is rank-0-only) closes at its last event.
        for (job, worker), start in open_spans.items():
            end = last_seen[(job, worker)]
            if end > start:
                spans.setdefault(worker, []).append(
                    (start, end - start, job))
    else:
        if metrics is None:
            raise ValueError("need a metric pickle or --timeline_dir")
        round_s = metrics.get("time_per_iteration") or 1.0
        for r, rnd in enumerate(metrics["per_round_schedule"]):
            for j, worker_ids in rnd.items():
                ids = (worker_ids if hasattr(worker_ids, "__iter__")
                       else [worker_ids])
                members = _schedule_key_members(j)
                # Packed pairs time-share the chip: split the round span
                # between the members so neither bar occludes the other.
                frac = round_s / len(members)
                for w in ids:
                    for mi, m in enumerate(members):
                        spans.setdefault(int(w), []).append(
                            (r * round_s + mi * frac, frac, m))
    if not spans:
        raise ValueError("no occupancy spans found")
    jobs = sorted({j for sp in spans.values() for _, _, j in sp})
    cmap = plt.get_cmap("tab20")
    color = {j: cmap(i % 20) for i, j in enumerate(jobs)}
    workers = sorted(spans)
    fig, ax = plt.subplots(figsize=(9, 0.6 * max(len(workers), 3) + 1.5))
    for row, w in enumerate(workers):
        ax.broken_barh([(s, d) for s, d, _ in spans[w]],
                       (row - 0.4, 0.8),
                       facecolors=[color[j] for _, _, j in spans[w]],
                       edgecolor="black", linewidth=0.3)
    ax.set_yticks(range(len(workers)))
    ax.set_yticklabels([f"worker {w}" for w in workers])
    ax.set_xlabel("time (s)")
    ax.grid(axis="x", alpha=0.3)
    handles = [plt.Rectangle((0, 0), 1, 1, color=color[j]) for j in jobs]
    ax.legend(handles, [f"job {j}" for j in jobs], ncol=min(len(jobs), 6),
              fontsize=7, loc="upper center", bbox_to_anchor=(0.5, -0.18))
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_utilization(results: Dict[str, dict], output: str) -> str:
    """Per-round cluster utilization timeline per policy."""
    fig, ax = plt.subplots(figsize=(6, 3.5))
    for label, metrics in results.items():
        util = metrics.get("utilization_list") or []
        ax.plot(range(len(util)), util, label=label, linewidth=0.9)
    ax.set_xlabel("round")
    ax.set_ylabel("cluster utilization")
    ax.set_ylim(0, 1.05)
    ax.grid(alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def _load(pairs: List[str]) -> Dict[str, dict]:
    results = {}
    for pair in pairs:
        label, path = pair.split("=", 1)
        with open(path, "rb") as f:
            results[label] = pickle.load(f)
    return results


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--metric", required=True,
                   choices=["jct", "ftf", "ftf_themis", "bars", "heatmap",
                            "utilization", "gantt"])
    p.add_argument("--pickles", nargs="+", default=None,
                   help="label=path pairs of driver metric pickles")
    p.add_argument("--bar_metric", default="makespan")
    p.add_argument("--timeline_dir", default=None,
                   help="gantt only: physical run timeline dir for "
                        "exact in-lease spans instead of round-"
                        "quantized pickle spans")
    p.add_argument("--output", required=True)
    args = p.parse_args()
    if not args.pickles and not (args.metric == "gantt"
                                 and args.timeline_dir):
        p.error("--pickles is required (except gantt --timeline_dir)")

    results = _load(args.pickles or [])
    if args.metric == "jct":
        plot_jct_cdf(results, args.output)
    elif args.metric == "ftf":
        plot_ftf_cdf(results, args.output)
    elif args.metric == "ftf_themis":
        plot_ftf_cdf(results, args.output, themis=True)
    elif args.metric == "bars":
        plot_policy_bars(results, args.output, metric=args.bar_metric)
    elif args.metric == "heatmap":
        plot_schedule_heatmap(next(iter(results.values())), args.output)
    elif args.metric == "utilization":
        plot_utilization(results, args.output)
    elif args.metric == "gantt":
        plot_worker_gantt(
            next(iter(results.values())) if results else None,
            args.output, timeline_dir=args.timeline_dir)
    print(args.output)


if __name__ == "__main__":
    main()
