"""Max-min fairness policies ("Gavel" in the paper).

LP: maximize the minimum (priority- and proportional-share-normalized)
effective throughput across jobs (reference:
scheduler/policies/max_min_fairness.py:86-108). The `WithPerf` variant
uses real throughputs; the base variant first replaces all throughputs
with 1.0 so only time shares matter.
"""
from __future__ import annotations

import numpy as np

from .lp import LinearProgram
from .policy import Policy, PolicyWithPacking
from .simple import ProportionalPolicy


class MaxMinFairnessPolicyWithPerf(Policy):
    name = "MaxMinFairness_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._proportional = ProportionalPolicy()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        m, n = throughputs.shape
        job_ids, worker_types = index

        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        priority = np.array([1.0 / unflattened_priority_weights[j] for j in job_ids])
        proportional = self._proportional.get_throughputs(throughputs, index, cluster_spec)
        weights = priority.reshape((m, 1)) / proportional.reshape((m, 1))

        # Effective rate coefficients: throughput * weight * scale_factor.
        coeff = throughputs * weights * sf

        # Variables: x (m*n) then t; maximize t s.t. coeff_i . x_i >= t.
        lp = LinearProgram(m * n + 1)
        t = m * n
        lp.bounds[t] = (None, None)
        for i in range(m):
            row = lp.row()
            row[i * n:(i + 1) * n] = -coeff[i]
            row[t] = 1.0
            lp.add_le(row, 0.0)
        for row, rhs in zip(*self.cluster_capacity_rows(m, n, sf, self._num_workers, 1)):
            lp.add_le(row, rhs)
        for row, rhs in zip(*self.job_time_rows(m, n, 1)):
            lp.add_le(row, rhs)
        c = np.zeros(m * n + 1)
        c[t] = -1.0
        res = lp.minimize(c).solve()
        if not res.success:
            return None
        x = res.x[:m * n].reshape((m, n)).clip(0.0, 1.0)
        return self.unflatten(x, index)


class MaxMinFairnessPolicy(Policy):
    """Throughput-agnostic max-min: all throughputs forced to 1.0."""

    name = "MaxMinFairness"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._perf = MaxMinFairnessPolicyWithPerf(solver)

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       priority_weights, cluster_spec):
        ones = {
            job_id: {wt: 1.0 for wt in per_wt}
            for job_id, per_wt in unflattened_throughputs.items()
        }
        if not ones:
            return None
        return self._perf.get_allocation(ones, scale_factors, priority_weights,
                                         cluster_spec)


class MaxMinFairnessStrategyProofPolicy(MaxMinFairnessPolicy):
    """Strategy-proof entry point: throughput-agnostic max-min, so a job
    cannot gain by misreporting throughputs
    (reference: policies/max_min_fairness_strategy_proof.py:13-46)."""

    name = "MaxMinFairness"


class MaxMinFairnessPolicyWithPacking(PolicyWithPacking):
    name = "MaxMinFairness_Packing"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._proportional = ProportionalPolicy()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, cluster_spec):
        tensor, index = self.flatten(unflattened_throughputs, cluster_spec,
                                     unflattened_priority_weights)
        if tensor is None or len(tensor) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        num_singles, m, n = tensor.shape

        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        E, fixed = self.normalized_effective_rows(
            tensor, index, sf, unflattened_throughputs, cluster_spec,
            self._proportional)

        lp = LinearProgram(m * n + 1)
        t = m * n
        lp.bounds[t] = (None, None)
        for si in range(num_singles):
            row = lp.row()
            row[:m * n] = -E[si]
            row[t] = 1.0
            lp.add_le(row, 0.0)
        for row, rhs in zip(*self.cluster_capacity_rows(m, n, sf, self._num_workers, 1)):
            lp.add_le(row, rhs)
        for row, rhs in zip(*self.per_job_time_rows(job_ids, single_job_ids,
                                                    relevant, n, 1)):
            lp.add_le(row, rhs)
        # Zero out combos with mismatched scale factors.
        for v in fixed:
            lp.bounds[v] = (0, 0)
        c = np.zeros(m * n + 1)
        c[t] = -1.0
        res = lp.minimize(c).solve()
        if not res.success:
            return None
        x = res.x[:m * n].reshape((m, n)).clip(0.0, 1.0)
        return self.unflatten(x, index)
