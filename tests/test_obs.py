"""Observability subsystem tests: registry semantics + concurrency
(under the lock sanitizer), golden Chrome-trace export, the report CLI,
the /metrics + /healthz endpoint (unit and scraped mid-run through a
real loopback scheduler), and obs-on/off simulator determinism."""
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.obs import Observability, names
from shockwave_tpu.obs.exporter import ObsHttpServer
from shockwave_tpu.obs.names import MetricSpec
from shockwave_tpu.obs.registry import MetricsRegistry
from shockwave_tpu.obs.report import load_spans, phase_table, render
from shockwave_tpu.obs.tracing import Tracer

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DATA = os.path.join(REPO, "data")


class SteppingClock:
    """Deterministic clock: every read advances by `step`."""

    def __init__(self, start=100.0, step=1.0):
        self.now = start
        self.step = step

    def __call__(self):
        t = self.now
        self.now += self.step
        return t


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """Exposition text -> {(name, frozenset(label pairs)): value}.
    Doubles as the 'is this parseable' check: any malformed sample
    line raises."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        body, value = line.rsplit(" ", 1)
        if "{" in body:
            name, labels_body = body.split("{", 1)
            assert labels_body.endswith("}")
            labels = _LABEL_RE.findall(labels_body[:-1])
            key = (name, frozenset(labels))
        else:
            key = (body, frozenset())
        samples[key] = float(value)
    return samples


COUNTER = MetricSpec("test_events_total", "counter", "events", ("kind",))
GAUGE = MetricSpec("test_depth", "gauge", "depth")
HIST = MetricSpec("test_latency_seconds", "histogram", "latency",
                  ("op",), (0.1, 1.0, 10.0))


class TestRegistry:
    def test_counter_accumulates_per_label(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind="a")
        reg.inc(COUNTER, amount=2.5, kind="a")
        reg.inc(COUNTER, kind="b")
        assert reg.value(COUNTER, kind="a") == 3.5
        assert reg.value(COUNTER, kind="b") == 1.0
        assert reg.value(COUNTER, kind="never") == 0.0

    def test_gauge_overwrites(self):
        reg = MetricsRegistry()
        reg.set_gauge(GAUGE, 4)
        reg.set_gauge(GAUGE, 2)
        assert reg.value(GAUGE) == 2.0

    def test_histogram_buckets_and_stats(self):
        reg = MetricsRegistry()
        for v in (0.05, 0.5, 5.0, 50.0):
            reg.observe(HIST, v, op="x")
        count, total = reg.histogram_stats(HIST, op="x")
        assert count == 4
        assert total == pytest.approx(55.55)
        samples = parse_prometheus(reg.render_prometheus())
        le = lambda b: samples[("test_latency_seconds_bucket",
                                frozenset({("op", "x"), ("le", b)}))]
        assert le("0.1") == 1        # cumulative
        assert le("1") == 2
        assert le("10") == 3
        assert le("+Inf") == 4

    def test_kind_and_label_misuse_raise(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.inc(GAUGE)                       # wrong kind
        with pytest.raises(ValueError):
            reg.observe(COUNTER, 1.0, kind="a")  # wrong kind
        with pytest.raises(ValueError):
            reg.inc(COUNTER)                     # missing label
        with pytest.raises(ValueError):
            reg.inc(COUNTER, kind="a", extra="b")
        with pytest.raises(ValueError):
            reg.inc(COUNTER, amount=-1, kind="a")

    def test_timed_uses_injected_clock(self):
        clock = SteppingClock(step=2.0)
        reg = MetricsRegistry(clock=clock)
        with reg.timed(HIST, op="solve"):
            pass
        count, total = reg.histogram_stats(HIST, op="solve")
        assert (count, total) == (1, 2.0)  # exactly one clock step

    def test_disabled_registry_records_nothing(self):
        reg = MetricsRegistry(enabled=False)
        reg.inc(COUNTER, kind="a")
        reg.set_gauge(GAUGE, 9)
        reg.observe(HIST, 1.0, op="x")
        assert reg.render_prometheus().strip() == ""

    def test_rendering_is_parseable_and_typed(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind='we"ird\nlabel')
        reg.set_gauge(GAUGE, 1.5)
        text = reg.render_prometheus()
        assert "# TYPE test_events_total counter" in text
        assert "# HELP test_depth depth" in text
        samples = parse_prometheus(text)
        assert samples[("test_depth", frozenset())] == 1.5


@pytest.mark.runtime
class TestRegistryConcurrency:
    """Exact counts under thread contention, with the registry lock
    instrumented by the sanitizer (the conftest `runtime`-marker
    fixture sets SWTPU_SANITIZE=1 and asserts a clean report)."""

    def test_parallel_increments_are_exact(self):
        reg = MetricsRegistry()
        n_threads, n_ops = 8, 2000
        barrier = threading.Barrier(n_threads)

        def worker(k):
            barrier.wait()
            for _ in range(n_ops):
                reg.inc(COUNTER, kind="shared")
                reg.observe(HIST, 0.5, op=f"t{k % 2}")

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.value(COUNTER, kind="shared") == n_threads * n_ops
        c0, _ = reg.histogram_stats(HIST, op="t0")
        c1, _ = reg.histogram_stats(HIST, op="t1")
        assert c0 + c1 == n_threads * n_ops


class TestTracer:
    def test_golden_chrome_trace_export(self, tmp_path):
        clock = SteppingClock(start=10.0, step=1.0)
        tracer = Tracer(clock=clock)
        with tracer.span(names.SPAN_SOLVE, round=0):       # t=10..13
            with tracer.span(names.SPAN_DISPATCH, round=0):  # t=11..12
                pass
        path = str(tmp_path / "trace.json")
        tracer.export_chrome_trace(path)
        with open(path) as f:
            trace = json.load(f)
        golden = [
            {"name": "dispatch", "ph": "X", "cat": "swtpu",
             "ts": 11_000_000.0, "dur": 1_000_000.0,
             "args": {"round": 0}},
            {"name": "solve", "ph": "X", "cat": "swtpu",
             "ts": 10_000_000.0, "dur": 3_000_000.0,
             "args": {"round": 0}},
        ]
        # Span identities (trace_id/span_id/parent_id) ride in args
        # since the fleet-tracing work; strip them for the golden
        # compare and assert them separately below.
        id_keys = ("trace_id", "span_id", "parent_id")
        got = [{k: (({a: v for a, v in e[k].items()
                      if a not in id_keys}) if k == "args" else e[k])
                for k in ("name", "ph", "cat", "ts", "dur", "args")}
               for e in trace["traceEvents"]]
        assert got == golden
        events = trace["traceEvents"]
        assert all("trace_id" in e["args"] and "span_id" in e["args"]
                   for e in events)
        # Nesting yields parent links within one trace: the dispatch
        # span's parent is the solve span, and both share a trace id.
        dispatch, solve = events[0], events[1]
        assert dispatch["args"]["parent_id"] == solve["args"]["span_id"]
        assert dispatch["args"]["trace_id"] == solve["args"]["trace_id"]
        assert "parent_id" not in solve["args"]  # root
        assert trace["displayTimeUnit"] == "ms"
        # pid/tid present on every event (Perfetto requires them).
        assert all("pid" in e and "tid" in e for e in trace["traceEvents"])

    def test_ring_buffer_bounds_memory(self):
        tracer = Tracer(clock=SteppingClock(), max_events=3)
        for i in range(10):
            with tracer.span(names.SPAN_WAIT, i=i):
                pass
        events = tracer.events()
        assert len(events) == 3
        assert [e["args"]["i"] for e in events] == [7, 8, 9]

    def test_disabled_tracer_records_nothing(self):
        tracer = Tracer(enabled=False)
        with tracer.span(names.SPAN_WAIT) as ctx:
            assert ctx is None
        assert tracer.events() == []

    def test_remote_parent_splices_cross_process_context(self):
        """A span opened with an explicit remote parent joins that
        trace and links to the remote span id — the worker-daemon /
        trainer adoption path."""
        from shockwave_tpu.obs.propagation import SpanContext
        remote = SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
        tracer = Tracer(clock=SteppingClock())
        with tracer.span(names.SPAN_WAIT, parent=remote) as ctx:
            assert ctx.trace_id == remote.trace_id
            assert ctx.span_id != remote.span_id
        event = tracer.events()[0]
        assert event["parent_id"] == remote.span_id
        assert event["trace_id"] == remote.trace_id

    def test_record_span_pins_identity_for_late_roots(self):
        """record_span writes a span under a pre-allocated context so
        children created earlier link to it (the scheduler's per-round
        root span, recorded at round end)."""
        from shockwave_tpu.obs.propagation import new_root_context
        tracer = Tracer(clock=SteppingClock())
        root = new_root_context()
        with tracer.span(names.SPAN_SOLVE, parent=root):
            pass
        tracer.record_span(names.SPAN_ROUND, ts=0.0, dur=5.0,
                           context=root, round=3)
        solve, round_span = tracer.events()
        assert solve["parent_id"] == root.span_id
        assert round_span["span_id"] == root.span_id
        assert round_span["trace_id"] == solve["trace_id"]


class TestPropagation:
    def test_traceparent_roundtrip(self):
        from shockwave_tpu.obs import propagation as prop
        ctx = prop.new_root_context()
        assert prop.parse_traceparent(prop.format_traceparent(ctx)) == ctx

    def test_malformed_traceparent_is_none(self):
        from shockwave_tpu.obs import propagation as prop
        for bad in (None, "", "junk", "00-zz-yy-01",
                    "01-" + "a" * 32 + "-" + "b" * 16 + "-01-extra"):
            assert prop.parse_traceparent(bad) is None

    def test_rpc_metadata_roundtrip(self):
        from shockwave_tpu.obs import propagation as prop
        ctx = prop.new_root_context()
        metadata = prop.rpc_metadata(ctx, send_ts=42.5)
        got, send_ts = prop.from_rpc_metadata(metadata)
        assert got == ctx and send_ts == 42.5
        assert prop.rpc_metadata(None) == ()
        assert prop.from_rpc_metadata(None) == (None, None)

    def test_environ_roundtrip(self):
        from shockwave_tpu.obs import propagation as prop
        ctx = prop.new_root_context()
        env = prop.to_environ(ctx, {})
        assert prop.from_environ(env) == ctx
        assert prop.from_environ({}) is None

    def test_ids_are_unique_and_well_formed(self):
        from shockwave_tpu.obs import propagation as prop
        trace_ids = {prop.new_trace_id() for _ in range(100)}
        span_ids = {prop.new_span_id() for _ in range(100)}
        assert len(trace_ids) == 100 and len(span_ids) == 100
        assert all(len(t) == 32 for t in trace_ids)
        assert all(len(s) == 16 for s in span_ids)


class TestShardMerge:
    def _shard(self, tmp_path, role, host, spans):
        from shockwave_tpu.core.durable_io import write_text_atomic
        path = os.path.join(str(tmp_path),
                            names.shard_filename(role, sum(host.encode())))
        write_text_atomic(path, json.dumps(
            {"schema": 1, "role": role, "pid": 1, "host": host,
             "spans": spans}))
        return path

    def test_shard_writer_flush_and_load(self, tmp_path):
        from shockwave_tpu.obs.shard import (ShardSpanWriter,
                                             discover_shards, load_shard)
        shard = ShardSpanWriter(str(tmp_path), role="worker",
                                clock=SteppingClock())
        span = shard.open_span(names.SPAN_LAUNCH, job=7)
        shard.close_span(span, steps=123)
        with shard.span(names.SPAN_RUNJOB, parent=span.context,
                        round=2):
            pass
        path = shard.flush()
        assert path in discover_shards(str(tmp_path))
        payload = load_shard(path)
        assert payload["role"] == "worker"
        by_name = {s["name"]: s for s in payload["spans"]}
        assert by_name["launch"]["args"]["steps"] == 123
        assert (by_name["runjob"]["parent_id"]
                == by_name["launch"]["span_id"])

    def test_load_shard_tolerates_garbage(self, tmp_path):
        from shockwave_tpu.obs.shard import load_shard
        bad = tmp_path / "spans-x-1.json"
        bad.write_text("{not json")
        assert load_shard(str(bad)) is None
        assert load_shard(str(tmp_path / "missing.json")) is None

    def test_merge_aligns_remote_host_clock(self, tmp_path):
        """A worker shard whose clock runs 100s ahead is shifted back
        by the min (recv - send) over its RPC pairs; the scheduler
        host is the reference."""
        from shockwave_tpu.obs.merge import merge_directory
        self._shard(tmp_path, "scheduler", "host-a", [
            {"name": "runjob-rpc", "ts": 10.0, "dur": 0.01,
             "trace_id": "t1", "span_id": "s1", "parent_id": None,
             "args": {}}])
        self._shard(tmp_path, "worker", "host-b", [
            {"name": "runjob", "ts": 110.2, "dur": 0.5,
             "trace_id": "t1", "span_id": "s2", "parent_id": "s1",
             "args": {"send_ts": 10.0}},
            {"name": "runjob", "ts": 140.1, "dur": 0.5,
             "trace_id": "t1", "span_id": "s3", "parent_id": "s1",
             "args": {"send_ts": 40.0}}])
        summary = merge_directory(str(tmp_path))
        assert summary["shards"] == 2 and summary["spans"] == 3
        # min(110.2-10, 140.1-40) = 100.1 subtracted from host-b.
        assert summary["offsets"]["host-b"] == pytest.approx(100.1)
        assert summary["offsets"]["host-a"] == 0.0
        with open(summary["out"]) as f:
            merged = json.load(f)
        worker_spans = [e for e in merged["traceEvents"]
                        if (e.get("args") or {}).get("role") == "worker"]
        # 110.2 - 100.1 = 10.1s -> microseconds.
        assert min(e["ts"] for e in worker_spans) == pytest.approx(
            10.1e6, rel=1e-6)

    def test_parent_chain_walks_across_shards(self, tmp_path):
        from shockwave_tpu.obs.merge import (merge_directory,
                                             parent_chain, spans_by_id)
        self._shard(tmp_path, "scheduler", "h", [
            {"name": "round", "ts": 0.0, "dur": 2.0, "trace_id": "t",
             "span_id": "root", "parent_id": None, "args": {}},
            {"name": "runjob-rpc", "ts": 0.5, "dur": 0.01,
             "trace_id": "t", "span_id": "rpc", "parent_id": "root",
             "args": {}}])
        self._shard(tmp_path, "trainer", "h", [
            {"name": "trainer", "ts": 0.6, "dur": 1.0, "trace_id": "t",
             "span_id": "tr", "parent_id": "rpc", "args": {"job": 0}}])
        summary = merge_directory(str(tmp_path))
        with open(summary["out"]) as f:
            events = json.load(f)["traceEvents"]
        index = spans_by_id(events)
        trainer = next(e for e in events if e.get("name") == "trainer")
        chain = [c["name"] for c in parent_chain(index, trainer)]
        assert chain == ["trainer", "runjob-rpc", "round"]

    def test_merge_cli(self, tmp_path):
        self._shard(tmp_path, "scheduler", "h", [
            {"name": "solve", "ts": 0.0, "dur": 1.0, "trace_id": "t",
             "span_id": "a", "parent_id": None, "args": {}}])
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.merge",
             str(tmp_path)], capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        summary = json.loads(out.stdout)
        assert summary["shards"] == 1
        empty = tmp_path / "empty"
        empty.mkdir()
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.merge",
             str(empty)], capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1


class TestReport:
    def _write_trace(self, tmp_path):
        clock = SteppingClock(start=0.0, step=0.5)
        tracer = Tracer(clock=clock)
        for rnd in range(2):
            with tracer.span(names.SPAN_SOLVE, round=rnd):
                pass
            with tracer.span(names.SPAN_DISPATCH, round=rnd):
                pass
            # Round-less span (journal fsync fires from RPC threads):
            # attributed to the round whose window contains it.
            with tracer.span(names.SPAN_JOURNAL_FSYNC, etype="x"):
                pass
            with tracer.span(names.SPAN_WAIT, round=rnd):
                pass
            with tracer.span(names.SPAN_END_ROUND, round=rnd):
                pass
        path = str(tmp_path / "trace.json")
        tracer.export_chrome_trace(path)
        return path

    def test_phase_table_assigns_roundless_spans(self, tmp_path):
        spans = load_spans(self._write_trace(tmp_path))
        rounds, per_round, totals = phase_table(spans)
        assert rounds == [0, 1]
        for rnd in (0, 1):
            assert per_round[rnd][names.SPAN_JOURNAL_FSYNC] > 0
        assert totals[names.SPAN_SOLVE][0] == 2

    def test_render_has_all_phase_columns(self, tmp_path):
        spans = load_spans(self._write_trace(tmp_path))
        table = render(spans)
        for phase in names.REPORT_PHASES:
            assert phase in table
        assert "total_s" in table and "mean_s" in table

    def test_cli_prints_table(self, tmp_path):
        path = self._write_trace(tmp_path)
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report", path],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "solve" in out.stdout
        assert "journal-fsync" in out.stdout

    def test_cli_fails_on_empty_trace(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text('{"traceEvents": []}')
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report", str(path)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1


class TestCatalog:
    def test_catalog_covers_every_spec(self):
        from shockwave_tpu.obs.catalog import catalog_markdown
        table = catalog_markdown()
        for spec in names.all_metric_specs():
            assert spec.name in table

    def test_readme_contains_every_metric(self):
        """README's generated catalog must not drift from names.py."""
        with open(os.path.join(REPO, "README.md")) as f:
            readme = f.read()
        for spec in names.all_metric_specs():
            assert spec.name in readme, (
                f"{spec.name} missing from README.md — regenerate the "
                "catalog with `python -m shockwave_tpu.obs.catalog`")


class TestExporter:
    def test_metrics_and_healthz_roundtrip(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind="a")
        server = ObsHttpServer(
            reg, health_fn=lambda: {"round": 7, "live_workers": 2},
            addr="127.0.0.1", port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                samples = parse_prometheus(r.read().decode())
            assert samples[("test_events_total",
                            frozenset({("kind", "a")}))] == 1.0
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                health = json.loads(r.read())
            assert health == {"round": 7, "live_workers": 2,
                              "status": "ok"}
            try:
                urllib.request.urlopen(base + "/nope", timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_failing_health_callback_returns_500(self):
        def broken():
            raise RuntimeError("wedged")

        server = ObsHttpServer(MetricsRegistry(), health_fn=broken,
                               addr="127.0.0.1", port=0).start()
        try:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/healthz", timeout=5)
                assert False, "expected 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                body = json.loads(e.read())
                assert body["status"] == "error"
                assert "wedged" in body["error"]
        finally:
            server.stop()

    def test_history_endpoint_404_without_history(self):
        """A process keeping no telemetry history (e.g. an HA standby)
        answers /history.json with 404, not an error page."""
        server = ObsHttpServer(MetricsRegistry(), addr="127.0.0.1",
                               port=0).start()
        try:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/history.json",
                    timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert json.loads(e.read())["status"] == "no_history"
        finally:
            server.stop()

    def test_history_endpoint_serves_payload(self, tmp_path):
        from shockwave_tpu.obs.history import TelemetryHistory
        reg = MetricsRegistry()
        hist = TelemetryHistory(reg, SteppingClock(),
                                str(tmp_path / "history.json"))
        hist.record_observation("ResNet-18", 32, 1, "v5e", 50.0, 0)
        hist.sample_round(1)
        server = ObsHttpServer(reg, history_fn=hist.payload,
                               addr="127.0.0.1", port=0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/history.json",
                    timeout=5) as r:
                payload = json.loads(r.read())
            assert len(payload["rounds"]) == 1
            assert payload["observations"] == [
                [0, "ResNet-18", 32, 1, "v5e", 50.0]]
            assert set(payload["alerts"]) == {
                "round_overrun", "dispatch_failure_burn",
                "throughput_regression"}
        finally:
            server.stop()


class TestTelemetryHistory:
    def _history(self, tmp_path, clock=None, **kwargs):
        from shockwave_tpu.obs.history import TelemetryHistory
        reg = MetricsRegistry()
        return reg, TelemetryHistory(
            reg, clock or SteppingClock(),
            str(tmp_path / "history.json"), **kwargs)

    def test_round_samples_snapshot_every_metric(self, tmp_path):
        reg, hist = self._history(tmp_path)
        reg.inc(COUNTER, kind="a")
        reg.set_gauge(GAUGE, 7)
        reg.observe(HIST, 0.5, op="x")
        hist.sample_round(1)
        entry = hist.payload()["rounds"][0]
        assert entry["round"] == 1
        assert entry["metrics"]["test_events_total{a}"] == 1.0
        assert entry["metrics"]["test_depth"] == 7.0
        assert entry["metrics"]["test_latency_seconds_count{x}"] == 1.0

    def test_ring_is_bounded(self, tmp_path):
        _, hist = self._history(tmp_path, max_rounds=4,
                                max_observations=8,
                                flush_interval_rounds=1000)
        for r in range(10):
            hist.sample_round(r)
            for _ in range(3):
                hist.record_observation("t", 32, 1, "v5e", 10.0, r)
        payload = hist.payload()
        assert [e["round"] for e in payload["rounds"]] == [6, 7, 8, 9]
        assert len(payload["observations"]) == 8

    def test_flush_and_reload_survive_restart(self, tmp_path):
        reg, hist = self._history(tmp_path)
        hist.record_observation("t", 32, 1, "v5e", 10.0, 0)
        hist.sample_round(1)
        hist.flush()
        # A new incarnation (promoted standby / restarted scheduler)
        # reloads the ring and keeps appending.
        reg2, hist2 = self._history(tmp_path)
        hist2.sample_round(2)
        payload = hist2.payload()
        assert [e["round"] for e in payload["rounds"]] == [1, 2]
        assert payload["observations"] == [[0, "t", 32, 1, "v5e", 10.0]]

    def test_round_overrun_alert(self, tmp_path):
        from shockwave_tpu.obs import history as hist_mod

        class JumpClock:
            def __init__(self):
                self.now = 0.0

            def __call__(self):
                return self.now

        clock = JumpClock()
        reg, hist = self._history(tmp_path, clock=clock)
        hist._time_per_iteration = 10.0
        hist.sample_round(1)
        clock.now = 11.0  # within 1.5x
        hist.sample_round(2)
        assert hist.alerts[hist_mod.CHECK_ROUND_OVERRUN] == 0
        clock.now = 40.0  # 29s round >> 15s
        hist.sample_round(3)
        assert hist.alerts[hist_mod.CHECK_ROUND_OVERRUN] == 1
        assert reg.value(names.ALERT,
                         check=hist_mod.CHECK_ROUND_OVERRUN) == 1.0

    def test_dispatch_burn_alert(self, tmp_path):
        from shockwave_tpu.obs import history as hist_mod
        reg, hist = self._history(tmp_path)
        hist.sample_round(0)
        reg.inc(names.DISPATCHES_TOTAL, amount=10, outcome="ok")
        hist.sample_round(1)
        assert hist.alerts[hist_mod.CHECK_DISPATCH_BURN] == 0
        reg.inc(names.DISPATCHES_TOTAL, amount=9, outcome="unavailable")
        hist.sample_round(2)
        assert hist.alerts[hist_mod.CHECK_DISPATCH_BURN] == 1

    def test_throughput_regression_alert(self, tmp_path):
        from shockwave_tpu.obs import history as hist_mod
        reg, hist = self._history(tmp_path)
        for r in range(6):
            hist.record_observation("t", 32, 1, "v5e", 100.0, r)
        hist.sample_round(6)
        assert hist.alerts[hist_mod.CHECK_THROUGHPUT_REGRESSION] == 0
        for r in range(3):
            hist.record_observation("t", 32, 1, "v5e", 40.0, 7 + r)
        hist.sample_round(10)
        assert hist.alerts[hist_mod.CHECK_THROUGHPUT_REGRESSION] == 1
        assert reg.value(
            names.ALERT,
            check=hist_mod.CHECK_THROUGHPUT_REGRESSION) == 1.0


class TestExplain:
    """Unit tests of the journal -> per-job timeline derivation on a
    synthetic event stream (the loopback acceptance runs in
    scripts/tests/trace_smoke.py and the CI trace-smoke job)."""

    def _events(self):
        def ev(seq, etype, t=0.0, **data):
            return {"seq": seq, "type": etype, "t": t, "data": data}
        # Round 0: job 0 runs, job 1 queued. Round 1: job 1 runs,
        # job 0 preempted-waits. Round 2: job 0's microtask FAILS
        # (worker death; compensated). Round 3: job 0 reruns and both
        # complete.
        return [
            ev(1, "job_added", t=0.0, int_id=0,
               job={"job_type": "ResNet-18", "scale_factor": 1}),
            ev(2, "job_added", t=0.1, int_id=1,
               job={"job_type": "Transformer", "scale_factor": 1,
                    "trace_position": 3}),
            ev(3, "round_recorded", round=0, assignments=[[0, [5]]]),
            ev(4, "microtask_done", t=1.0, key=0,
               updates=[[5, [200], [1.5]]]),
            ev(5, "round_ended", t=2.0, round=1),
            ev(6, "round_recorded", round=1, assignments=[[1, [5]]]),
            ev(7, "microtask_done", t=3.0, key=1,
               updates=[[5, [150], [1.4]]]),
            ev(8, "round_ended", t=4.0, round=2),
            ev(9, "round_recorded", round=2, assignments=[[0, [5]]]),
            ev(10, "failure_comp", int_id=0),
            ev(11, "microtask_done", t=5.0, key=0,
               updates=[[5, [0], [0.0]]]),
            ev(12, "round_ended", t=6.0, round=3),
            ev(13, "round_recorded", round=3,
               assignments=[[0, [5]], [1, [6]]]),
            ev(14, "microtask_done", t=7.0, key=0,
               updates=[[5, [200], [1.5]]]),
            ev(15, "microtask_done", t=7.1, key=1,
               updates=[[6, [150], [1.4]]]),
            ev(16, "job_removed", t=7.5, int_id=0, ts=7.5),
            ev(17, "job_removed", t=7.6, int_id=1, ts=7.6),
            ev(18, "round_ended", t=8.0, round=4),
        ]

    def test_phases_and_full_coverage(self):
        from shockwave_tpu.obs import explain as ex
        tl = ex.build_timeline(self._events(), 0)
        phases = tl.phases()
        assert phases == {0: ex.PHASE_RUN, 1: ex.PHASE_PREEMPTED,
                          2: ex.PHASE_RESTART, 3: ex.PHASE_RUN}
        totals = tl.phase_totals()
        assert sum(totals.values()) == len(phases)  # 100% coverage
        assert tl.failure_comps == 1

    def test_queue_wait_and_deferral_marker(self):
        from shockwave_tpu.obs import explain as ex
        tl = ex.build_timeline(self._events(), 1)
        phases = tl.phases()
        assert phases[0] == ex.PHASE_QUEUE
        assert phases[1] == ex.PHASE_RUN
        assert tl.deferred  # trace_position rode job_added
        text = ex.render(tl)
        assert "deferred" in text
        assert "100.0%" in text

    def test_quarantine_migration_classification(self):
        from shockwave_tpu.obs import explain as ex

        def ev(seq, etype, **data):
            return {"seq": seq, "type": etype, "t": 0.0, "data": data}
        events = [
            ev(1, "job_added", int_id=0, job={"job_type": "t",
                                              "scale_factor": 1}),
            ev(2, "round_recorded", round=0, assignments=[[0, [5]]]),
            ev(3, "worker_quarantined", addr="h", port=1,
               worker_ids=[5]),
            ev(4, "microtask_done", key=0, updates=[[5, [0], [0.0]]]),
            ev(5, "round_ended", round=1),
            ev(6, "round_recorded", round=1, assignments=[[0, [6]]]),
            ev(7, "microtask_done", key=0, updates=[[6, [100], [1.0]]]),
            ev(8, "job_removed", int_id=0, ts=1.0),
            ev(9, "round_ended", round=2),
        ]
        tl = ex.build_timeline(events, 0)
        assert tl.phases() == {0: ex.PHASE_QUARANTINE, 1: ex.PHASE_RUN}

    def test_unknown_job_reports_cleanly(self):
        from shockwave_tpu.obs import explain as ex
        tl = ex.build_timeline(self._events(), 99)
        assert "no job_added" in ex.render(tl)

    def test_wall_attribution_covers_jct(self):
        from shockwave_tpu.obs import explain as ex
        tl = ex.build_timeline(self._events(), 0)
        text = ex.render(tl, wall=True)
        m = re.search(r"wall: jct ([0-9.]+)s, attributed ([0-9.]+)s "
                      r"\(([0-9.]+)%\)", text)
        assert m, text
        assert float(m.group(3)) >= 99.0

    def test_cli_reads_a_real_journal(self, tmp_path):
        from shockwave_tpu.sched.journal import DurabilityLayer
        layer = DurabilityLayer(str(tmp_path), obs=Observability(
            clock=SteppingClock(), enabled=False))
        for rec in self._events():
            layer.record(rec["type"], rec["data"])
        layer.close()
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.explain", "0",
             "--state_dir", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "jct 4 rounds" in out.stdout
        assert "restart" in out.stdout


class TestReportCompare:
    def _trace(self, tmp_path, name, solve_s):
        clock = SteppingClock(start=0.0, step=solve_s)
        tracer = Tracer(clock=clock)
        for rnd in range(3):
            with tracer.span(names.SPAN_SOLVE, round=rnd):
                pass
            with tracer.span(names.SPAN_DISPATCH, round=rnd):
                pass
        path = str(tmp_path / name)
        tracer.export_chrome_trace(path)
        return path

    def test_compare_passes_within_threshold(self, tmp_path):
        from shockwave_tpu.obs.report import compare
        a = self._trace(tmp_path, "a.json", 1.0)
        b = self._trace(tmp_path, "b.json", 1.1)
        text, regressed = compare(a, b, threshold=0.25)
        assert regressed == []
        assert "solve" in text

    def test_compare_flags_regression_and_cli_exits_2(self, tmp_path):
        from shockwave_tpu.obs.report import compare
        a = self._trace(tmp_path, "a.json", 1.0)
        b = self._trace(tmp_path, "b.json", 2.0)
        _, regressed = compare(a, b, threshold=0.25)
        assert names.SPAN_SOLVE in regressed
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report",
             "--compare", a, b], capture_output=True, text=True,
            cwd=REPO)
        assert out.returncode == 2, out.stdout + out.stderr
        assert "REGRESSED" in out.stdout


class _StubWorker:
    """Minimal in-process worker daemon (mirrors test_runtime's stub):
    simulates execution at a fixed throughput, no subprocesses."""

    def __init__(self, sched_port, worker_port, num_chips=2,
                 throughput=100.0, execution_time=0.4):
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self._iter_client = IteratorToSchedulerClient
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.server = serve_worker(worker_port, {
            "RunJob": self._run_job, "KillJob": lambda j: None,
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", worker_port, num_chips)

    def _run_job(self, jobs, worker_id, round_id):
        def execute():
            for j in jobs:
                it = self._iter_client(j["job_id"], worker_id,
                                       "localhost", self.sched_port)
                max_steps, _, _ = it.init()
            time.sleep(self.execution_time)
            steps = [min(int(self.throughput * self.round_duration),
                         j["num_steps"], int(max_steps)) for j in jobs]
            self._client.notify_done(
                [j["job_id"] for j in jobs], worker_id, steps,
                [self.execution_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    def stop(self):
        self.server.stop(grace=0)


@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestPhysicalObsLoopback:
    """Scrape /metrics and /healthz from a REAL loopback scheduler
    mid-run, then report on its exported trace — the acceptance drive
    for the endpoint and the round-phase spans."""

    def test_scrape_mid_run_and_report_after(self, tmp_path):
        from shockwave_tpu.sched.physical import PhysicalScheduler
        from shockwave_tpu.sched.scheduler import SchedulerConfig
        from shockwave_tpu.solver import get_policy
        sched_port, worker_port = free_port(), free_port()
        trace_path = str(tmp_path / "round_trace.json")
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=2.0, max_rounds=4,
                state_dir=str(tmp_path / "state"),
                snapshot_interval_rounds=2,
                obs_port=0, obs_trace_path=trace_path),
            expected_num_workers=2, port=sched_port)
        worker = _StubWorker(sched_port, worker_port, num_chips=2)
        base = f"http://127.0.0.1:{sched.obs_port}"
        try:
            for _ in range(2):
                sched.add_job(Job(
                    None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=600, duration=10000))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()

            # Mid-run scrape: poll until the first dispatch lands.
            deadline = time.time() + 30
            samples = {}
            while time.time() < deadline:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=5) as r:
                    samples = parse_prometheus(r.read().decode())
                if samples.get(("swtpu_dispatches_total",
                                frozenset({("outcome", "ok")})), 0) >= 1:
                    break
                time.sleep(0.2)
            assert samples.get(("swtpu_dispatches_total",
                                frozenset({("outcome", "ok")})), 0) >= 1
            # Journal fsync histogram is live (state_dir set).
            assert samples.get(("swtpu_journal_append_seconds_count",
                                frozenset({("sync", "true")})), 0) >= 1

            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                health = json.loads(r.read())
            assert health["status"] == "ok"
            assert health["live_workers"] == 2
            assert isinstance(health["round"], int)
            assert health["journal"]["last_seq"] >= 1
            assert isinstance(health["breakers"], dict)

            deadline = time.time() + 40
            while time.time() < deadline and len(sched._completed_jobs) < 2:
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 2

            # Final scrape: solve-time histogram and phase histogram.
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                samples = parse_prometheus(r.read().decode())
            assert samples.get(
                ("swtpu_allocation_solve_seconds_count",
                 frozenset({("policy", "MaxMinFairness")})), 0) >= 1
            assert samples.get(
                ("swtpu_round_phase_seconds_count",
                 frozenset({("phase", "solve")})), 0) >= 1
            assert samples[("swtpu_jobs_completed_total",
                            frozenset())] == 2.0
        finally:
            sched._done_event.set()
            worker.stop()
            sched.shutdown()
            sched._server.stop(grace=0)

        # Trace exported at shutdown; the report CLI digests it.
        assert os.path.exists(trace_path)
        span_names = {e["name"] for e in load_spans(trace_path)}
        for phase in (names.SPAN_SOLVE, names.SPAN_DISPATCH,
                      names.SPAN_WAIT, names.SPAN_END_ROUND,
                      names.SPAN_JOURNAL_FSYNC):
            assert phase in span_names, span_names
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report",
             trace_path], capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "journal-fsync" in out.stdout


@pytest.mark.runtime
@pytest.mark.timeout(180)
class TestFleetTraceLoopback:
    """ACCEPTANCE: a sanitizer-clean loopback drive (real scheduler,
    real worker daemon, real trainer subprocesses under the genuine
    LeaseIterator) yields ONE merged Perfetto trace in which a round's
    solve -> dispatch -> launch -> trainer -> done chain is connected
    by propagated span context across all three processes — asserted
    by walking parent links across the process boundaries."""

    def test_merged_trace_chains_across_processes(self, tmp_path):
        from shockwave_tpu.runtime.worker import WorkerDaemon
        from shockwave_tpu.sched.physical import PhysicalScheduler
        from shockwave_tpu.sched.scheduler import SchedulerConfig
        from shockwave_tpu.solver import get_policy
        sched_port, worker_port = free_port(), free_port()
        trace_dir = str(tmp_path / "trace")
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=3.0, max_rounds=8,
                state_dir=str(tmp_path / "state"),
                snapshot_interval_rounds=1000,
                obs_trace_dir=trace_dir, history={}),
            expected_num_workers=1, port=sched_port)
        daemon = WorkerDaemon(
            worker_type="v5e", sched_addr="127.0.0.1",
            sched_port=sched_port, worker_port=worker_port, num_chips=1,
            run_dirs={"static": REPO, "accordion": REPO, "gns": REPO,
                      "serving": REPO},
            data_dir=None, checkpoint_dir=str(tmp_path / "ckpt"),
            trace_dir=trace_dir)
        cmd = (f"{sys.executable} tests/toy_trainer.py "
               "--step_time 0.001 --chunk 150")
        job_id = sched.add_job(Job(
            None, "ResNet-18 (batch size 32)", cmd, "", "--num_steps",
            total_steps=300, duration=100000))
        runner = threading.Thread(target=sched.run, daemon=True)
        runner.start()
        try:
            deadline = time.time() + 90
            while (time.time() < deadline
                   and len(sched._completed_jobs) < 1):
                time.sleep(0.3)
            assert len(sched._completed_jobs) == 1
        finally:
            sched._done_event.set()
            daemon._shutdown()
            daemon.join()
            sched.shutdown()
            sched._server.stop(grace=0)

        # ONE merged trace, written by the scheduler's shutdown
        # collection, holding shards from all three process roles.
        from shockwave_tpu.obs.merge import parent_chain, spans_by_id
        merged_path = os.path.join(trace_dir, names.MERGED_TRACE_NAME)
        with open(merged_path) as f:
            merged = json.load(f)
        events = merged["traceEvents"]
        roles_present = {(e.get("args") or {}).get("role")
                         for e in events if e.get("ph") == "X"}
        assert {"scheduler", "worker", "trainer"} <= roles_present

        index = spans_by_id(events)
        trainers = [e for e in events if e.get("name") == "trainer"]
        assert trainers, "no trainer spans reached the merged trace"
        int_id = job_id.integer_job_id()
        connected = 0
        for trainer in trainers:
            assert (trainer.get("args") or {}).get("job") == int_id
            chain = parent_chain(index, trainer)
            chain_names = [c["name"] for c in chain]
            chain_roles = [(c.get("args") or {}).get("role")
                           for c in chain]
            # The chain must cross BOTH process boundaries and reach
            # the scheduler's round root.
            if (chain_names[0] == "trainer"
                    and "launch" in chain_names
                    and "runjob" in chain_names
                    and "runjob-rpc" in chain_names
                    and chain_names[-1] == "round"
                    and {"trainer", "worker",
                         "scheduler"} <= set(chain_roles)):
                connected += 1
                # The same round's solve span shares the trace id: the
                # whole solve->dispatch->launch->step->done story is
                # ONE trace.
                trace_id = (trainer.get("args") or {}).get("trace_id")
                solves = [e for e in events if e.get("name") == "solve"
                          and (e.get("args") or {}).get("trace_id")
                          == trace_id]
                assert len(solves) >= 1 or chain_names == [
                    "trainer", "launch", "runjob", "runjob-rpc",
                    "round"]  # round 0's solve ran pre-loop (startup)
        assert connected >= 1, [e.get("name") for e in events]

        # The trainer really consumed its budget through the chain.
        steps = sum((t.get("args") or {}).get("steps", 0)
                    for t in trainers)
        assert steps == 300


@pytest.mark.recovery
@pytest.mark.timeout(360)  # covers the summed internal wait budgets
class TestExporterUnderHAFailover:
    """Satellite: leader and standby both scraped mid-failover — no
    port clash (both exporters live concurrently), role blocks flip,
    and /history.json is served by whichever process holds the journal
    (404 on the standby; after promotion the successor serves a ring
    that includes pre-failover rounds reloaded from the state dir)."""

    def _get(self, port, path, timeout=5):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return json.loads(r.read())

    def test_history_follows_the_journal_holder(self, tmp_path):
        from test_ha import HA_JSON, _spawn, _wait_for_port
        state_dir = tmp_path / "state"
        trace = tmp_path / "obs_ha.trace"
        line = ("ResNet-18 (batch size 32)\tpython3 main.py "
                "--batch_size 32\timage_classification/cifar10\t"
                "--num_steps\t0\t600\t1\tstatic\t1\t-1.000000\t10000\t0")
        trace.write_text(line + "\n" + line + "\n")
        p1, p2 = free_port(), free_port()
        obs1, obs2 = free_port(), free_port()

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["SWTPU_HA_ENDPOINT_FILE"] = str(state_dir / "leader.lease")
        env["SWTPU_RPC_JITTER_SEED"] = "0"
        env["SWTPU_RPC_DEADLINE_S"] = "5"
        env["SWTPU_RPC_BUDGET_S"] = "8"
        run_physical = os.path.join(REPO, "scripts", "drivers",
                                    "run_physical.py")

        def sched_cmd(port, obs_port, out, standby=False):
            cmd = [sys.executable, run_physical, "--trace", str(trace),
                   "--policy", "max_min_fairness",
                   "--throughputs",
                   os.path.join(DATA, "tacc_throughputs.json"),
                   "--expected_num_workers", "1",
                   "--round_duration", "2", "--port", str(port),
                   "--state_dir", str(state_dir),
                   "--snapshot_interval", "4",
                   "--obs_port", str(obs_port),
                   "--history", '{"flush_interval_rounds": 1}',
                   "--output", str(out), "--ha", HA_JSON,
                   "--heartbeat_interval", "0.2",
                   "--worker_timeout", "1.0",
                   "--probe_failures", "2", "--kill_wait", "0.5",
                   "--completion_buffer", "5",
                   "--first_init_grace", "0", "--verbose"]
            if standby:
                cmd.append("--ha_standby")
            return cmd

        leader, llog = _spawn(
            sched_cmd(p1, obs1, tmp_path / "m1.pkl"),
            tmp_path / "leader.log", env)
        assert _wait_for_port(p1), "leader never bound"
        standby, slog = _spawn(
            sched_cmd(p2, obs2, tmp_path / "m2.pkl", standby=True),
            tmp_path / "standby.log", env)
        worker, wlog = _spawn(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "fault_stub_worker.py"),
             "--sched_port", str(p1), "--worker_port",
             str(free_port()), "--num_chips", "1",
             "--state_file", str(tmp_path / "w.json")],
            tmp_path / "worker.log", env)
        try:
            assert _wait_for_port(obs1), "leader exporter never bound"
            assert _wait_for_port(obs2), "standby exporter never bound"

            # Mid-run: BOTH endpoints serve concurrently on their own
            # ports; roles disagree exactly as they should.
            deadline = time.time() + 60
            pre_kill_round = None
            while time.time() < deadline:
                health = self._get(obs1, "/healthz")
                if health.get("ha", {}).get("role") == "leader":
                    hist = self._get(obs1, "/history.json")
                    if hist["rounds"]:
                        pre_kill_round = hist["rounds"][-1]["round"]
                        break
                time.sleep(0.3)
            assert pre_kill_round is not None, \
                (tmp_path / "leader.log").read_text()[-2000:]
            standby_health = self._get(obs2, "/healthz")
            assert standby_health["ha"]["role"] == "standby"
            try:
                self._get(obs2, "/history.json")
                assert False, "standby served history it does not hold"
            except urllib.error.HTTPError as e:
                assert e.code == 404

            os.kill(leader.pid, signal.SIGKILL)
            leader.wait(timeout=10)

            # The standby promotes, rebinds ITS obs port as the new
            # leader, reloads the history ring from the state dir, and
            # keeps serving — the role block flips on the same port.
            deadline = time.time() + 120
            promoted = False
            while time.time() < deadline and standby.poll() is None:
                try:
                    health = self._get(obs2, "/healthz", timeout=2)
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)  # window: standby server rebinding
                    continue
                if health.get("ha", {}).get("role") == "leader":
                    promoted = True
                    break
                time.sleep(0.3)
            assert promoted, (tmp_path / "standby.log").read_text()[-2000:]
            hist = self._get(obs2, "/history.json")
            assert hist["rounds"], "promoted leader serves no history"
            # Continuity: the reloaded ring reaches back to rounds the
            # DEAD leader sampled (the history followed the journal).
            assert hist["rounds"][0]["round"] <= pre_kill_round

            rc = standby.wait(timeout=120)
            assert rc == 0, (tmp_path / "standby.log").read_text()[-3000:]
            # The run itself stayed correct through the failover: both
            # trace jobs completed and their removals are durable in
            # the (epoch-fenced) journal the successor owns. Read the
            # raw segments (explain's loader) — load_state would hide
            # removals compacted into the snapshot.
            from shockwave_tpu.obs.explain import read_all_events
            removed = sum(e["type"] == "job_removed"
                          for e in read_all_events(str(state_dir)))
            assert removed == 2, [
                e["type"] for e in read_all_events(str(state_dir))][-20:]
        finally:
            for proc in (leader, standby, worker):
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
            for log in (llog, slog, wlog):
                log.close()


class TestSimObsDeterminism:
    """Scheduling decisions are bit-identical with obs recording on and
    off: instrumentation observes, never steers."""

    def _run(self, monkeypatch, obs_value):
        from shockwave_tpu.sched.scheduler import (Scheduler,
                                                   SchedulerConfig)
        from shockwave_tpu.solver import get_policy
        monkeypatch.setenv("SWTPU_OBS", obs_value)
        jobs = [Job(None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=(i + 1) * 20000, duration=4000)
                for i in range(5)]
        arrivals = [i * 150.0 for i in range(5)]
        sched = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate({"v100": 2}, arrivals, jobs)
        assert sched.obs.enabled == (obs_value == "1")
        return (makespan, sched.get_average_jct()[3],
                sched.rounds.per_round_schedule)

    def test_enabled_vs_disabled_bit_identical(self, monkeypatch):
        on = self._run(monkeypatch, "1")
        off = self._run(monkeypatch, "0")
        assert on == off


@pytest.mark.slow
class TestCanonicalObsDeterminism:
    """The canonical 120-job replay stays bit-identical (33207.58
    max_min makespan, exact JSON match with the recorded reproduce
    pickle) with obs instrumentation enabled vs. disabled."""

    def _simulate(self, obs_value):
        env = dict(os.environ, SWTPU_OBS=obs_value, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/drivers/simulate.py"),
             "--trace", os.path.join(DATA, "canonical_120job.trace"),
             "--policy", "max_min_fairness",
             "--throughputs", os.path.join(DATA, "tacc_throughputs.json"),
             "--cluster_spec", "v100:32", "--round_duration", "120"],
            capture_output=True, text=True, timeout=1800, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    def test_canonical_replay_bit_identical(self):
        def strip_wall(summary):
            # sim_wall_s / sim_core_wall_s / milp_wall_s are wall-clock
            # telemetry (nondeterministic run to run by construction);
            # everything else in the summary must replay exactly.
            return {k: v for k, v in summary.items()
                    if not k.endswith("_wall_s")}
        enabled = strip_wall(self._simulate("1"))
        disabled = strip_wall(self._simulate("0"))
        assert enabled == disabled
        with open(os.path.join(REPO, "reproduce", "pickles",
                               "max_min_fairness.json")) as f:
            recorded = strip_wall(json.load(f))
        assert enabled == recorded
        assert enabled["makespan"] == 33207.58


# ----------------------------------------------------------------------
# Mergeable quantile sketch (obs/quantiles.py)
# ----------------------------------------------------------------------

class TestQuantileSketch:
    def _sketch(self, values):
        from shockwave_tpu.obs.quantiles import QuantileSketch
        s = QuantileSketch()
        for v in values:
            s.add(v)
        return s

    def test_quantile_bounded_relative_error(self):
        from shockwave_tpu.obs.quantiles import GAMMA
        import numpy as np
        rng = np.random.RandomState(3)
        values = list(rng.exponential(0.2, 5000))
        s = self._sketch(values)
        for q in (0.5, 0.9, 0.99):
            exact = float(np.quantile(values, q, method="higher"))
            got = s.quantile(q)
            # Upper bucket edge: never under-reports by more than one
            # bucket, never over-reports by more than the bucket width.
            assert exact / GAMMA <= got <= exact * GAMMA * GAMMA

    def test_empty_and_mean(self):
        from shockwave_tpu.obs.quantiles import QuantileSketch
        s = QuantileSketch()
        assert s.quantile(0.99) is None
        assert s.mean() is None
        s.add(0.25)
        assert s.mean() == 0.25

    def test_merge_commutative_and_associative(self):
        """Exact merge algebra: any association/order of merges yields
        the same sketch — the property that lets shards arrive in any
        order on the heartbeat path."""
        from shockwave_tpu.obs.quantiles import QuantileSketch, merge_all
        import numpy as np
        rng = np.random.RandomState(7)
        parts = [self._sketch(rng.exponential(s * 0.1 + 0.01, 400))
                 for s in range(4)]
        ab_cd = merge_all([merge_all(parts[:2]), merge_all(parts[2:])])
        dcba = merge_all(parts[::-1])
        one_by_one = QuantileSketch()
        for p in parts:
            one_by_one.merge(p)
        assert ab_cd == dcba == one_by_one
        assert ab_cd.encode() == dcba.encode() == one_by_one.encode()

    def test_byte_deterministic_across_shard_orders(self):
        """Every permutation of shard arrival order must ENCODE
        byte-identically (the CI cmp contract), not just compare
        equal."""
        import itertools

        import numpy as np

        from shockwave_tpu.obs.quantiles import merge_all
        rng = np.random.RandomState(11)
        shards = [self._sketch(rng.exponential(0.1, 100))
                  for _ in range(3)]
        encodings = {merge_all([shards[i] for i in order]).encode()
                     for order in itertools.permutations(range(3))}
        assert len(encodings) == 1

    def test_wire_round_trip_and_validation(self):
        import pytest as _pytest

        from shockwave_tpu.obs.quantiles import QuantileSketch
        s = self._sketch([0.01, 0.5, 2.0, 2.0])
        rt = QuantileSketch.decode(s.encode())
        assert rt == s and rt.count == 4
        with _pytest.raises(ValueError):
            QuantileSketch.from_payload({"v": 99, "b": [], "n": 0, "s": 0})
        with _pytest.raises(ValueError):
            QuantileSketch.from_payload(
                {"v": 1, "b": [[3, 2]], "n": 5, "s": 0.0})

    def test_clamping_at_layout_edges(self):
        from shockwave_tpu.obs.quantiles import (MAX_BUCKET, MAX_VALUE,
                                                 MIN_VALUE, bucket_index)
        assert bucket_index(0.0) == 0
        assert bucket_index(MIN_VALUE / 10) == 0
        assert bucket_index(MAX_VALUE * 10) == MAX_BUCKET


class TestTelemetryHistoryServingRing:
    def test_record_serving_rides_payload_and_reload(self, tmp_path):
        """Measured-serving rows land in the /history.json payload and
        survive a flush/reload cycle (the crash-safe training set)."""
        from shockwave_tpu.obs.history import TelemetryHistory
        from shockwave_tpu.obs.registry import MetricsRegistry
        clock = SteppingClock()
        path = str(tmp_path / "history.json")
        hist = TelemetryHistory(MetricsRegistry(clock=clock), clock, path)
        row = {"service": 0, "measured_p99_s": 0.42,
               "analytic_p99_s": 0.3, "tokens_per_s": 1500.0,
               "mu_estimate": 23.4, "mu_analytic": 25.0, "requests": 80}
        hist.record_serving(row, round_id=7)
        payload = hist.payload()
        assert payload["serving"] == [dict(row, round=7)]
        hist.flush()
        reloaded = TelemetryHistory(MetricsRegistry(clock=clock), clock,
                                    path)
        assert reloaded.payload()["serving"] == [dict(row, round=7)]
