"""Gray-failure resilience: per-host health scoring, worker quarantine,
degrade fault injection (physical + sim) and the chaos-campaign
harness.

The acceptance drive (`TestQuarantineLoopback`) runs the REAL round
pipeline: two stub worker hosts, one silently degraded to 10% speed
mid-run while still answering every Ping — the scheduler must
quarantine it within a bounded number of rounds, finish every job on
the survivor with exact step budgets and zero failure charges, and
release the host on probation once it recovers.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.runtime import faults
from shockwave_tpu.runtime.resilience import (HEALTH_DEGRADED,
                                              HEALTH_HEALTHY,
                                              HEALTH_SUSPECT, HealthConfig,
                                              HostHealth)
from shockwave_tpu.sched.physical import PhysicalScheduler
from shockwave_tpu.sched.scheduler import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.abspath(os.path.join(TESTS_DIR, ".."))
DATA = os.path.join(REPO, "data")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")
CHAOS = os.path.join(REPO, "scripts", "drivers", "chaos_campaign.py")


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _job(total_steps=600):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=10000)


# ---------------------------------------------------------------------------
# HostHealth classifier units (pure state machine)
# ---------------------------------------------------------------------------

class TestHostHealthClassifier:
    CFG = HealthConfig(ewma_alpha=0.45, suspect_below=0.6,
                       degraded_below=0.3, recover_above=0.8,
                       min_samples=3, degraded_consecutive=2,
                       recover_consecutive=2)

    def test_healthy_stream_never_transitions(self):
        h = HostHealth(self.CFG)
        for _ in range(50):
            assert h.observe(1.0) is None
        assert h.state == HEALTH_HEALTHY
        assert h.score == pytest.approx(1.0)

    def test_ten_percent_straggler_degrades_within_bound(self):
        """A worker at 10% speed must be classified degraded within a
        handful of observations — the 'bounded number of rounds' in the
        acceptance criterion."""
        h = HostHealth(self.CFG)
        h.observe(1.0)  # one healthy round before the gray failure
        transitions = []
        for i in range(8):
            t = h.observe(0.1)
            if t:
                transitions.append((i, t))
            if h.state == HEALTH_DEGRADED:
                break
        assert h.state == HEALTH_DEGRADED
        assert transitions[-1][0] <= 5, transitions

    def test_min_samples_guards_cold_hosts(self):
        h = HostHealth(self.CFG)
        assert h.observe(0.0) is None  # one anomalous first sample
        assert h.state == HEALTH_HEALTHY

    def test_one_slow_round_does_not_flap(self):
        h = HostHealth(self.CFG)
        for _ in range(10):
            h.observe(1.0)
        h.observe(0.3)  # single bad sample: EWMA dips to ~0.68
        assert h.state == HEALTH_HEALTHY
        for _ in range(3):
            h.observe(1.0)
        assert h.state == HEALTH_HEALTHY

    def test_hysteresis_recovery_needs_consecutive_good_scores(self):
        h = HostHealth(self.CFG)
        for _ in range(6):
            h.observe(0.1)
        assert h.state == HEALTH_DEGRADED
        h.observe(1.0)
        assert h.state == HEALTH_DEGRADED  # score still climbing
        transitions = [h.observe(1.0) for _ in range(6)]
        assert h.state == HEALTH_HEALTHY
        assert HEALTH_HEALTHY in transitions

    def test_probation_restarts_as_suspect(self):
        h = HostHealth(self.CFG)
        for _ in range(6):
            h.observe(0.05)
        assert h.state == HEALTH_DEGRADED
        h.reset_probation()
        assert h.state == HEALTH_SUSPECT
        # Still slow: re-degrades quickly (escalating quarantine).
        for _ in range(3):
            h.observe(0.05)
        assert h.state == HEALTH_DEGRADED

    def test_config_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown worker-health"):
            HealthConfig.from_dict({"not_a_knob": 1})
        assert HealthConfig.from_dict(None) == HealthConfig()
        assert HealthConfig.from_dict(
            {"ewma_alpha": 0.2}).ewma_alpha == 0.2


# ---------------------------------------------------------------------------
# Degrade fault action (runtime/faults.py)
# ---------------------------------------------------------------------------

class TestDegradeFaultAction:
    def setup_method(self):
        faults.get_injector().clear()

    def teardown_method(self):
        faults.get_injector().clear()

    def test_slowdown_firing_window_and_recovery(self):
        inj = faults.get_injector()
        inj.install([{"method": "execute", "action": "degrade",
                      "factor": 0.1, "after": 1, "times": 2}])
        assert inj.slowdown("execute") == 1.0   # before the window
        assert inj.slowdown("execute") == 0.1
        assert inj.slowdown("execute") == 0.1
        assert inj.slowdown("execute") == 1.0   # recovered
        assert ("execute", "degrade") in inj.fired

    def test_overlapping_rules_compound(self):
        inj = faults.get_injector()
        inj.install([
            {"method": "execute", "action": "degrade", "factor": 0.5},
            {"method": "*", "action": "degrade", "factor": 0.5},
        ])
        assert inj.slowdown("execute") == pytest.approx(0.25)

    def test_degrade_rules_do_not_consume_rpc_hooks(self):
        """fire()/should_freeze() must skip degrade rules without
        advancing their window (and vice versa)."""
        inj = faults.get_injector()
        inj.install([{"method": "*", "action": "degrade", "factor": 0.5,
                      "times": 1}])
        inj.fire("Done")                      # rpc hook: no-op for degrade
        assert not inj.should_freeze("dispatch")
        assert inj.slowdown("dispatch") == 0.5  # window still intact

    def test_factor_validation(self):
        with pytest.raises(ValueError, match="factor"):
            faults.FaultRule(method="x", action="degrade", factor=0.0)
        with pytest.raises(ValueError, match="factor"):
            faults.FaultRule(method="x", action="degrade", factor=1.5)


# ---------------------------------------------------------------------------
# Simulator degrade events
# ---------------------------------------------------------------------------

class TestSimDegradeEvents:
    CLUSTER = {"v100": 4}

    def _run(self, fault_events=None, n_jobs=6, seed=0):
        from shockwave_tpu.core.oracle import read_throughputs
        from shockwave_tpu.core.profiles import build_profiles
        from shockwave_tpu.core.trace import parse_trace
        jobs, arrivals = parse_trace(
            os.path.join(DATA, "canonical_120job.trace"))
        jobs, arrivals = jobs[:n_jobs], arrivals[:n_jobs]
        profiles = build_profiles(
            jobs, read_throughputs(THROUGHPUTS))
        sched = Scheduler(
            get_policy("max_min_fairness", seed=seed), simulate=True,
            throughputs_file=THROUGHPUTS, profiles=profiles,
            config=SchedulerConfig(time_per_iteration=120.0, seed=seed))
        makespan = sched.simulate(dict(self.CLUSTER), arrivals, jobs,
                                  fault_events=fault_events)
        return makespan, sched

    def test_degrade_stretches_makespan_and_restore_recovers(self):
        baseline, _ = self._run()
        events = [{"at": 0.0, "degrade": [0, 1, 2, 3], "factor": 0.1},
                  {"at": 40000.0, "restore": [0, 1, 2, 3]}]
        degraded, sched = self._run(fault_events=events)
        assert degraded > baseline * 1.5, (baseline, degraded)
        # Every job still completes with its full budget and no
        # failure charges (a slowdown is not a failure).
        assert sched.get_num_completed_jobs() == 6
        assert all(v == 0 for v in sched.acct.failures.values())
        counter = sched._obs.registry.value(
            obs_names.SIM_FAULT_EVENTS_TOTAL, action="degrade")
        assert counter == 1

    def test_degrade_events_are_deterministic(self):
        events = [{"at": 5000.0, "degrade": [1, 2], "factor": 0.25},
                  {"at": 20000.0, "restore": [1, 2]},
                  {"at": 9000.0, "kill": [3]},
                  {"at": 26000.0, "revive": [3], "worker_type": "v100"}]
        events.sort(key=lambda e: e["at"])
        a, sa = self._run(fault_events=list(events))
        b, sb = self._run(fault_events=list(events))
        assert a == b
        assert sa.acct.total_steps_run == sb.acct.total_steps_run
        assert (sa.rounds.per_round_schedule
                == sb.rounds.per_round_schedule)

    def test_no_events_leaves_replay_untouched(self):
        """fault_events=None and fault_events=[] must equal the
        canonical path bit for bit."""
        a, sa = self._run(fault_events=None)
        b, sb = self._run(fault_events=[])
        assert a == b
        assert sa.rounds.per_round_schedule == sb.rounds.per_round_schedule

    def test_bad_factor_raises(self):
        with pytest.raises(ValueError, match="factor"):
            self._run(fault_events=[
                {"at": 0.0, "degrade": [0], "factor": 0.0}])


# ---------------------------------------------------------------------------
# Quarantine acceptance loopback (real round pipeline, stub daemons)
# ---------------------------------------------------------------------------

class _StubHost:
    """One stub worker HOST (own port => own liveness/health identity)
    with a mutable throughput — the gray-failure dial."""

    def __init__(self, sched_port, num_chips=1, throughput=100.0,
                 execution_time=0.2):
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self._iter_client = IteratorToSchedulerClient
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.port = free_port()
        self.server = serve_worker(self.port, {
            "RunJob": self._run_job, "KillJob": lambda j: None,
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", self.port, num_chips)

    def _run_job(self, jobs, worker_id, round_id):
        def execute():
            max_steps = 10**9
            for j in jobs:
                it = self._iter_client(j["job_id"], worker_id,
                                       "localhost", self.sched_port)
                max_steps, _, _ = it.init()
            time.sleep(self.execution_time)
            # Read the dial at completion time: a degraded host reports
            # proportionally fewer steps over the same wall time.
            steps = [min(int(self.throughput * self.round_duration),
                         j["num_steps"], int(max_steps)) for j in jobs]
            self._client.notify_done([j["job_id"] for j in jobs],
                                     worker_id, steps,
                                     [self.execution_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    def stop(self):
        self.server.stop(grace=0)


@pytest.mark.runtime
@pytest.mark.faults
@pytest.mark.timeout(120)
class TestQuarantineLoopback:
    """Acceptance: one of two hosts silently drops to 10% speed
    mid-run while answering every Ping. The scheduler must quarantine
    it within a bounded number of rounds, complete every job with
    exact step budgets and zero failure charges, and auto-release the
    host on probation once it recovers."""

    def test_degraded_host_quarantined_then_released(self):
        sched_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(
                time_per_iteration=2.0, heartbeat_interval_s=0.2,
                worker_timeout_s=3.0, worker_probe_failures=3,
                first_init_grace_s=0.0,
                worker_health={"quarantine_backoff_s": 3.0}),
            expected_num_workers=2, port=sched_port)
        host_a = _StubHost(sched_port, throughput=100.0)
        host_b = _StubHost(sched_port, throughput=100.0)
        b_ids = set(host_b.worker_ids)
        try:
            for _ in range(4):
                sched.add_job(_job(600))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()

            # Let at least one healthy round complete, then the gray
            # failure: B computes at 10% while its RPCs stay healthy.
            deadline = time.time() + 20
            while time.time() < deadline:
                with sched._lock:
                    if sched.rounds.num_completed_rounds >= 1:
                        break
                time.sleep(0.1)
            host_b.throughput = 10.0
            degraded_at_round = sched.rounds.num_completed_rounds

            # The scheduler must quarantine B within a bounded number
            # of rounds (classifier: ~4 bad micro-tasks).
            deadline = time.time() + 40
            while time.time() < deadline:
                with sched._lock:
                    if b_ids <= sched.workers.quarantined:
                        break
                time.sleep(0.1)
            with sched._lock:
                assert b_ids <= sched.workers.quarantined, (
                    "degraded host was never quarantined")
                quarantined_at_round = sched.rounds.num_completed_rounds
                # Quarantined = out of assignable capacity, not dead-dead.
                assert sched.workers.cluster_spec == {"v5e": 1}
                assert b_ids <= sched.workers.dead
                assert b_ids <= sched.suspect_worker_ids()
            assert quarantined_at_round - degraded_at_round <= 10, (
                f"quarantine took {quarantined_at_round} - "
                f"{degraded_at_round} rounds")

            # The host recovers (thermal event over); after the 3 s
            # backoff the next successful probe releases it on
            # probation.
            host_b.throughput = 100.0
            deadline = time.time() + 30
            while time.time() < deadline:
                with sched._lock:
                    if not sched.workers.quarantined:
                        break
                time.sleep(0.1)
            with sched._lock:
                assert not sched.workers.quarantined, (
                    "recovered host was never released from quarantine")
                assert sched.workers.cluster_spec == {"v5e": 2}

            # Every job drains with its exact budget and no failure
            # charges — the straggler cost rounds, never correctness.
            deadline = time.time() + 60
            while time.time() < deadline:
                if len(sched._completed_jobs) == 4:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 4, (
                f"only {sched._completed_jobs} completed")
            for i in range(4):
                assert sched.acct.total_steps_run[JobIdPair(i)] == 600
                assert sched.acct.failures.get(JobIdPair(i), 0) == 0

            reg = sched._obs.registry
            assert reg.value(obs_names.QUARANTINE_EVENTS_TOTAL,
                             action="quarantine") >= 1
            assert reg.value(obs_names.QUARANTINE_EVENTS_TOTAL,
                             action="release") >= 1
            assert reg.value(obs_names.WORKER_HEALTH_TRANSITIONS_TOTAL,
                             to="degraded") >= 1
        finally:
            sched._done_event.set()
            host_a.stop()
            host_b.stop()
            sched._server.stop(grace=0)

    def test_health_disabled_never_quarantines(self):
        sched_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(
                time_per_iteration=2.0, heartbeat_interval_s=0.2,
                worker_timeout_s=3.0, first_init_grace_s=0.0,
                worker_health_enabled=False),
            expected_num_workers=1, port=sched_port)
        host = _StubHost(sched_port, throughput=10.0)  # slow from birth
        try:
            sched.add_job(_job(100))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 40
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 1
            assert not sched.workers.quarantined
            assert sched.suspect_worker_ids() == frozenset()
        finally:
            sched._done_event.set()
            host.stop()
            sched._server.stop(grace=0)


# ---------------------------------------------------------------------------
# Stale per-host gauge labels (satellite): retired/quarantined hosts
# must drop their series from /metrics, not report the last value forever
# ---------------------------------------------------------------------------

class TestStaleHostGauges:
    def _sched_with_host(self):
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=2.0,
                                   heartbeat_interval_s=0.0),
            port=free_port())
        ids, _ = sched._register_worker_rpc("v5e", 1, "127.0.0.1",
                                            free_port())
        key = next(iter(sched._worker_hosts))
        host_label = f"{key[0]}:{key[1]}"
        # Simulate one liveness-monitor pass having exported the
        # per-host gauges.
        sched._obs.set_gauge(obs_names.WORKER_HEARTBEAT_AGE_SECONDS,
                             1.5, host=host_label)
        sched._set_breaker_gauge(key, sched._worker_hosts[key])
        sched._obs.set_gauge(obs_names.WORKER_HEALTH_SCORE, 0.9,
                             host=host_label)
        return sched, key, host_label

    def test_retired_host_series_dropped(self):
        sched, key, host_label = self._sched_with_host()
        try:
            text = sched._obs.registry.render_prometheus()
            assert host_label in text
            with sched._cv:
                sched._retire_worker_host(key)
            text = sched._obs.registry.render_prometheus()
            for name in ("swtpu_worker_heartbeat_age_seconds",
                         "swtpu_worker_breaker_state",
                         "swtpu_worker_health_score"):
                assert not any(name in line and host_label in line
                               for line in text.splitlines()), (
                    f"{name} still exposed for retired host:\n{text}")
        finally:
            sched.shutdown()

    def test_quarantined_host_drops_liveness_but_keeps_health(self):
        sched, key, host_label = self._sched_with_host()
        try:
            with sched._cv:
                sched._quarantine_worker_host(key)
            text = sched._obs.registry.render_prometheus()
            lines = text.splitlines()
            for name in ("swtpu_worker_heartbeat_age_seconds",
                         "swtpu_worker_breaker_state"):
                assert not any(name in line and host_label in line
                               for line in lines), (
                    f"{name} still exposed for quarantined host")
            # The health score IS the quarantined host's recovery
            # signal: it must stay exposed.
            assert any("swtpu_worker_health_score" in line
                       and host_label in line for line in lines)
            assert sched._obs.registry.value(
                obs_names.QUARANTINED_CHIPS) == 1
        finally:
            sched.shutdown()

    def test_dead_in_quarantine_drops_health_series_too(self):
        """A quarantined host that stops answering probes converts to a
        plain retirement — its health-score series (kept live during
        quarantine) must be dropped with it, and the retirement
        counted."""
        sched, key, host_label = self._sched_with_host()
        try:
            with sched._cv:
                sched._quarantine_worker_host(key)
            retirements = sched._obs.registry.value(
                obs_names.WORKER_RETIREMENTS_TOTAL)
            with sched._cv:
                sched._clear_quarantine_marker(key, reason="dead")
            text = sched._obs.registry.render_prometheus()
            assert not any("swtpu_worker_health_score" in line
                           and host_label in line
                           for line in text.splitlines())
            assert sched._obs.registry.value(
                obs_names.WORKER_RETIREMENTS_TOTAL) == retirements + 1
            assert not sched.workers.quarantined
            assert sched._obs.registry.value(
                obs_names.QUARANTINED_CHIPS) == 0
        finally:
            sched.shutdown()


# ---------------------------------------------------------------------------
# Serving replica placement skips suspect chips
# ---------------------------------------------------------------------------

class TestServingSkipsSuspectChips:
    def _mixed_sched(self, suspect_ids):
        """Simulation scheduler with a serving service and a patched
        suspect set (simulating what the physical health layer would
        report)."""
        from shockwave_tpu.core import trace as trace_mod
        sched = Scheduler(
            get_policy("max_min_fairness"), simulate=True,
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=120.0))
        for _ in range(4):
            sched.register_worker("v100", 1)
        job = trace_mod.make_serving_job(
            base_rps=5.0, peak_rps=5.0, period_s=86400.0,
            lifetime_s=40000.0, slo_p99_s=2.0)
        sched.add_job(job, timestamp=0.0)
        sched.suspect_worker_ids = lambda: frozenset(suspect_ids)
        return sched

    def test_replicas_avoid_suspect_chips(self):
        sched = self._mixed_sched({0, 1})
        assignments = sched._serving_tier.plan_round()
        used = {w for ids in assignments.values() for w in ids}
        assert used, "no replicas placed"
        assert not used & {0, 1}, (
            f"replicas placed on suspect chips: {used}")

    def test_suspect_chips_used_as_last_resort(self):
        sched = self._mixed_sched({0, 1, 2, 3})  # everything suspect
        assignments = sched._serving_tier.plan_round()
        used = {w for ids in assignments.values() for w in ids}
        assert used, "replica starved even though (suspect) chips exist"

    def test_empty_suspect_set_is_default_placement(self):
        a = self._mixed_sched(set())._serving_tier.plan_round()
        b = self._mixed_sched(set())._serving_tier.plan_round()
        assert a == b


# ---------------------------------------------------------------------------
# Sweep degrade knobs (satellite): seeded gray-failure events in the
# Monte Carlo sweep's scenario draw
# ---------------------------------------------------------------------------

class TestSweepDegradeKnobs:
    def _draw(self, seed=3, degrade_rate=2.0):
        import importlib.util
        import numpy as np
        drivers_dir = os.path.join(REPO, "scripts", "drivers")
        sys.path.insert(0, drivers_dir)  # driver_common sibling import
        try:
            spec = importlib.util.spec_from_file_location(
                "sweep_scenarios",
                os.path.join(drivers_dir, "sweep_scenarios.py"))
            sweep = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(sweep)
        finally:
            sys.path.remove(drivers_dir)
        from shockwave_tpu.core.trace import parse_trace
        jobs, arrivals = parse_trace(
            os.path.join(DATA, "canonical_120job.trace"))
        knobs = {"subsample": (0.1, 0.2), "fault_rate": 1.0,
                 "fault_max_chips": 2, "fault_down_s": 3600.0,
                 "fault_window_s": 20000.0,
                 "degrade_rate": degrade_rate,
                 "degrade_factor": (0.05, 0.5),
                 "degrade_down_s": 3600.0}
        rng = np.random.RandomState(seed)
        return sweep.draw_scenario(rng, jobs, arrivals, knobs,
                                   {"v100": 32})

    def test_degrade_events_drawn_and_deterministic(self):
        _, _, events_a, params_a = self._draw()
        _, _, events_b, params_b = self._draw()
        assert events_a == events_b and params_a == params_b
        degrades = [e for e in events_a if "degrade" in e]
        restores = [e for e in events_a if "restore" in e]
        assert len(degrades) == params_a["degrade_events"] > 0
        assert len(degrades) == len(restores)
        for e in degrades:
            assert 0.05 <= e["factor"] <= 0.5
        assert events_a == sorted(events_a, key=lambda e: e["at"])

    def test_degrade_rate_zero_reproduces_historical_draws(self):
        """degrade_rate=0 must leave the pre-existing seeded scenario
        content untouched (old sweep configs stay byte-reproducible)."""
        jobs_a, arr_a, ev_a, params_a = self._draw(degrade_rate=0.0)
        assert "degrade_events" not in params_a
        assert not any("degrade" in e for e in ev_a)
        assert params_a.get("fault_events") is not None


# ---------------------------------------------------------------------------
# Chaos campaign harness
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
class TestChaosCampaign:
    def _run(self, out, extra=(), timeout=240):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        return subprocess.run(
            [sys.executable, CHAOS,
             "--trace", os.path.join(DATA, "canonical_120job.trace"),
             "--policy", "max_min_fairness",
             "--throughputs", THROUGHPUTS,
             "--cluster_spec", "v100:8", "--round_duration", "120",
             "--out", out, *extra],
            capture_output=True, text=True, env=env, timeout=timeout)

    def test_sim_campaign_passes_and_is_byte_reproducible(self, tmp_path):
        out_a = str(tmp_path / "a.json")
        out_b = str(tmp_path / "b.json")
        ra = self._run(out_a, ["--num_schedules", "4"])
        assert ra.returncode == 0, ra.stdout + ra.stderr
        rb = self._run(out_b, ["--num_schedules", "4"])
        assert rb.returncode == 0, rb.stdout + rb.stderr
        with open(out_a, "rb") as fa, open(out_b, "rb") as fb:
            assert fa.read() == fb.read(), "artifact not byte-reproducible"
        with open(out_a) as f:
            doc = json.load(f)
        assert doc["summary"]["schedules"] == 4
        assert doc["summary"]["passed"] == 4
        assert doc["summary"]["violations"] == []
        faults_drawn = sum(v["plan"]["kills"] + v["plan"]["degrades"]
                           for v in doc["sim"].values())
        assert faults_drawn > 0, "campaign drew no faults at all"

    def test_resume_skips_completed_and_meta_mismatch_refuses(
            self, tmp_path):
        out = str(tmp_path / "c.json")
        r1 = self._run(out, ["--num_schedules", "2"])
        assert r1.returncode == 0, r1.stdout + r1.stderr
        with open(out) as f:
            two = json.load(f)
        # Resume to 3: seeds 0-1 skipped (byte-identical records), 2 new.
        r2 = self._run(out, ["--num_schedules", "3"])
        assert r2.returncode == 0, r2.stdout + r2.stderr
        with open(out) as f:
            three = json.load(f)
        assert {k: three["sim"][k] for k in two["sim"]} == two["sim"]
        assert len(three["sim"]) == 3
        # Different knobs, same artifact: refuse without --restart.
        r3 = self._run(out, ["--num_schedules", "3",
                             "--kill_rate", "9.0"])
        assert r3.returncode != 0
        assert "restart" in (r3.stdout + r3.stderr)

    def test_committed_study_is_clean(self):
        """The committed >=25-schedule chaos study must exist and pass
        every invariant (acceptance criterion)."""
        path = os.path.join(REPO, "reproduce", "chaos",
                            "chaos_campaign_40.json")
        with open(path) as f:
            doc = json.load(f)
        assert doc["summary"]["schedules"] >= 25
        assert doc["summary"]["violations"] == []
        assert doc["summary"]["passed"] == doc["summary"]["schedules"]
        for record in doc["sim"].values():
            assert all(record["invariants"].values()), record

    @pytest.mark.slow
    def test_physical_loopback_schedule(self, tmp_path):
        """One real-control-plane chaos schedule end to end (the CI
        chaos-smoke runs this same path)."""
        out = str(tmp_path / "p.json")
        r = self._run(out, ["--num_schedules", "0",
                            "--physical_schedules", "1",
                            "--workdir", str(tmp_path / "work")],
                      timeout=280)
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out) as f:
            doc = json.load(f)
        rec = doc["physical"]["0"]
        assert rec["violations"] == []
        assert all(rec["invariants"].values())
