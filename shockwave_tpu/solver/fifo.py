"""FIFO policies: queue-order placement, optionally perf-aware or packing.

Stateful: the base variant remembers placements across rounds and only
fills freed workers; `perf` mode re-places every round on the fastest
worker type; `packing` mode additionally co-locates queued jobs with
running ones when the combined normalized throughput clears a threshold
(reference: scheduler/policies/fifo.py).
"""
from __future__ import annotations

import random
from typing import Dict, Optional

from ..core.job import JobIdPair
from .policy import Policy, PolicyWithPacking


class FIFOPolicy(Policy):
    name = "FIFO"

    def __init__(self, mode: str = "base", seed: Optional[int] = None,
                 packing_threshold: float = 1.5):
        super().__init__()
        self._mode = mode
        self._allocation: Dict[JobIdPair, str] = {}
        self._rng = random.Random(seed)
        self._packing_threshold = packing_threshold

    def _pack(self, queue, throughputs, scale_factors):
        """Greedily co-locate the queue head with its best running partner."""
        while queue:
            candidate = queue.pop(0)
            best_gain = self._packing_threshold
            partner = None
            for scheduled in self._allocation:
                if scheduled.is_pair():
                    continue
                if scale_factors[scheduled] != scale_factors[candidate]:
                    continue
                worker_type = self._allocation[scheduled]
                merged = JobIdPair(scheduled[0], candidate[0])
                packed = throughputs[merged][worker_type]
                gain = 0.0
                for i, member in enumerate(merged.singletons()):
                    if packed[i] <= 0.0:
                        continue
                    gain += packed[i] / throughputs[member][worker_type]
                if gain > best_gain:
                    best_gain, partner = gain, scheduled
            if partner is None:
                break  # preserve FIFO: no queue-jumping past an unpackable head
            worker_type = self._allocation.pop(partner)
            self._allocation[JobIdPair(partner[0], candidate[0])] = worker_type

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        # Flat {worker_type: int} — a dict copy fully isolates it;
        # deepcopy ran once per allocation solve for nothing.
        available = dict(cluster_spec)
        if self._mode != "base":
            self._allocation = {}

        queue = [j for j in sorted(throughputs)
                 if j not in self._allocation and not j.is_pair()]

        # Release workers of completed jobs; backfill from the queue head.
        for scheduled in sorted(self._allocation):
            worker_type = self._allocation[scheduled]
            if scheduled not in throughputs:
                for member in scheduled.singletons():
                    if member in throughputs:
                        queue.append(member)
                        queue.sort()
                if queue:
                    head = queue[0]
                    if (scale_factors[head] <= available[worker_type]
                            and throughputs[head][worker_type] > 0.0):
                        queue.pop(0)
                        self._allocation[head] = worker_type
                        available[worker_type] -= scale_factors[head]
                del self._allocation[scheduled]
            else:
                available[worker_type] -= scale_factors[scheduled]

        # Place remaining queue on free workers.
        free_types = sorted(wt for wt in available if available[wt] > 0)
        while queue and free_types:
            job_id = queue.pop(0)
            fitting = [wt for wt in free_types
                       if available[wt] >= scale_factors[job_id]]
            if not fitting:
                break
            if self._mode == "base":
                worker_type = self._rng.choice(fitting)
            else:
                worker_type = max(fitting, key=lambda wt: throughputs[job_id][wt])
            if throughputs[job_id][worker_type] > 0.0:
                self._allocation[job_id] = worker_type
                available[worker_type] -= scale_factors[job_id]
                if available[worker_type] == 0:
                    free_types.remove(worker_type)

        if self._mode == "packing":
            self._pack(queue, throughputs, scale_factors)

        allocation = {j: {wt: 0.0 for wt in cluster_spec} for j in throughputs}
        for job_id, worker_type in self._allocation.items():
            allocation[job_id][worker_type] = 1.0
        return allocation


class FIFOPolicyWithPerf(Policy):
    name = "FIFO_Perf"

    def __init__(self, solver=None):
        super().__init__()
        self._policy = FIFOPolicy(mode="perf")

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(throughputs, scale_factors, cluster_spec)


class FIFOPolicyWithPacking(PolicyWithPacking):
    name = "FIFO_Packing"

    def __init__(self, packing_threshold: float = 1.5):
        super().__init__()
        self._policy = FIFOPolicy(mode="packing", packing_threshold=packing_threshold)

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(throughputs, scale_factors, cluster_spec)
