"""The designated clock adapter for the observability subsystem.

Every obs component (registry, tracer) takes its clock by injection so
the same instrumentation runs under the simulator's virtual clock
(`Scheduler.get_current_timestamp`) without perturbing bit-identical
replay, and under wall clocks in the physical control plane. This module
is the ONLY place in `shockwave_tpu/obs/` allowed to read a real clock —
enforced statically by the `obs-discipline` swtpu-check pass.
"""
from __future__ import annotations

import time
from typing import Callable

#: A clock is any zero-arg callable returning seconds as a float.
Clock = Callable[[], float]


def wall_clock() -> float:
    """Wall-clock seconds (epoch). The default clock for physical-mode
    components; timestamps line up with log lines and journal records."""
    return time.time()


def perf_clock() -> float:
    """High-resolution monotonic seconds, for benchmark harnesses where
    durations matter and absolute timestamps do not."""
    return time.perf_counter()
