#!/usr/bin/env python3
"""Trace-driven simulation driver.

Replays a trace against a simulated cluster and dumps the end-of-run
metrics (reference: scheduler/scripts/drivers/simulate_scheduler_with_trace.py).

Example:
    python scripts/drivers/simulate.py \
        --trace data/canonical_120job.trace \
        --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec v100:32 --round_duration 120
"""
import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.metrics import (parse_cluster_spec,
                                        unfair_fraction)
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.core.trace import parse_trace
from shockwave_tpu.obs.logconfig import LEVELS, setup_logging
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--trace", required=True)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--cluster_spec", default="v100:32",
                   help="worker_type:count[,worker_type:count...]")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--chips_per_server", type=int, default=1,
                   help="chips per simulated worker daemon (mirror a "
                        "multi-chip physical host, e.g. a gang loopback "
                        "worker)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--config", default=None,
                   help="JSON file of shockwave hyperparameters (a "
                        "'serving' block inside configures the serving "
                        "tier's autoscaler for any policy)")
    p.add_argument("--output", default=None, help="metrics pickle path")
    p.add_argument("--json_out", default=None,
                   help="also write the summary JSON line to this file "
                        "(CI artifact for the mixed serving smoke)")
    p.add_argument("--replay_schedule", default=None, metavar="PHYSICAL_PKL",
                   help="fidelity analysis: execute this physical metric "
                        "pickle's per_round_schedule verbatim instead of "
                        "the live policy (physical-vs-replay deltas "
                        "isolate the timing model from decision "
                        "divergence)")
    p.add_argument("--measured_rates", default=None, metavar="PHYSICAL_PKL",
                   help="fidelity analysis: override each job's oracle "
                        "rate with its mean measured throughput from this "
                        "physical pickle's throughput_timeline")
    p.add_argument("--obs_trace", default=None, metavar="TRACE_JSON",
                   help="export the simulator's span trace (virtual-"
                        "clock timeline) as Chrome-trace JSON at exit")
    p.add_argument("--log_level", default=None, choices=LEVELS,
                   help="root log level (default: warning, or info "
                        "with --verbose)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    setup_logging(args.log_level
                  or ("info" if args.verbose else "warning"))

    jobs, arrival_times = parse_trace(args.trace)
    throughputs = read_throughputs(args.throughputs)
    profiles = build_profiles(jobs, throughputs)
    cluster_spec = parse_cluster_spec(args.cluster_spec)
    for wt, count in cluster_spec.items():
        if count % args.chips_per_server:
            # The scheduler registers count // chips_per_server workers, so a
            # remainder would silently simulate a smaller cluster.
            raise SystemExit(
                f"--cluster_spec {wt}:{count} is not divisible by "
                f"--chips_per_server {args.chips_per_server}")

    shockwave_config = None
    serving_config = None
    if args.config:
        with open(args.config) as f:
            shockwave_config = json.load(f)
        # The serving tier is policy-agnostic; its autoscaler block
        # rides the same config file but a separate SchedulerConfig
        # field (the planner would reject the unknown keys).
        serving_config = shockwave_config.pop("serving", None)
    if shockwave_config is None and args.policy == "shockwave":
        shockwave_config = {}  # planner defaults
    if shockwave_config is not None:
        shockwave_config["num_gpus"] = sum(cluster_spec.values())
        shockwave_config["time_per_iteration"] = args.round_duration

    forced_schedule = None
    if args.replay_schedule:
        with open(args.replay_schedule, "rb") as f:
            forced_schedule = pickle.load(f)["per_round_schedule"]

    rate_override = None
    if args.measured_rates:
        with open(args.measured_rates, "rb") as f:
            timeline = pickle.load(f)["throughput_timeline"]
        # Mean of the per-round measured rates, skipping empty rounds
        # (a killed micro-task records 0.0).
        rate_override = {}
        for int_id, rounds in timeline.items():
            rates = [r for r, _ in rounds.values() if r > 0]
            if rates:
                rate_override[int_id] = sum(rates) / len(rates)

    policy = get_policy(args.policy, seed=args.seed)
    sched = Scheduler(
        policy, simulate=True, throughputs_file=args.throughputs,
        profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=args.round_duration, seed=args.seed,
            max_rounds=args.max_rounds, shockwave=shockwave_config,
            rate_override=rate_override, serving=serving_config))

    makespan = sched.simulate(
        cluster_spec, arrival_times, jobs,
        num_chips_per_server={wt: args.chips_per_server
                              for wt in cluster_spec},
        forced_schedule=forced_schedule)

    jct = sched.get_average_jct()
    ftf_static, ftf_themis = sched.get_finish_time_fairness()
    util, util_list = sched.get_cluster_utilization()
    ext_pct, ext, opp = sched.get_num_lease_extensions()
    envy_ratios, envy_pairwise = sched.get_envy_ratios()

    metrics = {
        "trace_file": args.trace,
        "policy": args.policy,
        "makespan": makespan,
        "avg_jct": jct[0] if jct else None,
        "geometric_mean_jct": jct[1] if jct else None,
        "harmonic_mean_jct": jct[2] if jct else None,
        "jct_list": jct[3] if jct else [],
        "finish_time_fairness_list": ftf_static,
        "finish_time_fairness_themis_list": ftf_themis,
        "cluster_util": util,
        "utilization_list": util_list,
        "envy_ratios": envy_ratios,
        "envy_list": envy_pairwise,
        "extension_percentage": ext_pct,
        "num_lease_extensions": ext,
        "num_lease_extension_opportunities": opp,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "time_per_iteration": args.round_duration,
        "throughput_timeline": sched.get_throughput_timeline(),
        "milp_solve_stats": sched.get_solve_stats(),
    }
    serving = sched.serving_summary()
    if serving is not None:
        metrics["serving"] = serving

    unfair = unfair_fraction(ftf_static)
    summary = {
        "policy": args.policy,
        "makespan": round(makespan, 2),
        "avg_jct": round(metrics["avg_jct"], 2) if metrics["avg_jct"] else None,
        "unfair_fraction": round(unfair, 4),
        "cluster_util": round(util, 4),
        "lease_extension_pct": round(ext_pct, 2),
        "rounds": sched.rounds.num_completed_rounds,
    }
    if serving is not None:
        summary["serving_slo_attainment"] = serving["slo_attainment"]
        summary["serving_requests_offered"] = serving["requests_offered"]
        summary["serving_services"] = serving["services"]
    print(json.dumps(summary))
    if args.json_out:
        # CI artifact, not durable state: a torn file just re-runs.
        with open(args.json_out, "w") as f:  # swtpu-check: ignore[durability]
            json.dump(summary, f, indent=2)

    if args.output:
        with open(args.output, "wb") as f:
            pickle.dump(metrics, f)
    if args.obs_trace:
        sched.obs.tracer.export_chrome_trace(args.obs_trace)


if __name__ == "__main__":
    main()
