from .policy import Policy, PolicyWithPacking
from .registry import ShockwavePolicy, get_policy

__all__ = ["Policy", "PolicyWithPacking", "ShockwavePolicy", "get_policy"]
