#!/usr/bin/env python3
"""Aggregate reproduce pickles into the paper's comparison table.

Reads every `<policy>.pkl` written by reproduce/*.sh and prints one row
per policy: makespan, avg/geo JCT, unfair-job fraction (rho > 1.1),
utilization, and lease-extension rate
(reference: reproduce/aggregate_result.py).
"""
import os
import pickle
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from shockwave_tpu.core.metrics import unfair_fraction

PAPER_NAMES = {
    "shockwave": "Shockwave",
    "min_total_duration": "OSSP",
    "finish_time_fairness": "Themis",
    "max_min_fairness": "Gavel",
    "allox": "AlloX",
    "max_sum_throughput_perf": "MST",
    "gandiva_fair": "Gandiva-Fair",
}


def summarize(metrics: dict) -> dict:
    unfair = unfair_fraction(metrics.get("finish_time_fairness_list") or [])
    return {
        "makespan_h": metrics["makespan"] / 3600.0,
        "avg_jct_h": (metrics.get("avg_jct") or 0.0) / 3600.0,
        "geo_jct_h": (metrics.get("geometric_mean_jct") or 0.0) / 3600.0,
        "unfair_frac": unfair,
        "util": metrics.get("cluster_util") or 0.0,
        "lease_ext_pct": metrics.get("extension_percentage") or 0.0,
    }


def main():
    pickle_dir = sys.argv[1] if len(sys.argv) > 1 else "reproduce/pickles"
    rows = []
    for policy, paper in PAPER_NAMES.items():
        path = os.path.join(pickle_dir, f"{policy}.pkl")
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            metrics = pickle.load(f)
        rows.append((paper, summarize(metrics)))
    if not rows:
        print(f"no pickles found in {pickle_dir}", file=sys.stderr)
        sys.exit(1)

    hdr = (f"{'policy':<14}{'makespan(h)':>12}{'avg JCT(h)':>12}"
           f"{'geo JCT(h)':>12}{'unfair%':>9}{'util':>7}{'lease%':>8}")
    print(hdr)
    print("-" * len(hdr))
    for paper, s in rows:
        print(f"{paper:<14}{s['makespan_h']:>12.2f}{s['avg_jct_h']:>12.2f}"
              f"{s['geo_jct_h']:>12.2f}{100 * s['unfair_frac']:>8.1f}%"
              f"{s['util']:>7.2f}{s['lease_ext_pct']:>7.1f}%")


if __name__ == "__main__":
    main()
