"""gRPC servers for both ends of the control plane.

- `serve_scheduler`: hosts WorkerToScheduler + IteratorToScheduler on the
  scheduler (reference: runtime/rpc/scheduler_server.py).
- `serve_worker`: hosts SchedulerToWorker on each worker daemon
  (reference: runtime/rpc/worker_server.py).

Callback dicts carry plain-Python payloads; proto (de)serialization stays
inside this module.
"""
from __future__ import annotations

import logging
import socket
from concurrent import futures
from typing import Callable, Dict

import grpc

from ..core.job import JobIdPair
from .proto import control_pb2 as pb
from .rpc import generic_handler

logger = logging.getLogger("shockwave_tpu.runtime")


def get_host_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except socket.gaierror:
        return "127.0.0.1"


def serve_scheduler(port: int, callbacks: Dict[str, Callable],
                    max_workers: int = 32) -> grpc.Server:
    """Start the scheduler-side server (non-blocking); returns the server."""

    def register_worker(request, context):
        try:
            worker_ids, round_duration = callbacks["RegisterWorker"](
                worker_type=request.worker_type,
                num_chips=request.num_chips,
                ip_addr=request.ip_addr,
                port=request.port)
            return pb.RegisterWorkerResponse(
                success=True, worker_ids=worker_ids,
                round_duration=round_duration)
        except Exception as e:  # noqa: BLE001 - reported to the caller
            logger.exception("RegisterWorker failed")
            return pb.RegisterWorkerResponse(success=False, error_message=str(e))

    def done(request, context):
        job_id = JobIdPair(*(list(request.job_ids) + [None])[:2])
        callbacks["Done"](job_id, request.worker_id,
                          list(request.num_steps),
                          list(request.execution_times),
                          list(request.iterator_logs) or None)
        return pb.Empty()

    def init_job(request, context):
        max_steps, max_duration, extra_time = callbacks["InitJob"](
            JobIdPair(request.job_id))
        return pb.InitJobResponse(max_steps=int(max_steps),
                                  max_duration=max_duration,
                                  extra_time=extra_time)

    def update_lease(request, context):
        max_steps, max_duration, run_time_so_far, deadline = callbacks["UpdateLease"](
            JobIdPair(request.job_id), request.worker_id, request.steps,
            request.duration, request.max_steps, request.max_duration)
        return pb.UpdateLeaseResponse(
            max_steps=int(max_steps), max_duration=float(max_duration),
            run_time_so_far=float(run_time_so_far), deadline=float(deadline))

    def update_resource_requirement(request, context):
        callbacks["UpdateResourceRequirement"](
            JobIdPair(request.job_id), request.worker_id,
            request.big_bs, request.small_bs)
        return pb.Empty()

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        generic_handler("shockwave_tpu.WorkerToScheduler", {
            "RegisterWorker": register_worker,
            "Done": done,
        }),
        generic_handler("shockwave_tpu.IteratorToScheduler", {
            "InitJob": init_job,
            "UpdateLease": update_lease,
            "UpdateResourceRequirement": update_resource_requirement,
        }),
    ))
    server.add_insecure_port(f"[::]:{port}")
    server.start()
    logger.info("scheduler control server listening on %d", port)
    return server


def serve_worker(port: int, callbacks: Dict[str, Callable],
                 max_workers: int = 16) -> grpc.Server:
    """Start the worker-side server (non-blocking); returns the server."""

    def run_job(request, context):
        jobs = [
            dict(job_id=j.job_id, command=j.command,
                 working_directory=j.working_directory,
                 needs_data_dir=j.needs_data_dir,
                 num_steps_arg=j.num_steps_arg, num_steps=j.num_steps,
                 mode=j.mode)
            for j in request.jobs
        ]
        callbacks["RunJob"](jobs, request.worker_id, request.round_id)
        return pb.Empty()

    def kill_job(request, context):
        callbacks["KillJob"](request.job_id)
        return pb.Empty()

    def reset(request, context):
        callbacks["Reset"]()
        return pb.Empty()

    def shutdown(request, context):
        callbacks["Shutdown"]()
        return pb.Empty()

    def ping(request, context):
        # Liveness probe: answering at all is the signal. An optional
        # callback lets the daemon surface health state in the future.
        cb = callbacks.get("Ping")
        if cb is not None:
            cb()
        return pb.Empty()

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        generic_handler("shockwave_tpu.SchedulerToWorker", {
            "RunJob": run_job,
            "KillJob": kill_job,
            "Reset": reset,
            "Shutdown": shutdown,
            "Ping": ping,
        }),
    ))
    server.add_insecure_port(f"[::]:{port}")
    server.start()
    logger.info("worker control server listening on %d", port)
    return server
