"""Throughput estimation for space-sharing (packing) decisions.

When a new job arrives, the scheduler has no packed-throughput profile for
it. The estimator profiles the job against a random subset of *reference*
job types, fills in the unmeasured entries by low-rank matrix completion,
and matches the job to the nearest reference job type by cosine distance
(reference: scheduler/throughput_estimator.py:17-204). The packed
throughputs of the matched reference type are then used as the new job's
estimates.

The matrix-completion step replaces the reference's external
`matrix_completion.pmf_solve` dependency with an in-repo regularized ALS
solver (`als_complete`) — fully vectorized numpy; the matrices involved
are tiny (num_reference_types x num_reference_types*num_worker_types), so
this runs in microseconds on the scheduler host.
"""
from __future__ import annotations

import random
from typing import Dict, Sequence

import numpy as np

MATRIX_COMPLETION_RANK = 10
MATRIX_COMPLETION_MU = 1e-2


def als_complete(A: np.ndarray, mask: np.ndarray, k: int = MATRIX_COMPLETION_RANK,
                 mu: float = MATRIX_COMPLETION_MU, max_iterations: int = 100,
                 epsilon: float = 1e-6, seed: int = 0) -> np.ndarray:
    """Low-rank completion of `A` where `mask==0`, via alternating least
    squares on the regularized PMF objective

        min_{U,V} ||mask * (A - U V^T)||_F^2 + mu (||U||^2 + ||V||^2).

    Returns the dense reconstruction U V^T.
    """
    n, m = A.shape
    k = min(k, n, m)
    rng = np.random.RandomState(seed)
    U = rng.randn(n, k) * 0.1
    V = rng.randn(m, k) * 0.1
    eye = mu * np.eye(k)
    prev = np.inf
    for _ in range(max_iterations):
        # Solve each row of U against the masked columns it observes.
        for i in range(n):
            w = mask[i] > 0
            if not w.any():
                continue
            Vw = V[w]
            U[i] = np.linalg.solve(Vw.T @ Vw + eye, Vw.T @ A[i, w])
        for j in range(m):
            w = mask[:, j] > 0
            if not w.any():
                continue
            Uw = U[w]
            V[j] = np.linalg.solve(Uw.T @ Uw + eye, Uw.T @ A[w, j])
        recon = U @ V.T
        err = float(np.linalg.norm(mask * (A - recon)))
        if abs(prev - err) < epsilon:
            break
        prev = err
    return U @ V.T


def cosine_distance(a: np.ndarray, b: np.ndarray) -> float:
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    if denom == 0:
        return 2.0  # maximal distance for degenerate (all-zero) profiles
    return 1.0 - float(np.dot(a, b) / denom)


class ThroughputEstimator:
    """Match an unprofiled job to the nearest offline-profiled reference
    job type (reference: throughput_estimator.py:17-38).

    `oracle_throughputs` uses the parsed oracle format of
    `core.oracle.read_throughputs`: oracle[worker_type][job_type] is a dict
    with key "null" -> isolated steps/s and other job-type keys ->
    [tput_self, tput_other] packed throughputs.
    """

    def __init__(self, oracle_throughputs: Dict[str, dict],
                 worker_types: Sequence[str], job_types: Sequence,
                 num_reference_job_types: int,
                 profiling_percentage: float, seed: int = 0):
        self._rng = random.Random(seed)
        self._oracle = oracle_throughputs
        self._worker_types = list(worker_types)
        self._job_types = list(job_types)
        self._profiling_percentage = profiling_percentage
        self._normalized = self._build_normalized_matrix()
        self._select_reference_types(num_reference_job_types)

    def _build_normalized_matrix(self) -> np.ndarray:
        """Row i = job type i; columns = (worker_type, other job type) pairs;
        value = packed throughput of i when colocated with the other type,
        normalized by i's isolated throughput (in [0, 1])."""
        n, m = len(self._job_types), len(self._worker_types)
        out = np.zeros((n, m * n), dtype=np.float64)
        for j, worker_type in enumerate(self._worker_types):
            per_worker = self._oracle[worker_type]
            for i, job_type in enumerate(self._job_types):
                entry = per_worker[job_type]
                isolated = entry["null"]
                if isolated <= 0:
                    # Job type infeasible on this worker type (e.g. OOM
                    # profile entry): packed share is 0 everywhere.
                    continue
                for k, other in enumerate(self._job_types):
                    out[i, j * n + k] = entry[other][0] / isolated
        # NOTE: unlike Gavel's original oracle, measured packed throughputs
        # can exceed the isolated throughput (e.g. the TACC V100 profiles),
        # so normalized values may be > 1; cosine matching handles that fine.
        if out.size and out.min() < 0.0:
            raise ValueError("packed throughputs must be non-negative")
        return out

    def _select_reference_types(self, num_reference_job_types: int) -> None:
        n = len(self._job_types)
        idx = sorted(self._rng.sample(range(n), num_reference_job_types))
        self._reference_job_types = [self._job_types[i] for i in idx]
        cols = [w * n + i for w in range(len(self._worker_types)) for i in idx]
        self._reference_matrix = self._normalized[np.ix_(idx, cols)]

    def _profile_job(self, true_job_type) -> Dict[str, dict]:
        """Simulate partial profiling: each (worker type, reference type)
        packed measurement is observed with probability
        `profiling_percentage` (reference: throughput_estimator.py:88-100)."""
        i = self._job_types.index(true_job_type)
        n = len(self._job_types)
        measured: Dict[str, dict] = {}
        for w, worker_type in enumerate(self._worker_types):
            measured[worker_type] = {}
            for ref in self._reference_job_types:
                if self._rng.uniform(0, 1) <= self._profiling_percentage:
                    k = self._job_types.index(ref)
                    measured[worker_type][ref] = self._normalized[i, w * n + k]
        return measured

    def match_job_to_reference_job(self, true_job_type):
        """Profile a subset of entries, complete the rest, return the
        reference job type with smallest cosine distance."""
        measured = self._profile_job(true_job_type)
        nref = len(self._reference_job_types)
        row = np.zeros(self._reference_matrix.shape[1])
        row_mask = np.zeros_like(row)
        for w, worker_type in enumerate(self._worker_types):
            for j, ref in enumerate(self._reference_job_types):
                if ref in measured[worker_type]:
                    row[w * nref + j] = measured[worker_type][ref]
                    row_mask[w * nref + j] = 1.0

        matrix = np.vstack([self._reference_matrix, row])
        mask = np.vstack([np.ones_like(self._reference_matrix), row_mask])
        if mask.min() == 0:
            try:
                recon = als_complete(matrix, mask)
            except np.linalg.LinAlgError:
                return self._rng.choice(self._reference_job_types)
            hi = float(matrix[mask > 0].max(initial=1.0))
            matrix = np.where(mask > 0, matrix, np.clip(recon, 0.0, hi))

        target = matrix[-1]
        if np.linalg.norm(target) == 0:
            return self._rng.choice(self._reference_job_types)
        distances = [
            (cosine_distance(matrix[i], target), i)
            for i in range(nref)
        ]
        _, best = min(distances)
        return self._reference_job_types[best]

    def get_reference_throughputs(self) -> Dict[str, dict]:
        """Reference-type-only packed oracle in the standard nested format
        (normalized; [tput_self, tput_other] per pair)."""
        n = len(self._reference_job_types)
        out: Dict[str, dict] = {}
        for w, worker_type in enumerate(self._worker_types):
            out[worker_type] = {}
            for j, ref in enumerate(self._reference_job_types):
                out[worker_type][ref] = {}
                for k, other in enumerate(self._reference_job_types):
                    out[worker_type][ref][other] = [
                        self._reference_matrix[j, w * n + k],
                        self._reference_matrix[k, w * n + j],
                    ]
        return out


__all__ = ["ThroughputEstimator", "als_complete", "cosine_distance",
           "MATRIX_COMPLETION_RANK", "MATRIX_COMPLETION_MU"]
