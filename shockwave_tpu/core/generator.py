"""Synthetic job / trace generation.

Samples jobs from the template table with the Philly-derived scale-factor
and duration distributions the reference uses (reference:
scheduler/utils.py:96-275, scripts/utils/generate_trace.py:350-433), plus
Poisson interarrival times. Pure host-side code; nothing here touches JAX.
"""
from __future__ import annotations

import math
import random
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .constants import steps_per_epoch
from .job import Job
from .job_table import JOB_TABLE

# Shockwave's duration mixture: mostly short jobs with a heavy tail
# (reference: generate_trace.py:371-403 "70% small, 20% medium, 10% large").
DURATION_PROBS = (0.72, 0.2, 0.05, 0.03)
DURATION_BOUNDARIES = (0.2, 0.5, 0.9, 1.0)


def philly_scale_factor(rng: random.Random,
                        mix: Optional[Sequence[float]] = None) -> int:
    """Scale factor from the Philly distribution: 70% x1, 10% x2, 15% x4,
    5% x8 by default, or an explicit 4-way mix (reference: utils.py:96-106,
    generate_trace.py:406-418)."""
    r = rng.uniform(0, 1)
    if mix is not None:
        assert abs(sum(mix) - 1.0) <= 1e-3
        bounds = np.cumsum(mix)
        for sf, b in zip((1, 2, 4, 8), bounds):
            if r <= b:
                return sf
        return 8
    if 0.7 <= r <= 0.8:
        return 2
    if 0.8 <= r <= 0.95:
        return 4
    if r >= 0.95:
        return 8
    return 1


def philly_duration(rng: random.Random) -> float:
    """Duration in seconds from the Philly log-uniform mixture
    (reference: utils.py:109-115)."""
    if rng.random() >= 0.8:
        return 60 * (10 ** rng.uniform(3, 4))
    return 60 * (10 ** rng.uniform(1.5, 3))


def duration_space(min_hours: float, max_hours: float, num: int,
                   base: float = 1.5, logspace: bool = True) -> np.ndarray:
    """Candidate duration grid in hours (reference:
    generate_trace.py:421-433)."""
    if not logspace:
        return np.linspace(min_hours, max_hours, num)
    powers = base ** np.linspace(1, num, num - 1)
    powers = np.insert(powers, 0, 0.0)
    powers = powers / powers.max()
    return np.round(powers * (max_hours - min_hours) + min_hours, 2)


def sample_duration(durations: np.ndarray, rng: random.Random,
                    np_rng: Optional[np.random.RandomState] = None) -> int:
    """Tiered duration sampling: pick a size class by DURATION_PROBS, then
    uniformly within that class's slice of the sorted duration grid
    (reference: generate_trace.py:371-403)."""
    n = len(durations)
    cuts = [round(n * b) for b in DURATION_BOUNDARIES]
    r = rng.uniform(0, 1)
    if r < DURATION_PROBS[0]:
        pool = durations[:cuts[0]]
    elif r < sum(DURATION_PROBS[:2]):
        pool = durations[cuts[0]:cuts[1]]
    elif r < sum(DURATION_PROBS[:3]):
        pool = durations[cuts[1]:cuts[2]]
    else:
        pool = durations[cuts[2]:]
    if len(pool) == 0:
        pool = durations
    choice = (np_rng.choice(pool) if np_rng is not None
              else rng.choice(list(pool)))
    return round(3600 * float(choice))


def sample_mode(rng: random.Random, mix: Sequence[float]) -> str:
    """static/accordion/gns with the given 3-way mix (reference:
    generate_trace.py:358-368)."""
    assert abs(sum(mix) - 1.0) <= 1e-3
    r = rng.uniform(0, 1)
    if r < mix[0]:
        return "static"
    if r < mix[0] + mix[1]:
        return "accordion"
    return "gns"


def poisson_interarrival(rng: random.Random, lam: float) -> float:
    """Exponential interarrival with mean `lam` seconds (reference:
    generate_trace.py:350-351 — note the reference treats lam as the MEAN,
    not the rate)."""
    return -math.log(1.0 - rng.random()) * lam


def generate_job(
    throughputs: dict,
    reference_worker_type: str = "v100",
    rng: Optional[random.Random] = None,
    job_id=None,
    fixed_job_duration: Optional[float] = None,
    generate_multi_gpu_jobs: bool = False,
    generate_multi_priority_jobs: bool = False,
    generate_dynamic_jobs: bool = False,
    run_dir: Optional[str] = None,
    scale_factor_mix: Optional[Sequence[float]] = None,
    mode_mix: Sequence[float] = (1.0, 0.0, 0.0),
    single_mode: Optional[str] = None,
    duration_generator: Optional[Callable[[random.Random], float]] = None,
    scale_factor_rng: Optional[random.Random] = None,
    duration_rng: Optional[random.Random] = None,
    mode_rng: Optional[random.Random] = None,
    slo_rng: Optional[random.Random] = None,
    min_epochs: int = 0,
) -> Job:
    """Sample one job: template, scale factor, duration, mode, priority, SLO.

    Steps are derived from the duration via the oracle's isolated
    throughput for (job_type, scale_factor) on the reference worker type
    (reference: utils.py:118-275).
    """
    rng = rng or random.Random()
    scale_factor_rng = scale_factor_rng or rng
    duration_rng = duration_rng or rng
    mode_rng = mode_rng or rng

    while True:
        template = rng.choice(JOB_TABLE)
        if generate_multi_gpu_jobs and template.distributed:
            scale_factor = philly_scale_factor(scale_factor_rng,
                                               scale_factor_mix)
        else:
            scale_factor = 1

        if fixed_job_duration:
            run_time = fixed_job_duration
        elif duration_generator is not None:
            run_time = duration_generator(duration_rng)
        else:
            run_time = philly_duration(duration_rng)

        if single_mode is not None:
            mode = single_mode
        elif generate_dynamic_jobs:
            mode = sample_mode(mode_rng, mode_mix)
        else:
            mode = "static"
        # Short accordion jobs shrink into degenerate ones; pin them static
        # (reference: utils.py:211-213).
        if run_time < 1000 and mode == "accordion":
            mode = "static"

        assert run_time > 0 and 1 <= scale_factor <= 8
        key = (template.model, scale_factor)
        oracle = throughputs[reference_worker_type].get(key)
        if oracle is None or oracle["null"] <= 0:
            continue  # no profile for this (type, scale) on the anchor type
        num_steps = int(run_time * oracle["null"])
        if num_steps <= 0:
            continue
        job = Job(
            job_id=job_id,
            job_type=template.model,
            command=(template.command % ((run_dir, run_dir)
                                         if template.command.count("%s") == 2
                                         else run_dir)
                     if run_dir is not None else template.command),
            working_directory=template.working_directory,
            num_steps_arg=template.num_steps_arg,
            total_steps=num_steps,
            duration=run_time,
            scale_factor=scale_factor,
            mode=mode,
            needs_data_dir=template.needs_data_dir,
        )
        if min_epochs:
            epochs = math.ceil(
                num_steps / steps_per_epoch(job.model, job.batch_size))
            if epochs < min_epochs:
                continue
        break

    if generate_multi_priority_jobs and rng.uniform(0, 1) <= 0.2:
        job.priority_weight = 5.0
    if slo_rng is not None:
        r = slo_rng.uniform(0, 1)
        job.SLO = 1.2 if r < 0.33 else (2.0 if r < 0.67 else 10.0)
    return job


def generate_trace(
    num_jobs: int,
    throughputs: dict,
    lam: float = 0.0,
    seed: int = 0,
    generate_multi_gpu_jobs: bool = True,
    generate_dynamic_jobs: bool = True,
    scale_factor_mix: Optional[Sequence[float]] = None,
    mode_mix: Sequence[float] = (0.34, 0.33, 0.33),
    min_duration_hours: float = 0.2,
    max_duration_hours: float = 5.0,
    num_durations: int = 100,
    logspace: bool = True,
    reference_worker_type: str = "v100",
) -> Tuple[List[Job], List[float]]:
    """Generate a full trace: jobs + arrival times. Seeded RNG streams per
    dimension so changing one knob doesn't reshuffle the others
    (reference: generate_trace.py:434-452)."""
    job_rng = random.Random(seed)
    arrival_rng = random.Random(seed + 1)
    duration_rng = random.Random(seed + 2)
    sf_rng = random.Random(seed + 3)
    mode_rng = random.Random(seed + 4)
    np_rng = np.random.RandomState(seed)

    durations = duration_space(min_duration_hours, max_duration_hours,
                               num_durations, logspace=logspace)
    jobs: List[Job] = []
    arrivals: List[float] = []
    t = 0.0
    for i in range(num_jobs):
        job = generate_job(
            throughputs,
            reference_worker_type=reference_worker_type,
            rng=job_rng,
            generate_multi_gpu_jobs=generate_multi_gpu_jobs,
            generate_dynamic_jobs=generate_dynamic_jobs,
            scale_factor_mix=scale_factor_mix,
            mode_mix=mode_mix,
            duration_generator=lambda r: sample_duration(durations, r, np_rng),
            scale_factor_rng=sf_rng,
            duration_rng=duration_rng,
            mode_rng=mode_rng,
        )
        jobs.append(job)
        arrivals.append(t if i > 0 else 0.0)
        t += poisson_interarrival(arrival_rng, lam) if lam > 0 else 0.0
    return jobs, arrivals


__all__ = ["generate_job", "generate_trace", "philly_scale_factor",
           "philly_duration", "sample_mode", "sample_duration",
           "duration_space", "poisson_interarrival"]
