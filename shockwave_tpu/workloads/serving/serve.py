#!/usr/bin/env python3
"""Serving replica workload (trace: "Serving (batch size N)").

One autoregressive token-serving replica: a small decoder-only LM
(models/decoder.py, KV-cached decode on the transformer/flash stack)
greedily generating ``tokens_per_request`` tokens for a batch of
``batch_size`` synthetic requests per step. The replica flows through
the standard cluster runtime unchanged — the LeaseIterator accounts one
step (= one served request batch) against a scheduler-granted lease and
exits cooperatively at expiry — so "progress" reported to the scheduler
is requests served, the serving tier's unit of work.

Dispatched with the trace's `serving_command` (core/trace.py) plus the
scheduler's --replica_of/--replica_index markers. The load-curve flags
parameterize BOTH the simulator's analytic twin and this process's
measured request clock: a seeded Poisson arrival stream drawn from the
same `serving/load.py` curve (serving/measured.ArrivalClock, split
round-robin across max_replicas) feeds a virtual queue whose service
times are the MEASURED decode-step walls — so every step admits and
completes concrete synthetic requests with admission->last-token
latencies. Samples accumulate into a mergeable quantile sketch
(obs/quantiles.py) and ship as compact deltas on the lease-renewal
heartbeat (unsent ones flush to the iterator log at exit and ride
Done), closing the autoscaler's measured-latency loop.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

import jax
import jax.numpy as jnp

from shockwave_tpu.models.decoder import DecoderLM
from shockwave_tpu.models.train_common import (common_parser,
                                               enable_compile_cache,
                                               parse_args)
from shockwave_tpu.runtime.iterator import LeaseIterator
from shockwave_tpu.serving.load import DiurnalLoad, Spike, seeded_spikes
from shockwave_tpu.serving.measured import (ArrivalClock, ReplicaMeter,
                                            derive_arrival_seed,
                                            encode_report)

THROUGHPUT_LOG_INTERVAL = 50
#: Cap on the synthetic arrival stream (arrivals are generated lazily,
#: so this only bounds a replica that outlives every realistic lease).
ARRIVAL_HORIZON_S = 7 * 86400.0


def build_parser():
    p = common_parser("Autoregressive serving replica")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--tokens_per_request", type=int, default=64)
    # Load-curve parameters: carried by the trace command so one line
    # parameterizes both the simulator's analytic model and this
    # process; the replica itself serves as fast as the chip allows.
    p.add_argument("--base_rps", type=float, default=0.0)
    p.add_argument("--peak_rps", type=float, default=0.0)
    p.add_argument("--period_s", type=float, default=0.0)
    p.add_argument("--phase_s", type=float, default=0.0)
    p.add_argument("--decode_tokens_per_s", type=float, default=0.0)
    p.add_argument("--max_replicas", type=int, default=8)
    p.add_argument("--spike_at", action="append", default=[])
    p.add_argument("--spike_seed", type=int, default=None)
    p.add_argument("--num_spikes", type=int, default=0)
    p.add_argument("--spike_mult", type=float, default=10.0)
    p.add_argument("--spike_duration_s", type=float, default=1800.0)
    p.add_argument("--replica_of", type=int, default=None)
    p.add_argument("--replica_index", type=int, default=0)
    # Measured request clock: seed override for the synthetic arrival
    # stream (default derives deterministically from spike_seed +
    # replica_index, so every dispatch of a replica replays the same
    # requests); the tier appends the service lifetime (seeded spikes
    # are drawn over it, matching the analytic model's placement) and
    # the service-relative spawn offset (a replica spawned at the
    # diurnal peak measures peak load, not the t=0 trough).
    p.add_argument("--arrival_seed", type=int, default=None)
    p.add_argument("--service_lifetime_s", type=float, default=None)
    p.add_argument("--arrival_phase_s", type=float, default=0.0)
    # Decode model shape (defaults sized for a single chip).
    p.add_argument("--model_dim", type=int, default=128)
    p.add_argument("--model_layers", type=int, default=2)
    p.add_argument("--model_heads", type=int, default=4)
    p.add_argument("--prompt_len", type=int, default=8)
    return p


def main():
    args = parse_args(build_parser())
    enable_compile_cache()

    max_len = args.prompt_len + args.tokens_per_request + 1
    model = DecoderLM(dim=args.model_dim, num_layers=args.model_layers,
                      num_heads=args.model_heads,
                      mlp_dim=2 * args.model_dim, max_len=max_len)
    rng = jax.random.PRNGKey(args.replica_index or 0)
    prompt = jax.random.randint(
        rng, (args.batch_size, args.prompt_len), 0, model.vocab_size,
        dtype=jnp.int32)
    params = model.init(rng, prompt)

    @jax.jit
    def serve_request_batch(params, prompt):
        """Greedy-decode tokens_per_request tokens for one batch of
        requests through the KV cache; returns the last generated
        token ids (the sync ref)."""
        caches = model.init_cache(args.batch_size)

        def step(carry, token_in):
            caches, pos = carry
            logits, caches = model.apply(params, token_in, caches, pos,
                                         method=DecoderLM.decode_step)
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (caches, pos + 1), next_tok[:, None]

        carry = (caches, jnp.int32(0))
        token = prompt[:, :1]
        for i in range(args.prompt_len):
            carry, token = step(carry, prompt[:, i:i + 1])
        def body(i, state):
            carry, token = state
            carry, token = step(carry, token)
            return (carry, token)
        carry, token = jax.lax.fori_loop(
            0, args.tokens_per_request, body, (carry, token))
        return token

    # Synthetic request stream: a small ring of the same cached prompt
    # batch. The LEASE bounds how long we serve, not the loader length
    # — the loop below re-enters the iterator at each synthetic "epoch"
    # boundary (a huge literal list here would cost gigabytes of
    # pointer storage per replica before the first request).
    request_ring = [prompt] * 1024
    if args.enable_lease_iterator:
        iterator = LeaseIterator(
            data_loader=request_ring,
            checkpoint_dir=args.checkpoint_dir,
            # Replicas are stateless (weights re-init from the replica
            # seed); there is no training state to checkpoint.
            load_checkpoint_func=lambda path: None,
            save_checkpoint_func=lambda path, state: None,
            synthetic_data=True)
    else:
        iterator = None

    # Measured request clock: seeded synthetic arrivals from the same
    # load curve the simulator's analytic twin reads, split round-robin
    # across the service's replica slots. Each decode step's measured
    # wall duration services one admitted batch on the virtual queue;
    # latency sketch deltas ship on the iterator log (-> Done heartbeat).
    spikes = tuple(Spike(*(float(x) for x in entry.split(":")))
                   for entry in args.spike_at)
    lifetime_s = (float(args.service_lifetime_s)
                  if args.service_lifetime_s else ARRIVAL_HORIZON_S)
    if args.spike_seed is not None and args.num_spikes > 0:
        # Same draw the tier/simulator make (over the service LIFETIME,
        # not the horizon): the measured stream and the analytic model
        # must place the seeded spikes identically.
        spikes = spikes + seeded_spikes(
            int(args.spike_seed), lifetime_s, int(args.num_spikes),
            float(args.spike_mult), float(args.spike_duration_s))
    load = DiurnalLoad(base_rps=args.base_rps,
                       peak_rps=max(args.peak_rps, args.base_rps),
                       period_s=args.period_s, phase_s=args.phase_s,
                       spikes=spikes)
    arrival_seed = (args.arrival_seed if args.arrival_seed is not None
                    else derive_arrival_seed(args.spike_seed,
                                             args.replica_index))
    horizon_s = max(min(lifetime_s, ARRIVAL_HORIZON_S)
                    - float(args.arrival_phase_s), 0.0)
    meter = ReplicaMeter(
        ArrivalClock(load, arrival_seed, horizon_s,
                     replica_index=args.replica_index,
                     num_replicas=max(args.max_replicas, 1),
                     phase_s=float(args.arrival_phase_s)),
        batch_size=args.batch_size,
        tokens_per_request=args.tokens_per_request)

    served = 0
    window_start = time.time()
    window_steps = 0
    last = None
    budget = args.num_steps

    report_seq = 0
    dispatch_round = int(os.environ.get("SWTPU_ROUND_ID", "0") or 0)

    def meter_window() -> None:
        """Account the just-synced window: JAX dispatch is async, so
        per-step walls are only honest AFTER a device sync — amortize
        the window's synced wall evenly over its steps, then queue the
        sketch delta for the next lease renewal (unsent deltas flush
        to the iterator log at exit and ride Done instead; the (round,
        seq) stamp lets the tier dedupe double delivery)."""
        nonlocal window_start, window_steps, report_seq
        now = time.time()
        if window_steps > 0:
            per_step = max(now - window_start, 0.0) / window_steps
            for _ in range(window_steps):
                meter.step(per_step)
        window_start, window_steps = now, 0
        delta = meter.take_delta()
        if delta is not None and iterator is not None:
            report_seq += 1
            delta["round"] = dispatch_round
            delta["seq"] = report_seq
            iterator.queue_measurement(encode_report(delta))

    def serve_one(batch):
        nonlocal last, served, window_steps, window_start
        last = serve_request_batch(params, batch)
        if iterator is not None:
            iterator.set_sync_ref(last)
        served += 1
        window_steps += 1
        if window_steps >= THROUGHPUT_LOG_INTERVAL:
            jax.block_until_ready(last)
            print(f"[THROUGHPUT_ESTIMATION]\t{time.time()}\t{served}",
                  flush=True)
            meter_window()

    try:
        if iterator is not None:
            while not iterator.done and (budget is None or served < budget):
                try:
                    for batch in iterator:
                        serve_one(batch)
                        if budget is not None and served >= budget:
                            iterator.complete()
                            break
                except StopIteration:
                    pass  # lease expiry or epoch boundary; `done` decides
        else:
            for _ in range(budget or 100):
                serve_one(prompt)
    finally:
        if last is not None:
            jax.block_until_ready(last)
        meter_window()                   # final partial-window delta
    print(f"SERVED {served} request batches "
          f"(x{args.batch_size} requests, {args.tokens_per_request} "
          f"tokens each)", flush=True)
    return served


if __name__ == "__main__":
    main()
