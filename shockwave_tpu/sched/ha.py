"""Control-plane high availability: journal-shipping hot standby with
fenced automatic failover.

The scheduler process itself was the last single point of failure:
every worker, job and serving replica already survives a crash, but
recovering the control plane meant a human running ``--resume`` (PR 2).
This module closes that gap with the standard lease-and-epoch recipe,
built entirely on machinery the tree already trusts:

- **Liveness lease** (``<state_dir>/leader.lease``): the leader rewrites
  a small JSON lease (epoch, endpoint, wall stamp) every
  ``lease_interval_s`` via the crash-safe ``write_text_atomic`` path.
  A standby that sees the stamp age past ``lease_ttl_s`` declares the
  leader dead and tries to promote. The same file doubles as the
  **endpoint registry**: worker-side clients re-resolve the scheduler
  address from it across a failover (``SWTPU_HA_ENDPOINT_FILE``).

- **Fenced epochs** (``<state_dir>/epoch.<n>.claim``): leadership of
  epoch *n* is claimed by creating the claim file with
  ``O_CREAT|O_EXCL`` — the filesystem's compare-and-swap, so exactly
  one process can ever win an epoch. The epoch rides every
  scheduler->worker RPC as gRPC metadata (``swtpu-leader-epoch``) and
  every journal record; workers reject lower epochs
  (FAILED_PRECONDITION), recovery discards a deposed leader's
  post-fencing journal writes (``journal.filter_epoch_chain``), and a
  leader that observes a higher claim **self-fences** (stops
  journaling and dispatching, exits). A wedged-but-alive old leader —
  the gray case PR 8 taught us to fear — can therefore never
  double-dispatch: its RPCs are refused at every worker and its writes
  are superseded on disk.

- **Hot standby** (`HotStandby`): a second scheduler process tails the
  leader's journal with the streaming `journal.JournalFollower` and
  keeps a warm, near-current in-memory twin (the what-if ``thaw``
  replay path: ``restore_from_durable_state`` + incremental
  ``_apply_journal_event``). The twin is ADVISORY — it powers the
  replication-lag metrics and instant read-only answers — while
  promotion itself re-enters through the conservative PR 2 recovery
  path (`load_state` + in-flight requeue with no failure charge +
  orphan gates), so correctness never rests on the incremental feed.

Split-brain windows are bounded, not wished away: between a standby's
claim and its first dispatch, the old leader may still be running. The
guarantees that hold REGARDLESS of timing are (a) workers execute
dispatches from at most the highest epoch they have seen, and (b) the
surviving journal chain contains exactly one writer per epoch. Both are
asserted by the leader-kill/leader-freeze chaos schedules
(``scripts/drivers/chaos_campaign.py --ha_schedules``).
"""
from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..core.durable_io import fsync_dir, write_text_atomic
from ..obs import names as obs_names

logger = logging.getLogger("shockwave_tpu.sched.ha")

LEASE_NAME = "leader.lease"
PROMOTION_NAME = "promotion.json"
_CLAIM_RE = re.compile(r"^epoch\.(\d{12})\.claim$")

#: Role gauge values (swtpu_ha_role).
ROLE_STANDBY = 0.0
ROLE_LEADER = 1.0
ROLE_FENCED = 2.0


class EpochClaimError(RuntimeError):
    """Another process won the epoch this one tried to claim."""


@dataclass(frozen=True)
class HAConfig:
    """Knobs of the control-plane HA layer. Defaults suit the loopback
    drives (sub-second rounds); production deployments scale the lease
    knobs with their round duration. README "Control-plane HA"
    documents each knob."""
    #: Leader lease rewrite cadence. Must be well under lease_ttl_s or
    #: a busy leader's late renewal reads as death.
    lease_interval_s: float = 0.5
    #: Lease stamp age at which a standby declares the leader dead and
    #: attempts promotion. The failover detection floor.
    lease_ttl_s: float = 2.5
    #: Standby journal-tail / lease-watch cadence.
    standby_poll_interval_s: float = 0.25
    #: How long worker-side clients keep re-resolving + retrying a
    #: report (Done / lease RPC) across a failover window before
    #: dropping it (the round watchdog then requeues the job).
    failover_budget_s: float = 30.0
    #: Address the leader advertises in the lease (workers re-resolve
    #: to it). Loopback drives use 127.0.0.1.
    advertise_addr: str = "127.0.0.1"
    #: Epoch already claimed by the promoting standby (set internally
    #: by the --ha_standby driver path; fresh leaders claim their own).
    claimed_epoch: Optional[int] = None

    @classmethod
    def from_dict(cls, config: Optional[dict]) -> "HAConfig":
        if not config:
            return cls()
        config = {k: v for k, v in config.items()
                  if not k.startswith("_")}  # config-file comments
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(config) - known
        if unknown:
            raise ValueError(f"unknown ha option(s): {sorted(unknown)}")
        return cls(**config)


# ----------------------------------------------------------------------
# Lease + epoch-claim files
# ----------------------------------------------------------------------

def lease_path(state_dir: str) -> str:
    return os.path.join(state_dir, LEASE_NAME)


def write_lease(state_dir: str, epoch: int, addr: str, port: int,
                stamp: Optional[float] = None,
                failover_budget_s: Optional[float] = None) -> None:
    """Atomically rewrite the leader lease (tmp + fsync + rename + dir
    fsync — a crash leaves whole-old or whole-new, never torn, so a
    standby's JSON parse can only fail on a genuinely foreign file).
    The lease doubles as the worker-side config channel: clients read
    `failover_budget_s` (how long to hold reports across a failover)
    from it, so the operator tunes ONE --ha block, not every daemon's
    environment."""
    lease = {
        "epoch": int(epoch), "addr": addr, "port": int(port),
        "pid": os.getpid(),
        "stamp": time.time() if stamp is None else stamp,
    }
    if failover_budget_s is not None:
        lease["failover_budget_s"] = float(failover_budget_s)
    write_text_atomic(lease_path(state_dir),
                      json.dumps(lease, sort_keys=True) + "\n")


def read_lease(state_dir: str) -> Optional[dict]:
    """The current lease, or None when absent/unparseable (a torn
    foreign file is treated as no lease — the TTL clock, not the parse,
    decides liveness)."""
    try:
        with open(lease_path(state_dir)) as f:
            lease = json.load(f)
    except (OSError, ValueError):
        return None
    return lease if isinstance(lease, dict) else None


def _claim_path(state_dir: str, epoch: int) -> str:
    return os.path.join(state_dir, f"epoch.{epoch:012d}.claim")


def max_claimed_epoch(state_dir: str) -> int:
    """Highest epoch any process has ever claimed in this state dir
    (0 when none)."""
    try:
        names = os.listdir(state_dir)
    except OSError:
        return 0
    epochs = [int(m.group(1)) for name in names
              for m in (_CLAIM_RE.match(name),) if m]
    return max(epochs, default=0)


def try_claim_epoch(state_dir: str, epoch: int, role: str) -> bool:
    """Atomically claim leadership of `epoch` — the fencing CAS.

    ``O_CREAT|O_EXCL`` guarantees exactly one winner per epoch number
    even when several standbys race a promotion. The claim file (and
    the directory entry making it durable) is fsync'd before returning
    True: a claim a crash can un-happen would let two processes each
    believe they won."""
    path = _claim_path(state_dir, epoch)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False
    try:
        os.write(fd, json.dumps({
            "epoch": int(epoch), "pid": os.getpid(), "role": role,
            "time": time.time()}, sort_keys=True).encode() + b"\n")
        os.fsync(fd)
    finally:
        os.close(fd)
    fsync_dir(state_dir)
    return True


def claim_next_epoch(state_dir: str, role: str,
                     attempts: int = 64) -> int:
    """Claim the next free epoch (fresh-leader startup, where losing a
    race just means taking the next number). Promotion paths use
    `try_claim_epoch` on exactly max+1 instead — there, losing the race
    means someone ELSE is promoting and this process must stand down."""
    for _ in range(attempts):
        epoch = max_claimed_epoch(state_dir) + 1
        if try_claim_epoch(state_dir, epoch, role):
            return epoch
    raise EpochClaimError(
        f"{state_dir}: could not claim an epoch in {attempts} attempts "
        "(claim churn — is a promotion storm running?)")


# ----------------------------------------------------------------------
# Leader side
# ----------------------------------------------------------------------

class HAController:
    """Leader-side HA duties: own a claimed epoch, renew the liveness
    lease, and self-fence the moment a higher claim appears.

    The renewal thread is the leader's deadman switch: every interval
    it (a) checks `max_claimed_epoch` — a higher number means a standby
    promoted over us (we were frozen, partitioned, or wedged) and the
    `on_fenced` callback fires exactly once; (b) rewrites the lease.
    A SIGSTOPped leader renews nothing; when SIGCONTed, the very next
    tick discovers the successor's claim and fences — bounding the
    zombie's write window to one renewal interval plus whatever the
    worker-side epoch rejection already refused.
    """

    def __init__(self, state_dir: str, cfg: HAConfig, port: int,
                 obs=None, on_fenced: Optional[Callable[[int], None]] = None):
        self.state_dir = state_dir
        self.cfg = cfg
        self.port = int(port)
        if obs is None:
            from ..obs import get_observability
            obs = get_observability()
        self._obs = obs
        self._on_fenced = on_fenced
        self._fenced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if cfg.claimed_epoch is not None:
            self.epoch = int(cfg.claimed_epoch)
        else:
            self.epoch = claim_next_epoch(state_dir, role="leader")
        self._obs.set_gauge(obs_names.HA_LEADER_EPOCH, self.epoch)
        self._obs.set_gauge(obs_names.HA_ROLE, ROLE_LEADER)
        logger.info("HA leader epoch %d claimed (state dir %s)",
                    self.epoch, state_dir)

    @property
    def fenced(self) -> bool:
        return self._fenced.is_set()

    def epoch_value(self) -> Optional[int]:
        """Current epoch for outbound RPC metadata (clients call this
        per RPC; it is immutable for the incarnation's lifetime)."""
        return self.epoch

    def start(self) -> "HAController":
        """Write the first lease and start the renewal thread (call
        once the gRPC port is bound — the lease advertises it)."""
        self._renew_once()
        self._thread = threading.Thread(target=self._renew_loop,
                                        name="ha-lease", daemon=True)
        self._thread.start()
        return self

    def _renew_once(self) -> bool:
        """One deadman tick. Returns False once fenced."""
        highest = max_claimed_epoch(self.state_dir)
        if highest > self.epoch:
            if not self._fenced.is_set():
                self._fenced.set()
                self._obs.set_gauge(obs_names.HA_ROLE, ROLE_FENCED)
                logger.warning(
                    "HA leader epoch %d FENCED: epoch %d was claimed by "
                    "a successor; ceasing journal writes and dispatch",
                    self.epoch, highest)
                if self._on_fenced is not None:
                    self._on_fenced(highest)
            return False
        write_lease(self.state_dir, self.epoch,
                    self.cfg.advertise_addr, self.port,
                    failover_budget_s=self.cfg.failover_budget_s)
        self._obs.inc(obs_names.HA_LEASE_RENEWALS_TOTAL)
        return True

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.cfg.lease_interval_s):
            try:
                if not self._renew_once():
                    return
            except Exception:  # noqa: BLE001 - the deadman must not die
                logger.exception("HA lease renewal tick failed")

    def fence_now(self) -> None:
        """Fence from the dispatch path (a worker rejected our epoch):
        same transition as the renewal thread's discovery, callable from
        under the scheduler lock."""
        if not self._fenced.is_set():
            self._fenced.set()
            self._obs.set_gauge(obs_names.HA_ROLE, ROLE_FENCED)
            logger.warning("HA leader epoch %d fenced by a worker's "
                           "stale-epoch rejection", self.epoch)
            if self._on_fenced is not None:
                self._on_fenced(self.epoch + 1)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ----------------------------------------------------------------------
# Standby side
# ----------------------------------------------------------------------

@dataclass
class PromotionRecord:
    """What a successful promotion measured (mirrored to
    ``<state_dir>/promotion.json`` for the chaos driver)."""
    epoch: int
    #: Wall time the standby declared the lease lapsed.
    detected_at: float
    #: Wall stamp of the last lease the dead leader wrote (failover
    #: latency is measured from stamp + ttl, the earliest any standby
    #: could have acted).
    last_lease_stamp: float
    #: Journal seq the warm twin had applied at promotion.
    applied_seq: int
    #: Twin replication lag at promotion (now - last record walltime).
    replication_lag_s: float


class HotStandby:
    """The standby process: tail the leader's journal, keep a warm twin,
    promote when the lease lapses.

    ``twin_factory`` builds a detached simulation-mode `Scheduler`
    (typically via ``whatif.fork.twin_config``) that journal events are
    replayed into; pass None to follow without a twin (fsck --follow
    style lag watching). The twin is rebuilt from `load_state` whenever
    the follower falls behind compaction.
    """

    #: All standby state is written by the single standby main thread
    #: (poll/promote loop); the obs exporter's request thread reads
    #: `follower` through `health()` — an advisory telemetry read of an
    #: atomically rebound reference (worst case: one stale /healthz
    #: sample during a twin rebuild). Documented for the race detector.
    _EXTERNALLY_SYNCHRONIZED = frozenset({"follower", "twin"})

    def __init__(self, state_dir: str, cfg: HAConfig,
                 twin_factory: Optional[Callable[[], object]] = None,
                 obs=None, clock=time.time):
        from .journal import JournalFollower, load_state
        self.state_dir = state_dir
        self.cfg = cfg
        self._twin_factory = twin_factory
        self._clock = clock
        if obs is None:
            from ..obs import get_observability
            obs = get_observability()
        self._obs = obs
        self._load_state = load_state
        self._follower_cls = JournalFollower
        self.twin = None
        self.follower: Optional[JournalFollower] = None
        self._last_seen_stamp: Optional[float] = None
        self._obs.set_gauge(obs_names.HA_ROLE, ROLE_STANDBY)
        self._rebuild_twin()

    # -- twin maintenance ---------------------------------------------

    def _rebuild_twin(self) -> None:
        """(Re)seed the twin and follower from durable state — initial
        warm-up, and the behind-compaction recovery path."""
        start_seq = 0
        if self._twin_factory is not None:
            self.twin = self._twin_factory()
            try:
                recovered = self._load_state(self.state_dir)
                self.twin.restore_from_durable_state(recovered)
                start_seq = recovered.last_seq
            except Exception:  # noqa: BLE001 - an empty/new state dir is
                # normal at bring-up; the follower starts from seq 0 and
                # the twin warms as the leader writes.
                logger.info("standby twin starts empty (no recoverable "
                            "state yet)", exc_info=True)
        else:
            snapshot_seq = self._follower_cls(self.state_dir
                                              ).snapshot_horizon()
            start_seq = snapshot_seq
        self.follower = self._follower_cls(self.state_dir,
                                           start_after_seq=start_seq)

    def _apply(self, events) -> None:
        if self.twin is None:
            return
        # Same suspension contract as restore_from_durable_state: the
        # twin must never re-journal (it has no layer anyway) nor gate
        # replayed admissions through a what-if plane.
        self.twin._replaying = True
        try:
            for event in events:
                self.twin._apply_journal_event(event.get("type", "?"),
                                               event.get("data", {}))
        finally:
            self.twin._replaying = False

    def poll_once(self) -> str:
        """One standby tick: ship new journal records into the twin and
        refresh the replication gauges. Returns the follower status."""
        from .journal import FOLLOW_BEHIND
        events, status = self.follower.poll()
        if events:
            self._apply(events)
            self._obs.inc(obs_names.HA_REPLICATION_RECORDS_TOTAL,
                          amount=len(events))
        if status == FOLLOW_BEHIND:
            logger.warning("standby fell behind journal compaction at "
                           "seq %d; rebuilding twin from snapshot",
                           self.follower.last_seq)
            self._rebuild_twin()
        self._obs.set_gauge(obs_names.HA_REPLICATION_APPLIED_SEQ,
                            self.follower.last_seq)
        if self.follower.last_record_walltime is not None:
            self._obs.set_gauge(
                obs_names.HA_REPLICATION_LAG_SECONDS,
                max(self._clock() - self.follower.last_record_walltime,
                    0.0))
        return status

    # -- liveness / promotion -----------------------------------------

    def leader_lapsed(self) -> bool:
        """Whether the leader's lease is past its TTL. A state dir with
        NO lease yet is not lapsed — the leader may simply not have
        started; a standby never promotes over a leader it has never
        seen (bring-up ordering, not failure)."""
        lease = read_lease(self.state_dir)
        if lease is None:
            return False
        self._last_seen_stamp = float(lease.get("stamp", 0.0))
        return self._clock() - self._last_seen_stamp >= self.cfg.lease_ttl_s

    def try_promote(self) -> Optional[PromotionRecord]:
        """Attempt the promotion CAS (claim exactly max+1). Returns the
        record on victory; None when another claimant won — the caller
        returns to standby (the winner's lease will appear)."""
        detected = self._clock()
        epoch = max_claimed_epoch(self.state_dir) + 1
        if not try_claim_epoch(self.state_dir, epoch, role="standby"):
            logger.warning("promotion race lost for epoch %d; resuming "
                           "standby", epoch)
            return None
        # Advertise IMMEDIATELY (with the promoting process's pid but
        # the not-yet-bound port): other standbys see a fresh stamp and
        # stand down while this one reconstructs the scheduler.
        write_lease(self.state_dir, epoch, self.cfg.advertise_addr,
                    self._promote_port,
                    failover_budget_s=self.cfg.failover_budget_s)
        lag = (self._clock() - self.follower.last_record_walltime
               if self.follower.last_record_walltime is not None else 0.0)
        record = PromotionRecord(
            epoch=epoch, detected_at=detected,
            last_lease_stamp=self._last_seen_stamp or 0.0,
            applied_seq=self.follower.last_seq,
            replication_lag_s=max(lag, 0.0))
        self._obs.inc(obs_names.HA_FAILOVERS_TOTAL)
        logger.warning(
            "standby PROMOTING as epoch %d (lease lapsed %.2fs ago; "
            "twin applied seq %d, replication lag %.3fs)", epoch,
            detected - (self._last_seen_stamp or detected),
            record.applied_seq, record.replication_lag_s)
        return record

    _promote_port = 0  # set by run_until_promoted

    def run_until_promoted(self, port: int,
                           stop: Optional[threading.Event] = None
                           ) -> Optional[PromotionRecord]:
        """Follow + watch until this process wins a promotion (or `stop`
        is set). Writes ``promotion.json`` with the measured latency;
        the caller then constructs the real PhysicalScheduler with
        ``resume=True`` and ``ha.claimed_epoch`` from the record — the
        conservative crash-recovery path, exactly as if an operator had
        restarted it by hand, minus the operator."""
        self._promote_port = int(port)
        while stop is None or not stop.is_set():
            self.poll_once()
            if self.leader_lapsed():
                record = self.try_promote()
                if record is not None:
                    promoted_wall = self._clock()
                    write_text_atomic(
                        os.path.join(self.state_dir, PROMOTION_NAME),
                        json.dumps({
                            "epoch": record.epoch,
                            "detected_at": record.detected_at,
                            "last_lease_stamp": record.last_lease_stamp,
                            "promoted_at": promoted_wall,
                            "from_lease_expiry_s": max(
                                promoted_wall - (record.last_lease_stamp
                                                 + self.cfg.lease_ttl_s),
                                0.0),
                            "applied_seq": record.applied_seq,
                            "replication_lag_s": record.replication_lag_s,
                        }, indent=1, sort_keys=True) + "\n")
                    self._obs.observe(
                        obs_names.HA_PROMOTION_SECONDS,
                        max(promoted_wall - record.detected_at, 0.0))
                    return record
            time.sleep(self.cfg.standby_poll_interval_s)
        return None

    def health(self) -> dict:
        """Standby /healthz block."""
        lease = read_lease(self.state_dir)
        now = self._clock()
        lag = (now - self.follower.last_record_walltime
               if self.follower and self.follower.last_record_walltime
               is not None else None)
        return {"ha": {
            "role": "standby",
            "leader_epoch": lease.get("epoch") if lease else None,
            "leader_lease_age_s": (
                round(now - float(lease.get("stamp", 0.0)), 3)
                if lease else None),
            "applied_seq": self.follower.last_seq if self.follower else 0,
            "replication_lag_s": (round(lag, 3)
                                  if lag is not None else None),
            "stale_records_dropped": (self.follower.stale_dropped
                                      if self.follower else 0),
        }}
