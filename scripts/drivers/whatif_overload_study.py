#!/usr/bin/env python3
"""Overload admission study: Monte-Carlo admission control vs
always-admit on a seeded overload trace.

Two legs of the SAME overloaded workload (a trace subset with arrivals
compressed by --load_scale onto a deliberately small cluster):

- **always_admit** — no what-if plane at all (the configured default
  everywhere else in the tree): every arrival is admitted on the spot.
- **gate** — the what-if plane's Monte-Carlo admission control
  (plane.gate_admission): at each arrival, K seeded twin rollouts with
  and without the candidate; the candidate is deferred while admitting
  it would push the projected worst-case finish-time fairness past the
  envelope (or break the serving SLO floor), with a hard deferral cap
  so nothing starves.

The committed acceptance artifact (reproduce/whatif/) must show the
gate leg strictly improving WORST-CASE FTF (max rho over all jobs)
with serving SLO attainment no worse — the decision log rides in the
artifact as evidence. Byte-reproducible: all content derives from the
seed; wall telemetry stays on stderr.

The CI smoke (whatif-smoke) runs this twice and `cmp`s the artifacts,
then gates on the improvement flags via --check.

Example (the committed study):
    python scripts/drivers/whatif_overload_study.py \
        --trace data/serving_mixed.trace --cluster_spec v100:8 \
        --num_jobs 12 --load_scale 6 \
        --out reproduce/whatif/overload_admission_study.json --check
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import driver_common  # noqa: E402
from shockwave_tpu.core.durable_io import write_text_atomic  # noqa: E402
from shockwave_tpu.core.metrics import (parse_cluster_spec,  # noqa: E402
                                        unfair_fraction)
from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402

ARTIFACT_SCHEMA = 1


def overload_workload(args):
    """The seeded overload: first --num_jobs trace lines, arrivals
    compressed by --load_scale (same order; serving services keep
    arrival 0 anchors)."""
    jobs, arrivals = parse_trace(args.trace)
    if args.num_jobs:
        jobs, arrivals = jobs[:args.num_jobs], arrivals[:args.num_jobs]
    arrivals = [a / args.load_scale for a in arrivals]
    return jobs, arrivals


def run_leg(args, whatif_config):
    jobs, arrivals = overload_workload(args)
    cluster_spec = parse_cluster_spec(args.cluster_spec)
    throughputs = read_throughputs(args.throughputs)
    profiles = build_profiles(jobs, throughputs)
    shockwave_config, serving_config, _, _ = driver_common.load_configs(
        args.config, args.policy, cluster_spec, args.round_duration)
    sched = driver_common.build_scheduler(
        args.policy, args.throughputs, profiles,
        round_duration=args.round_duration, seed=args.seed,
        max_rounds=args.max_rounds, shockwave_config=shockwave_config,
        serving_config=serving_config, whatif_config=whatif_config)
    makespan = sched.simulate(cluster_spec, arrivals, jobs)
    ftf_static, _ = sched.get_finish_time_fairness()
    jct = sched.get_average_jct()
    leg = {
        "makespan": round(makespan, 2),
        "avg_jct": round(jct[0], 2) if jct else None,
        "worst_ftf": round(max(ftf_static), 6) if ftf_static else None,
        "unfair_fraction": round(unfair_fraction(ftf_static), 4),
        "ftf_list": [round(v, 5) for v in sorted(ftf_static)],
        "completed_jobs": sched.get_num_completed_jobs(),
        "rounds": sched.rounds.num_completed_rounds,
    }
    serving = sched.serving_summary()
    if serving is not None:
        leg["serving_slo_attainment"] = serving["slo_attainment"]
        leg["serving_requests_offered"] = serving["requests_offered"]
    if sched._whatif is not None:
        leg["decision_log"] = sched._whatif.decision_log
        leg["deferrals"] = sum(1 for d in sched._whatif.decision_log
                               if d["decision"] == "defer")
        leg["rollouts"] = sched._whatif.rollouts
    return leg


def main():
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--trace", default="data/serving_mixed.trace")
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", default="data/tacc_throughputs.json")
    p.add_argument("--cluster_spec", default="v100:8")
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--config", default=None)
    p.add_argument("--num_jobs", type=int, default=12,
                   help="trace-head subset size (0 = whole trace)")
    p.add_argument("--load_scale", type=float, default=6.0,
                   help="arrival compression factor (>1 = overload)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    # Gate envelope (whatif.WhatIfConfig admission fields).
    p.add_argument("--horizon_rounds", type=int, default=50)
    p.add_argument("--samples", type=int, default=2)
    p.add_argument("--rho_limit", type=float, default=1.3)
    p.add_argument("--defer_rounds", type=float, default=3.0)
    p.add_argument("--max_defers", type=int, default=24)
    p.add_argument("--load_guard", type=float, default=1.0)
    p.add_argument("--wait_budget", type=float, default=0.6)
    p.add_argument("--out", required=True)
    p.add_argument("--check", action="store_true",
                   help="exit nonzero unless the gate leg strictly "
                        "improves worst-case FTF with serving "
                        "attainment no worse")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()
    setup_logging("info" if args.verbose else "warning")

    gate_config = {
        "admission": "gate", "seed": args.seed,
        "admission_horizon_rounds": args.horizon_rounds,
        "admission_samples": args.samples,
        "admission_rho_limit": args.rho_limit,
        "admission_defer_rounds": args.defer_rounds,
        "admission_max_defers": args.max_defers,
        "admission_load_guard": args.load_guard,
        "admission_wait_budget": args.wait_budget,
    }
    meta = {
        "trace": args.trace, "policy": args.policy,
        "throughputs": args.throughputs,
        "cluster_spec": args.cluster_spec,
        "round_duration": args.round_duration, "config": args.config,
        "num_jobs": args.num_jobs, "load_scale": args.load_scale,
        "seed": args.seed, "max_rounds": args.max_rounds,
        "gate": gate_config,
    }

    import time as _time
    t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    always = run_leg(args, None)
    gate = run_leg(args, gate_config)

    improvement = {
        "worst_ftf_always": always["worst_ftf"],
        "worst_ftf_gate": gate["worst_ftf"],
        "worst_ftf_improved": (
            always["worst_ftf"] is not None
            and gate["worst_ftf"] is not None
            and gate["worst_ftf"] < always["worst_ftf"]),
        "all_jobs_completed": (
            gate["completed_jobs"] == always["completed_jobs"]),
    }
    att_a = always.get("serving_slo_attainment")
    att_g = gate.get("serving_slo_attainment")
    if att_a is not None:
        improvement["serving_attainment_always"] = att_a
        improvement["serving_attainment_gate"] = att_g
        improvement["serving_no_worse"] = att_g >= att_a
    doc = {"schema": ARTIFACT_SCHEMA, "meta": meta,
           "always_admit": always, "gate": gate,
           "improvement": improvement}
    write_text_atomic(args.out,
                      json.dumps(doc, indent=1, sort_keys=True) + "\n")

    ok = improvement["worst_ftf_improved"] and \
        improvement["all_jobs_completed"] and \
        improvement.get("serving_no_worse", True)
    print(json.dumps({
        "artifact": args.out,
        "worst_ftf_always": always["worst_ftf"],
        "worst_ftf_gate": gate["worst_ftf"],
        "deferrals": gate.get("deferrals", 0),
        "rollouts": gate.get("rollouts", 0),
        "improved": ok,
        "wall_s": round(_time.monotonic() - t0, 2),  # swtpu-check: ignore[determinism]
    }))
    if args.check and not ok:
        print("ADMISSION STUDY FAILED: gate did not improve over "
              "always-admit", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
