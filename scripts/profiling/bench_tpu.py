#!/usr/bin/env python3
"""Single-chip TPU benchmark phase for bench.py.

Measures, on the real TPU backend:
  1. The flagship Seq2SeqTransformer jitted train step — steps/s and
     achieved MFU (model FLOPs from XLA cost analysis when available,
     else an analytic 6*N*tokens estimate, against the chip's peak
     bf16 FLOPs).
  2. Fused Pallas flash attention vs the einsum attention path at long
     sequence length — per-call latency and speedup.

Prints ONE JSON line; exits 75 when no TPU backend is available so the
caller can degrade gracefully (bench.py merges these fields into its
headline JSON only when present).

Reference counterpart: scheduler/scripts/profiling/measure_throughput.py
grounds the reference in measured GPU numbers; this grounds the TPU
build in measured v5e numbers.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.core.timing import marginal_step_time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

# Peak dense bf16 FLOPs/s per chip. v5e (TPU v5 lite): 197 TFLOP/s.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return 197e12  # default to v5e if the kind string is unrecognized


def timed_op(fn, q, k, v, n1=8, n2=32, warmup=3):
    """Marginal per-call time for an attention op, chained through q so
    the closing scalar fetch waits for the whole window (two-point
    timing; see core/timing.py for why block_until_ready is not enough
    here). Output feeds back as q — shapes match (b, t, h, d)."""

    def step(q, _batch):
        out = fn(q, k, v)
        return out.astype(q.dtype), out

    return marginal_step_time(step, q, None, n1=n1, n2=n2, warmup=warmup)


def transformer_train_bench(batch=64, steps=30, warmup=5, seq=None,
                            prefix="transformer"):
    """Flagship Seq2SeqTransformer train step at a given sequence length.

    The default (seq=None -> the model's trace-parity max_len of 64) is
    the scheduling-relevant config, but at seq 64 attention is a
    rounding error and the step is input/overhead-bound; pass a long
    seq (e.g. 2048, the flash kernel's regime) for a compute-bound MFU
    that reflects the framework's compute efficiency."""
    from shockwave_tpu.models.transformer import Seq2SeqTransformer

    model = (Seq2SeqTransformer(use_flash=True) if seq is None
             else Seq2SeqTransformer(use_flash=True, max_len=seq))
    seq = model.max_len
    rng = jax.random.PRNGKey(0)
    src = jnp.ones((batch, seq), jnp.int32)
    tgt = jnp.ones((batch, seq), jnp.int32)
    params = model.init(rng, src[:1], tgt[:1])["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def train_step(params, opt_state, src, tgt):
        def loss_fn(p):
            logits = model.apply({"params": p}, src, tgt)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    # FLOPs per step from XLA's own cost model where exposed. Lower and
    # compile through `step` itself so the timed calls below hit this
    # same executable in the jit cache instead of compiling twice.
    flops = None
    try:
        compiled = step.lower(params, opt_state, src, tgt).compile()
        analyses = compiled.cost_analysis()
        analysis = analyses[0] if isinstance(analyses, (list, tuple)) \
            else analyses
        flops = float(analysis.get("flops", 0.0)) or None
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        flops = None
    if flops is None:
        n_params = sum(x.size for x in jax.tree.leaves(params))
        flops = 6.0 * n_params * batch * seq  # fwd+bwd analytic estimate

    def chained(state, batch):
        params, opt_state = state
        params, opt_state, loss = step(params, opt_state, src, tgt)
        return (params, opt_state), loss

    dt = marginal_step_time(chained, (params, opt_state), None,
                            n1=max(steps // 4, 2), n2=steps, warmup=warmup)

    mfu = flops / dt / peak_flops(jax.devices()[0])
    return {
        f"{prefix}_steps_per_s": round(1.0 / dt, 2),
        f"{prefix}_batch": batch,
        f"{prefix}_seq_len": seq,
        f"{prefix}_flops_per_step": flops,
        f"{prefix}_mfu": round(mfu, 4),
    }


def attention_bench(b=4, t=2048, h=8, d=64):
    """Flash kernel vs einsum attention at long sequence length."""
    from shockwave_tpu.ops import flash_attention

    rng = jax.random.PRNGKey(0)
    ks = jax.random.split(rng, 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, t, h, d), jnp.bfloat16)

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))

    def einsum_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(1.0 * d)
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
        w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    ein = jax.jit(einsum_attn)
    t_flash = timed_op(flash, q, k, v)
    t_ein = timed_op(ein, q, k, v)
    return {
        "flash_attn_ms": round(t_flash * 1e3, 3),
        "einsum_attn_ms": round(t_ein * 1e3, 3),
        "flash_speedup": round(t_ein / t_flash, 3),
        "attn_shape": [b, t, h, d],
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=128,
                   help="the Transformer family's largest trace batch "
                        "size (core/job_table.py)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--long_seq", type=int, default=2048,
                   help="sequence length for the compute-bound config "
                        "(0 disables the long-seq phase)")
    p.add_argument("--long_batch", type=int, default=4)
    p.add_argument("--save_dir", default=os.path.join(REPO, "reproduce",
                                                      "tpu"),
                   help="directory for the timestamped raw artifact "
                        "('' disables persisting)")
    args = p.parse_args()

    if jax.default_backend() != "tpu":
        print(json.dumps({"skip": f"backend={jax.default_backend()}"}))
        sys.exit(75)

    # device kind lives in the base dict so the printed JSON is
    # self-describing even with --save_dir '' (persisting disabled);
    # save_measurement tolerates the explicit field.
    result = {"device": jax.devices()[0].device_kind,
              "peak_bf16_flops": peak_flops(jax.devices()[0])}
    result.update(transformer_train_bench(batch=args.batch, steps=args.steps))
    if args.long_seq:
        # Compute-bound configuration: long-sequence flash regime, where
        # MFU reflects MXU efficiency rather than input/overhead costs.
        result.update(transformer_train_bench(
            batch=args.long_batch, steps=max(args.steps // 3, 5),
            seq=args.long_seq, prefix="transformer_long"))
        # Same regime at a production long-context per-chip batch (4x the
        # tokens): separates small-batch underutilization from kernel
        # cost in the MFU number.
        big = args.long_batch * 4
        result.update(transformer_train_bench(
            batch=big, steps=max(args.steps // 3, 5),
            seq=args.long_seq, prefix=f"transformer_long_b{big}"))
    result.update(attention_bench())

    if args.save_dir:
        # Persist the raw measurement (the committed-artifact pattern of
        # the reference's oracle JSONs): hardware claims stay checkable
        # even when the chip is later unreachable.
        from shockwave_tpu.core.artifacts import save_measurement
        path, result = save_measurement(args.save_dir, "bench", result,
                                        device_kind=result["device"])
        print(f"saved {path}", file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
