#!/usr/bin/env python3
"""Fleet-tracing overhead microbenchmark: spans/s throughput and the
per-round cost of context propagation + shard flushing.

Measures the three costs the tracing work charges the hot paths:

- **span** — one context-carrying `Tracer.span` enter/exit (id
  allocation, parent-stack push/pop, ring append): what every
  phase/dispatch span costs the round pipeline;
- **propagate** — `propagation.rpc_metadata` + `from_rpc_metadata`
  round trip (what each RunJob RPC pays on top of the span);
- **shard flush** — one atomic rewrite of a realistically-sized shard
  file (what a worker daemon pays per dispatch).

Prints ONE JSON line; bench.py embeds it as the `tracing_phase` row.
``--smoke`` exits nonzero when spans/s falls under --min_spans_per_s
or the estimated per-round overhead exceeds --max_round_overhead_s —
the CI floor gate.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.obs import names as obs_names  # noqa: E402
from shockwave_tpu.obs import propagation  # noqa: E402
from shockwave_tpu.obs.shard import ShardSpanWriter  # noqa: E402
from shockwave_tpu.obs.tracing import Tracer  # noqa: E402

#: Spans one 32-chip round emits with propagation on: ~6 phase/root
#: spans + one runjob-rpc per chip, + worker-side runjob/launch/
#: done-report and a trainer span per dispatch.
SPANS_PER_ROUND_ESTIMATE = 6 + 32 * 4


def bench_spans(n):
    tracer = Tracer(clock=time.perf_counter)
    t0 = time.perf_counter()
    for i in range(n):
        with tracer.span(obs_names.SPAN_TRACING_BENCH, i=i):
            pass
    wall = time.perf_counter() - t0
    return wall / n, len(tracer.events())


def bench_propagation(n):
    ctx = propagation.new_root_context()
    t0 = time.perf_counter()
    for _ in range(n):
        metadata = propagation.rpc_metadata(ctx, send_ts=1234.5)
        out, ts = propagation.from_rpc_metadata(metadata)
    wall = time.perf_counter() - t0
    assert out == ctx and ts == 1234.5
    return wall / n


def bench_flush(spans_in_shard, flushes):
    with tempfile.TemporaryDirectory() as td:
        shard = ShardSpanWriter(td, role="bench",
                                clock=time.perf_counter)
        for i in range(spans_in_shard):
            with shard.span(obs_names.SPAN_TRACING_BENCH, i=i):
                pass
        t0 = time.perf_counter()
        for _ in range(flushes):
            shard.flush()
        return (time.perf_counter() - t0) / flushes


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--spans", type=int, default=200_000)
    p.add_argument("--propagations", type=int, default=100_000)
    p.add_argument("--shard_spans", type=int, default=2_000,
                   help="shard size for the flush benchmark (a worker "
                        "daemon's steady-state ring)")
    p.add_argument("--flushes", type=int, default=20)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--min_spans_per_s", type=float, default=20_000.0,
                   help="--smoke: fail below this span throughput")
    p.add_argument("--max_round_overhead_s", type=float, default=0.05,
                   help="--smoke: fail when the estimated scheduler-"
                        "side per-round tracing cost exceeds this "
                        "(spans + propagation for a 32-chip round)")
    p.add_argument("--output", default=None, help="also write the JSON")
    args = p.parse_args()

    span_s, recorded = bench_spans(args.spans)
    prop_s = bench_propagation(args.propagations)
    flush_s = bench_flush(args.shard_spans, args.flushes)
    # Scheduler-side per-round estimate: every span in the round plus
    # one metadata round trip per dispatched chip (flushes happen on
    # the worker, off the scheduler's critical path).
    round_overhead_s = (SPANS_PER_ROUND_ESTIMATE * span_s
                        + 32 * prop_s)
    row = {
        "spans_per_s": round(1.0 / span_s, 1),
        "span_mean_us": round(span_s * 1e6, 3),
        "propagate_mean_us": round(prop_s * 1e6, 3),
        "shard_flush_mean_s": round(flush_s, 6),
        "shard_flush_spans": args.shard_spans,
        "round_overhead_est_s": round(round_overhead_s, 6),
        "spans_recorded": recorded,
    }
    print(json.dumps(row))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(row, f)
    if args.smoke:
        if row["spans_per_s"] < args.min_spans_per_s:
            print(f"SMOKE FAIL: {row['spans_per_s']} spans/s < "
                  f"{args.min_spans_per_s}", file=sys.stderr)
            return 1
        if round_overhead_s > args.max_round_overhead_s:
            print(f"SMOKE FAIL: estimated per-round overhead "
                  f"{round_overhead_s:.4f}s > "
                  f"{args.max_round_overhead_s}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
