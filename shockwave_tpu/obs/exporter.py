"""Scheduler health endpoint: a lightweight HTTP server exposing

- ``GET /metrics``      — Prometheus text exposition of a MetricsRegistry,
- ``GET /healthz``      — JSON from an injected health callback (current
  round, live workers, breaker states, journal lag, ...),
- ``GET /history.json`` — JSON from an injected telemetry-history
  callback (obs/history.py: per-round metric snapshots + observed
  throughput points + alert verdicts); 404 when the process keeps no
  history (e.g. an HA hot standby before promotion — the history is
  served by whichever process holds the journal).

Built on the stdlib ThreadingHTTPServer: no new dependencies, one
daemon thread, bounded per-request work (render + send). Opt-in via
``SchedulerConfig.obs_port`` / ``run_physical.py --obs_port`` (port 0
binds an ephemeral port, readable from ``.port`` after start()).

The server never touches scheduler internals directly — the health
callback owns its own locking — so a wedged scheduler can stall
``/healthz`` but never the other way around.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .registry import MetricsRegistry

logger = logging.getLogger("shockwave_tpu.obs")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ObsHttpServer:
    def __init__(self, registry: MetricsRegistry,
                 health_fn: Optional[Callable[[], dict]] = None,
                 history_fn: Optional[Callable[[], dict]] = None,
                 addr: str = "0.0.0.0", port: int = 0):
        self._registry = registry
        self._health_fn = health_fn
        self._history_fn = history_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # One scrape every few seconds; access logs are noise.
            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("obs http: " + fmt, *args)

            def _send(self, code: int, content_type: str,
                      body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = outer._registry.render_prometheus().encode()
                    self._send(200, PROMETHEUS_CONTENT_TYPE, body)
                elif path == "/healthz":
                    code, payload = outer._health()
                    self._send(code, "application/json",
                               json.dumps(payload).encode())
                elif path == "/history.json":
                    code, payload = outer._history()
                    self._send(code, "application/json",
                               json.dumps(payload).encode())
                else:
                    self._send(404, "text/plain",
                               b"try /metrics, /healthz or "
                               b"/history.json\n")

        self._httpd = ThreadingHTTPServer((addr, port), _Handler)
        self._httpd.daemon_threads = True
        # Stdlib accept loop: request handling enters the tree through
        # _Handler.do_GET, which the thread-roots pass discovers via
        # the ThreadingHTTPServer constructor above.
        self._thread = threading.Thread(  # swtpu-check: ignore[thread-roots]
            target=self._httpd.serve_forever, name="swtpu-obs-http",
            daemon=True)
        self._started = False

    def _health(self):
        if self._health_fn is None:
            return 200, {"status": "ok"}
        try:
            payload = dict(self._health_fn())
        except Exception as e:  # noqa: BLE001 - a health probe must
            # report the failure, not take the exporter thread down.
            logger.exception("health callback failed")
            return 500, {"status": "error", "error": f"{type(e).__name__}: {e}"}
        payload.setdefault("status", "ok")
        return 200, payload

    def _history(self):
        if self._history_fn is None:
            return 404, {"status": "no_history",
                         "detail": "this process keeps no telemetry "
                                   "history (see /metrics for live "
                                   "gauges)"}
        try:
            return 200, dict(self._history_fn())
        except Exception as e:  # noqa: BLE001 - history is telemetry;
            # a broken ring must report, not take the exporter down.
            logger.exception("history callback failed")
            return 500, {"status": "error",
                         "error": f"{type(e).__name__}: {e}"}

    @property
    def port(self) -> int:
        """The bound port (resolves port=0 to the ephemeral choice)."""
        return self._httpd.server_address[1]

    def start(self) -> "ObsHttpServer":
        if not self._started:
            self._thread.start()
            self._started = True
            logger.info("obs endpoint serving /metrics and /healthz on "
                        "port %d", self.port)
        return self

    def stop(self) -> None:
        if self._started:
            self._httpd.shutdown()
            self._started = False
        self._httpd.server_close()
