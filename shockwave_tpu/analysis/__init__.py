"""swtpu-check: invariant-enforcing static analysis + runtime sanitizer.

``python -m shockwave_tpu.analysis`` runs five AST-based, repo-aware
passes over the tree (exit 0 clean / 1 findings, ``file:line`` format);
``analysis/sanitizer.py`` is the runtime half — instrumented locks that
detect lock-order cycles and unowned protected-state access under
``SWTPU_SANITIZE=1``. See README "Static analysis & invariants".

Kept import-light on purpose: ``core/locking.requires_lock`` imports
``analysis.sanitizer`` on every annotated call, so this package must
not pull in the AST machinery (or anything heavy) at import time.
"""
from . import sanitizer
from .sanitizer import enabled, maybe_wrap, monitor

__all__ = ["sanitizer", "enabled", "maybe_wrap", "monitor"]
