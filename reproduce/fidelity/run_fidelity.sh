#!/bin/bash
# Physical-vs-simulation fidelity experiment on one real TPU chip
# (counterpart of the reference's reproduce/tacc_32gpus_comparison flow,
# analyze_fidelity.py:31-56, scaled to a single-chip loopback).
#
# Runs the 3-job trace through the REAL scheduler + worker daemon + job
# subprocesses on the attached chip, then the same trace in simulation
# against the measured v5e oracle, and checks the metrics agree.
#
# Tips: pre-warm the XLA compile cache by running each workload once for
# a few steps (first-dispatch compiles otherwise eat into round 0), and
# keep round_duration >= 120 s.
set -eu
cd "$(dirname "$0")/../.."
OUT=${1:-reproduce/fidelity/out}   # untracked by default; pass
                                   # reproduce/fidelity to refresh the
                                   # committed artifacts deliberately
PORT=${2:-50381}
ROUND=120
TRACE=reproduce/fidelity/fidelity_3job.trace
CKPT=$(mktemp -d /tmp/swtpu_fidelity.XXXX)
mkdir -p "$OUT"

python scripts/drivers/run_physical.py \
    --trace "$TRACE" --policy max_min_fairness \
    --throughputs data/v5e_throughputs.json \
    --expected_num_workers 1 --round_duration "$ROUND" --port "$PORT" \
    --timeout 3600 --timeline_dir "$OUT/timelines" \
    --output "$OUT/physical_v5e.pkl" --verbose &
SCHED_PID=$!
# The worker must die with the script, even if the scheduler fails.
WORKER_PID=""
trap '[ -n "$WORKER_PID" ] && kill "$WORKER_PID" 2>/dev/null || true' EXIT
sleep 5
python -m shockwave_tpu.runtime.worker --worker_type v5e \
    --sched_addr 127.0.0.1 --sched_port "$PORT" --worker_port "$((PORT+1))" \
    --num_chips 1 --data_dir /tmp/swtpu_data --checkpoint_dir "$CKPT" &
WORKER_PID=$!

wait "$SCHED_PID"
kill "$WORKER_PID" 2>/dev/null || true

python scripts/drivers/simulate.py \
    --trace "$TRACE" --policy max_min_fairness \
    --throughputs data/v5e_throughputs.json \
    --cluster_spec v5e:1 --round_duration "$ROUND" \
    --output "$OUT/simulated_v5e.pkl"

python reproduce/analyze_fidelity.py \
    "$OUT/physical_v5e.pkl" "$OUT/simulated_v5e.pkl" --tolerance 0.15 \
    | tee "$OUT/fidelity_report.txt"
