"""Input pipelines: real dataset loaders with synthetic fallbacks.

CIFAR-10 (pickled python batches or .npz) and wikitext-2 (tokens files)
load from disk when a data directory containing them is passed —
matching the reference's torchvision/corpus loaders
(workloads/pytorch/image_classification/cifar10/main.py:118-137,
language_modeling/word_language_model/data.py). When no directory is
given or the files are absent (CI, benchmarks, dry runs), deterministic
synthetic batches of the right shapes are produced on host instead —
the reference's GavelIterator had the same synthetic-data escape hatch
(gavel_iterator.py:89-92). Loaders expose `.synthetic` so the lease
iterator only caches batches on the synthetic path. multi30k /
monet2photo / ml20m are synthetic-only for now.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np


class SyntheticBatches:
    """A fixed-length epoch of host-generated batches.

    SWTPU_SYNTH_EPOCH_BATCHES overrides the epoch length — epoch-driven
    mechanisms (the Accordion monitor decides once per epoch) are
    untestable end-to-end on CPU against dataset-sized epochs."""

    synthetic = True

    def __init__(self, make_batch, batches_per_epoch: int, seed: int = 0):
        self._make_batch = make_batch
        override = int(os.environ.get("SWTPU_SYNTH_EPOCH_BATCHES", "0"))
        self._len = override if override > 0 else max(1, batches_per_epoch)
        rng = np.random.RandomState(seed)
        # One real batch, reused; keeps host CPU out of the hot loop.
        self._batch = make_batch(rng)

    def __len__(self):
        return self._len

    def __iter__(self):
        for _ in range(self._len):
            yield self._batch


class ArrayBatches:
    """An epoch over in-memory arrays, reshuffled each epoch. Partial
    trailing batches are dropped: every yielded batch has the full
    batch_size leading dim, as fixed-shape jit/sharding requires."""

    synthetic = False

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int,
                 seed: int = 0, shuffle: bool = True):
        self._arrays = arrays
        self._bs = batch_size
        self._rng = np.random.RandomState(seed)
        self._shuffle = shuffle
        self._n = arrays[0].shape[0]
        if self._n < batch_size:
            raise ValueError(
                f"dataset has {self._n} samples < batch_size {batch_size}")

    def __len__(self):
        return self._n // self._bs

    def __iter__(self):
        order = (self._rng.permutation(self._n) if self._shuffle
                 else np.arange(self._n))
        for i in range(len(self)):
            idx = order[i * self._bs:(i + 1) * self._bs]
            yield tuple(a[idx] for a in self._arrays)


def _load_cifar10(data_dir: str) -> Optional[tuple]:
    """Read CIFAR-10 from `data_dir`: either the standard pickled python
    batches (cifar-10-batches-py/data_batch_*) or a cifar10.npz with
    images/labels arrays. Returns (images NHWC float32 in [0,1], labels
    int32) or None when absent."""
    batch_dir = None
    for cand in (data_dir, os.path.join(data_dir, "cifar-10-batches-py")):
        if os.path.exists(os.path.join(cand, "data_batch_1")):
            batch_dir = cand
            break
    if batch_dir is not None:
        images, labels = [], []
        for i in range(1, 6):
            with open(os.path.join(batch_dir, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(np.asarray(d[b"data"], np.uint8))
            labels.append(np.asarray(d[b"labels"], np.int64))
        x = np.concatenate(images).reshape(-1, 3, 32, 32)
        x = x.transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        y = np.concatenate(labels).astype(np.int32)
        return x, y
    npz = os.path.join(data_dir, "cifar10.npz")
    if os.path.exists(npz):
        d = np.load(npz)
        x = np.asarray(d["images"], np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        return x, np.asarray(d["labels"], np.int32)
    return None


def cifar10(batch_size: int, data_dir: Optional[str] = None,
            dataset_size: int = 50000, seed: int = 0):
    if data_dir:
        real = _load_cifar10(data_dir)
        if real is not None and real[0].shape[0] >= batch_size:
            return ArrayBatches(real, batch_size, seed)

    def make(rng):
        return (rng.rand(batch_size, 32, 32, 3).astype(np.float32),
                rng.randint(0, 10, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def imagenet(batch_size: int, dataset_size: int = 100000, seed: int = 0):
    def make(rng):
        return (rng.rand(batch_size, 224, 224, 3).astype(np.float32),
                rng.randint(0, 1000, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def multi30k(batch_size: int, src_len: int = 32, tgt_len: int = 32,
             vocab: int = 9521, dataset_size: int = 10000, seed: int = 0):
    def make(rng):
        src = rng.randint(1, vocab, size=(batch_size, src_len)).astype(np.int32)
        tgt = rng.randint(1, vocab, size=(batch_size, tgt_len)).astype(np.int32)
        return src, tgt
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def _load_wikitext2(data_dir: str, seq_len: int,
                    vocab_cap: int) -> Optional[tuple]:
    """Read wikitext-2 word-level LM windows from `data_dir`
    (wiki.train.tokens or train.txt). Builds a frequency-ranked vocab
    capped at `vocab_cap` (rarer words -> <unk>=0) and slices the token
    stream into (seq_len + 1)-long windows, reference-style batchify
    (word_language_model/data.py)."""
    path = None
    for cand in ("wiki.train.tokens", "train.txt",
                 os.path.join("wikitext-2", "wiki.train.tokens")):
        full = os.path.join(data_dir, cand)
        if os.path.exists(full):
            path = full
            break
    if path is None:
        return None
    with open(path, encoding="utf-8") as f:
        words = f.read().split()
    uniq, counts = np.unique(np.asarray(words), return_counts=True)
    keep = uniq[np.argsort(-counts)][: vocab_cap - 1]
    ids = {w: i + 1 for i, w in enumerate(keep)}  # 0 = <unk>
    stream = np.fromiter((ids.get(w, 0) for w in words), np.int32,
                         count=len(words))
    n_windows = (len(stream) - 1) // (seq_len + 1)
    if n_windows == 0:
        return None
    windows = stream[: n_windows * (seq_len + 1)].reshape(
        n_windows, seq_len + 1)
    return (windows[:, :-1], windows[:, 1:])


def wikitext2(batch_size: int, seq_len: int = 35, vocab: int = 33278,
              dataset_size: int = 59675, seed: int = 0,
              data_dir: Optional[str] = None):
    if data_dir:
        real = _load_wikitext2(data_dir, seq_len, vocab)
        if real is not None and real[0].shape[0] >= batch_size:
            return ArrayBatches(real, batch_size, seed)

    def make(rng):
        tokens = rng.randint(1, vocab, size=(batch_size, seq_len + 1)).astype(np.int32)
        return tokens[:, :-1], tokens[:, 1:]
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def monet2photo(batch_size: int, image_size: int = 128,
                dataset_size: int = 1193, seed: int = 0):
    """Unpaired image batches for CycleGAN (domains A=paintings, B=photos)."""
    def make(rng):
        a = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        b = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        return a.astype(np.float32), b.astype(np.float32)
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def ml20m(batch_size: int, num_items: int = 20108, dataset_size: int = 117907,
          seed: int = 0):
    def make(rng):
        # ~1% interaction density multi-hot rows.
        rows = (rng.rand(batch_size, num_items) < 0.01).astype(np.float32)
        return (rows,)
    return SyntheticBatches(make, dataset_size // batch_size, seed)
