"""Durability-layer semantics: journal framing, torn-tail truncation,
snapshot atomicity/fallback, compaction bounds, and replay determinism
(same journal -> identical scheduler state)."""
import os
import pickle
import subprocess
import sys

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.sched.journal import (JOURNAL_MAGIC, TAIL_CLEAN,
                                         TAIL_TORN, DurabilityLayer,
                                         JournalWriter, list_segments,
                                         load_snapshot, load_state,
                                         read_journal, write_snapshot)
from shockwave_tpu.sched.scheduler import Scheduler
from shockwave_tpu.solver import get_policy

TESTS_DIR = os.path.dirname(__file__)
DATA = os.path.join(TESTS_DIR, "..", "data")
FSCK = os.path.join(TESTS_DIR, "..", "scripts", "utils", "fsck_journal.py")


def _write_events(layer, n, etype="ev"):
    return [layer.record(etype, {"i": i}) for i in range(n)]


class TestFraming:
    def test_roundtrip(self, tmp_path):
        layer = DurabilityLayer(str(tmp_path))
        _write_events(layer, 5)
        layer.close()
        (seg,) = list_segments(str(tmp_path))
        records, status = read_journal(seg)
        assert status == TAIL_CLEAN
        assert [r["data"]["i"] for r in records] == list(range(5))
        assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]

    def test_torn_tail_discarded_not_fatal(self, tmp_path):
        layer = DurabilityLayer(str(tmp_path))
        _write_events(layer, 3)
        layer.close()
        (seg,) = list_segments(str(tmp_path))
        # Chop the last record in half: a crash mid-append.
        size = os.path.getsize(seg)
        with open(seg, "r+b") as f:
            f.truncate(size - 7)
        records, status = read_journal(seg)
        assert status == TAIL_TORN
        assert [r["data"]["i"] for r in records] == [0, 1]
        # Recovery consumes it without complaint.
        rec = load_state(str(tmp_path))
        assert len(rec.events) == 2
        assert rec.tail_status == TAIL_TORN

    def test_corrupt_record_stops_read(self, tmp_path):
        layer = DurabilityLayer(str(tmp_path))
        _write_events(layer, 3)
        layer.close()
        (seg,) = list_segments(str(tmp_path))
        with open(seg, "r+b") as f:
            blob = f.read()
            # Flip a byte in the middle of the SECOND record's payload.
            f.seek(len(blob) // 2)
            orig = blob[len(blob) // 2]
            f.write(bytes([orig ^ 0xFF]))
        records, status = read_journal(seg)
        assert status == TAIL_TORN
        assert len(records) < 3

    def test_reopen_truncates_torn_tail_and_appends(self, tmp_path):
        layer = DurabilityLayer(str(tmp_path))
        _write_events(layer, 3)
        layer.close()
        (seg,) = list_segments(str(tmp_path))
        with open(seg, "ab") as f:
            f.write(b"\x99\x00\x00\x00partial-crash-garbage")
        # Reopen: the torn tail must be truncated so new appends land at
        # a record boundary and stay readable.
        layer2 = DurabilityLayer(str(tmp_path))
        layer2.record("after", {"ok": True})
        layer2.close()
        records, status = read_journal(seg)
        assert status == TAIL_CLEAN
        assert [r["type"] for r in records] == ["ev", "ev", "ev", "after"]
        assert records[-1]["seq"] == 4  # seq continued, not restarted

    def test_bad_magic_rejected(self, tmp_path):
        path = tmp_path / "journal.000000000001.log"
        path.write_bytes(b"not a journal at all")
        from shockwave_tpu.sched.journal import JournalError
        with pytest.raises(JournalError):
            read_journal(str(path))
        assert JOURNAL_MAGIC not in path.read_bytes()


class TestSnapshots:
    def test_roundtrip_and_prev_fallback(self, tmp_path):
        d = str(tmp_path)
        write_snapshot(d, {"state": {"v": 1}, "last_seq": 10})
        write_snapshot(d, {"state": {"v": 2}, "last_seq": 20})
        assert load_snapshot(d)["state"]["v"] == 2
        # Corrupt the current snapshot: loader falls back to previous.
        with open(os.path.join(d, "snapshot.pkl"), "r+b") as f:
            f.seek(3)
            f.write(b"\xde\xad\xbe\xef")
        snap = load_snapshot(d)
        assert snap is not None and snap["state"]["v"] == 1
        # Both corrupt: None, not a crash.
        with open(os.path.join(d, "snapshot.pkl.prev"), "r+b") as f:
            f.seek(3)
            f.write(b"\xde\xad\xbe\xef")
        assert load_snapshot(d) is None

    def test_tmp_leftover_ignored(self, tmp_path):
        d = str(tmp_path)
        # A crash mid-write leaves only the tmp file: no snapshot.
        with open(os.path.join(d, "snapshot.pkl.tmp"), "wb") as f:
            f.write(b"half-written")
        assert load_snapshot(d) is None


class TestCompaction:
    def test_snapshot_bounds_journal_size(self, tmp_path):
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        for batch in range(4):
            _write_events(layer, 50)
            layer.snapshot({"state": {"batch": batch}})
            segs = list_segments(d)
            # One retained previous-interval segment (the .prev
            # snapshot's replay tail) + one fresh magic-only segment:
            # journal size is bounded by TWO intervals, not growing.
            assert len(segs) <= 2
            retained = sum(len(read_journal(p)[0]) for p in segs)
            assert retained <= 50  # at most one interval of records kept
        _write_events(layer, 5)
        layer.close()
        rec = load_state(d)
        # Only post-snapshot events replay; the snapshot covers the rest.
        assert len(rec.events) == 5
        assert rec.snapshot["state"]["batch"] == 3
        assert rec.snapshot["last_seq"] == 200
        assert [e["seq"] for e in rec.events] == [201, 202, 203, 204, 205]

    def test_prev_snapshot_fallback_can_still_replay(self, tmp_path):
        """If the current snapshot corrupts, recovery through .prev must
        find every event after the PREVIOUS horizon still on disk —
        compaction may only delete what .prev no longer needs."""
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 50)
        layer.snapshot({"state": {"gen": 1}})   # .prev-to-be, covers 50
        _write_events(layer, 50)
        layer.snapshot({"state": {"gen": 2}})   # current, covers 100
        _write_events(layer, 5)
        layer.close()
        with open(os.path.join(d, "snapshot.pkl"), "r+b") as f:
            f.seek(10)
            f.write(b"\xde\xad\xbe\xef")
        rec = load_state(d)
        assert rec.snapshot["state"]["gen"] == 1
        # Everything after gen-1's horizon replays: 51..105.
        assert [e["seq"] for e in rec.events] == list(range(51, 106))

    def test_interrupted_snapshot_rotation_keeps_needed_events(
            self, tmp_path):
        """Crash AFTER write_snapshot but BEFORE segment rotation leaves
        one segment spanning the snapshot horizon; the next compaction
        must keep it (it holds events past the .prev horizon)."""
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 13)
        layer.close()
        # Simulate the interrupted snapshot: written, never rotated.
        write_snapshot(d, {"state": {"gen": 1}, "last_seq": 13})
        layer = DurabilityLayer(d)  # continues the spanning segment
        _write_events(layer, 3)     # seqs 14..16
        layer.snapshot({"state": {"gen": 2}})  # rotates gen 1 to .prev
        layer.close()
        # Corrupt gen 2: recovery via gen 1 must still see 14..16.
        with open(os.path.join(d, "snapshot.pkl"), "r+b") as f:
            f.seek(10)
            f.write(b"\xde\xad\xbe\xef")
        rec = load_state(d)
        assert rec.snapshot["state"]["gen"] == 1
        assert [e["seq"] for e in rec.events] == [14, 15, 16]

    def test_both_snapshots_unreadable_refuses_truncated_replay(
            self, tmp_path):
        """With the journal head compacted away and BOTH snapshot
        generations corrupt, recovery must refuse loudly — replaying
        the surviving tail onto an empty scheduler would renumber every
        job and silently drop accounting."""
        from shockwave_tpu.sched.journal import JournalError
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 50)
        layer.snapshot({"state": {"gen": 1}})
        _write_events(layer, 50)
        layer.snapshot({"state": {"gen": 2}})  # seq 1..50 now deleted
        _write_events(layer, 5)
        layer.close()
        for name in ("snapshot.pkl", "snapshot.pkl.prev"):
            with open(os.path.join(d, name), "r+b") as f:
                f.seek(10)
                f.write(b"\xde\xad\xbe\xef")
        with pytest.raises(JournalError, match="unrecoverable"):
            load_state(d)

    def test_has_state_sees_prev_only_state(self, tmp_path):
        """A dir whose current snapshot is corrupt but whose .prev loads
        is STILL stateful — a fresh non-resume run must refuse it."""
        from shockwave_tpu.sched.journal import has_state
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 10)
        layer.snapshot({"state": {"gen": 1}})
        layer.snapshot({"state": {"gen": 2}})   # rotates gen 1 to .prev
        layer.close()
        with open(os.path.join(d, "snapshot.pkl"), "r+b") as f:
            f.seek(10)
            f.write(b"\xde\xad\xbe\xef")
        assert has_state(d)

    def test_rotation_failure_keeps_wal_alive(self, tmp_path, monkeypatch):
        """If opening the fresh post-snapshot segment fails (ENOSPC,
        EACCES, ...), the layer must fall back to the previous segment
        — a silently closed writer would drop every later event."""
        import shockwave_tpu.sched.journal as jmod
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 5)
        real = jmod._segment_path

        def broken(state_dir, start_seq):
            return os.path.join(state_dir, "no-such-dir",
                                f"journal.{start_seq:012d}.log")

        monkeypatch.setattr(jmod, "_segment_path", broken)
        layer.snapshot({"state": {}})
        monkeypatch.setattr(jmod, "_segment_path", real)
        # The WAL still accepts (and persists) events.
        layer.record("after_failure", {"ok": True})
        layer.close()
        rec = load_state(d)
        assert [e["type"] for e in rec.events] == ["after_failure"]

    def test_crash_between_snapshot_and_compaction(self, tmp_path):
        """Events covered by the snapshot but not yet deleted must be
        skipped on recovery, not replayed twice."""
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 10)
        layer.close()
        # Snapshot written, crash before segment deletion: simulate by
        # writing the snapshot directly.
        write_snapshot(d, {"state": {}, "last_seq": 10})
        rec = load_state(d)
        assert rec.events == []
        layer2 = DurabilityLayer(d)
        assert layer2.record("next", {}) == 11
        layer2.close()


def _make_scheduler():
    return Scheduler(get_policy("max_min_fairness"),
                     throughputs_file=os.path.join(
                         DATA, "tacc_throughputs.json"))


def _job(total_steps):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=10000)


def _comparable_state(s):
    """Plain-data projection of the replay-relevant scheduler state."""
    return {
        "job_id_counter": s._job_id_counter,
        "total_steps_run": dict(s.acct.total_steps_run),
        "steps_run": {k: dict(v) for k, v in s.acct.steps_run.items()},
        "failures": dict(s.acct.failures),
        "completion_times": dict(s.acct.completion_times),
        "start_timestamps": dict(s.acct.start_timestamps),
        "completed": sorted(repr(j) for j in s._completed_jobs),
        "cluster_spec": dict(s.workers.cluster_spec),
        "worker_ids": list(s.workers.worker_ids),
        "dead": sorted(s.workers.dead),
        "per_round_schedule": list(s.rounds.per_round_schedule),
        "num_scheduled_rounds": dict(s.rounds.num_scheduled_rounds),
        "num_queued_rounds": dict(s.rounds.num_queued_rounds),
        "num_completed_rounds": s.rounds.num_completed_rounds,
        "throughputs": {repr(k): dict(v)
                        for k, v in s._throughputs.items()},
        "cost": dict(s._job_cost_so_far),
        "run_meta": dict(s._run_meta),
    }


def _drive_workload(sched):
    """A deterministic little history: workers, jobs, progress, a
    completion, a failure, a worker retirement."""
    sched.record_run_meta(start_time=100.0, trace="t.trace")
    sched.register_worker("v100", 2)
    j0 = sched.add_job(_job(300), timestamp=1.0)
    j1 = sched.add_job(_job(100), timestamp=2.0)
    sched._record_round({0: (0,), 1: (1,)})

    def complete(jid, worker, steps, ts):
        sched.rounds.current_assignments[jid] = (worker,)
        sched._running_jobs.add(jid)
        sched.acct.latest_timestamps[jid] = ts
        sched.done_callback(jid, worker, [steps], [4.0])
        sched.rounds.completed_in_round.discard(jid)

    complete(j0, 0, 200, 5.0)     # partial progress
    complete(j1, 1, 0, 6.0)       # failed micro-task (zero steps)
    complete(j1, 1, 100, 8.0)     # second attempt completes job 1
    sched.deregister_workers([1])  # lose a chip
    return j0, j1


@pytest.mark.recovery
class TestReplayDeterminism:
    def test_same_journal_identical_state(self, tmp_path):
        d = str(tmp_path)
        live = _make_scheduler()
        layer = DurabilityLayer(d)
        live.attach_durability(layer)
        _drive_workload(live)
        layer.close()

        recovered = load_state(d)
        assert recovered.events, "journal captured nothing"
        replicas = []
        for _ in range(2):
            s = _make_scheduler()
            s.restore_from_durable_state(recovered)
            replicas.append(s)
        assert _comparable_state(replicas[0]) == _comparable_state(
            replicas[1])
        # And the replay reproduces the LIVE accounting, not just a
        # self-consistent one.
        assert _comparable_state(replicas[0]) == _comparable_state(live)
        assert replicas[0].acct.total_steps_run[JobIdPair(0)] == 200
        assert JobIdPair(1) in replicas[0]._completed_jobs
        # The failed attempt is visible, the success reset it to 0 —
        # and the job completed so the counter entry is gone.
        assert JobIdPair(1) not in replicas[0].acct.failures

    def test_snapshot_plus_tail_equals_full_replay(self, tmp_path):
        """Recovery through a mid-history snapshot must land on the same
        state as a journal-only replay of the full history."""
        d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
        # Run A: snapshot mid-way, journal the rest.
        a = _make_scheduler()
        layer_a = DurabilityLayer(d1)
        a.attach_durability(layer_a)
        a.record_run_meta(start_time=100.0, trace="t.trace")
        a.register_worker("v100", 2)
        a.add_job(_job(300), timestamp=1.0)
        layer_a.snapshot({"state": a.snapshot_state()})
        j1 = a.add_job(_job(100), timestamp=2.0)
        a.rounds.current_assignments[j1] = (1,)
        a._running_jobs.add(j1)
        a.acct.latest_timestamps[j1] = 8.0
        a.done_callback(j1, 1, [100], [4.0])
        layer_a.close()
        # Run B: identical history, no snapshot.
        b = _make_scheduler()
        layer_b = DurabilityLayer(d2)
        b.attach_durability(layer_b)
        b.record_run_meta(start_time=100.0, trace="t.trace")
        b.register_worker("v100", 2)
        b.add_job(_job(300), timestamp=1.0)
        j1b = b.add_job(_job(100), timestamp=2.0)
        b.rounds.current_assignments[j1b] = (1,)
        b._running_jobs.add(j1b)
        b.acct.latest_timestamps[j1b] = 8.0
        b.done_callback(j1b, 1, [100], [4.0])
        layer_b.close()

        ra, rb = _make_scheduler(), _make_scheduler()
        ra.restore_from_durable_state(load_state(d1))
        rb.restore_from_durable_state(load_state(d2))
        assert _comparable_state(ra) == _comparable_state(rb)
        assert ra.run_meta["start_time"] == 100.0

    def test_unknown_event_skipped(self, tmp_path):
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        layer.record("event_from_the_future", {"x": 1})
        layer.record("run_meta", {"start_time": 7.0})
        layer.close()
        s = _make_scheduler()
        s.restore_from_durable_state(load_state(d))
        assert s.run_meta == {"start_time": 7.0}


@pytest.mark.recovery
class TestFsckValidator:
    def _run(self, state_dir):
        env = dict(os.environ)
        return subprocess.run(
            [sys.executable, FSCK, state_dir, "--verbose"],
            capture_output=True, text=True, env=env, timeout=60)

    def test_clean_state_passes(self, tmp_path):
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 20)
        layer.snapshot({"state": {}})
        _write_events(layer, 3)
        layer.close()
        proc = self._run(d)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "CLEAN" in proc.stdout

    def test_torn_tail_reports_recoverable(self, tmp_path):
        d = str(tmp_path)
        layer = DurabilityLayer(d)
        _write_events(layer, 3)
        layer.close()
        (seg,) = list_segments(d)
        with open(seg, "ab") as f:
            f.write(b"\x42\x00\x00half a record")
        proc = self._run(d)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "TORN" in proc.stdout

    def test_unusable_state(self, tmp_path):
        proc = self._run(str(tmp_path))  # empty dir: nothing to recover
        assert proc.returncode == 2

    def test_gap_in_replayable_stream_flagged(self, tmp_path):
        """A lost segment leaves a seq gap past the snapshot horizon:
        recovery would silently skip those events, so fsck must flag
        the dir as unusable rather than CLEAN."""
        d = str(tmp_path)
        write_snapshot(d, {"state": {}, "last_seq": 10})
        w = JournalWriter(os.path.join(d, "journal.000000000011.log"))
        for seq in (11, 12, 17, 18):  # 13..16 lost with their segment
            w.append({"seq": seq, "type": "ev", "data": {}})
        w.close()
        proc = self._run(d)
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "GAP" in proc.stdout


class TestSnapshotPayloadIsSelfContained:
    def test_snapshot_pickles_and_restores_shared_structure(self, tmp_path):
        """Planner metadata and the scheduler's throughput timelines
        share OrderedDicts; a snapshot must preserve the sharing."""
        s = _make_scheduler()
        s.register_worker("v100", 1)
        s.add_job(_job(300), timestamp=1.0)
        blob = pickle.dumps({"state": s.snapshot_state()})
        state = pickle.loads(blob)["state"]
        s2 = _make_scheduler()
        s2.restore_state(state)
        assert s2._job_id_counter == 1
        assert JobIdPair(0) in s2.acct.jobs
        assert s2.workers.cluster_spec == {"v100": 1}
