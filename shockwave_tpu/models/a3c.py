"""A3C actor-critic with a pure-JAX vectorized environment.

The reference trains A3C on Pong with 4 asynchronous CPU actor processes
sharing a model (workloads/pytorch/rl/{main,train,model}.py,
shared_optim.py). Asynchronous Hogwild updates are a poor fit for TPU —
the idiomatic redesign runs the actors as a *batch dimension*: a
vectorized Catch/Pong-style environment written in JAX, an n-step
actor-critic unroll under `lax.scan`, and one fused update per tick, so
the whole act->learn loop is a single compiled XLA program (actors are
synchronous-parallel instead of asynchronous; same algorithm family,
MXU-friendly execution).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

GRID_H = 16
GRID_W = 16
NUM_ACTIONS = 3  # left, stay, right


class EnvState(NamedTuple):
    ball_y: jnp.ndarray   # [B] int32
    ball_x: jnp.ndarray   # [B] int32
    ball_dx: jnp.ndarray  # [B] int32 in {-1, 0, 1}
    paddle_x: jnp.ndarray  # [B] int32
    rng: jnp.ndarray      # [B, 2] uint32 per-env keys


def env_reset(rng: jnp.ndarray, batch: int) -> EnvState:
    col_key, dx_key, env_keys = jax.random.split(rng, 3)
    keys = jax.random.split(env_keys, batch)
    cols = jax.random.randint(col_key, (batch,), 0, GRID_W)
    dxs = jax.random.randint(dx_key, (batch,), -1, 2)
    return EnvState(ball_y=jnp.zeros((batch,), jnp.int32),
                    ball_x=cols.astype(jnp.int32),
                    ball_dx=dxs.astype(jnp.int32),
                    paddle_x=jnp.full((batch,), GRID_W // 2, jnp.int32),
                    rng=keys)


def env_observe(state: EnvState) -> jnp.ndarray:
    """[B, H, W, 2] float32 one-hot planes (ball, paddle)."""
    b = state.ball_y.shape[0]
    ball = jnp.zeros((b, GRID_H, GRID_W))
    ball = ball.at[jnp.arange(b), state.ball_y, state.ball_x].set(1.0)
    paddle = jnp.zeros((b, GRID_H, GRID_W))
    paddle = paddle.at[jnp.arange(b), GRID_H - 1, state.paddle_x].set(1.0)
    return jnp.stack([ball, paddle], axis=-1)


def env_step(state: EnvState, action: jnp.ndarray) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray]:
    """Batched transition. Returns (next_state, reward, done)."""
    paddle = jnp.clip(state.paddle_x + action - 1, 0, GRID_W - 1)
    ball_x = jnp.clip(state.ball_x + state.ball_dx, 0, GRID_W - 1)
    ball_y = state.ball_y + 1
    done = ball_y >= GRID_H - 1
    reward = jnp.where(done,
                       jnp.where(ball_x == paddle, 1.0, -1.0),
                       0.0)
    # Per-env auto-reset on done.
    next_keys = jax.vmap(lambda k: jax.random.split(k, 3))(state.rng)
    reset_col = jax.vmap(lambda k: jax.random.randint(k, (), 0, GRID_W))(
        next_keys[:, 0])
    reset_dx = jax.vmap(lambda k: jax.random.randint(k, (), -1, 2))(
        next_keys[:, 1])
    new_rng = jnp.where(done[:, None], next_keys[:, 2], state.rng)
    return (EnvState(
        ball_y=jnp.where(done, 0, ball_y).astype(jnp.int32),
        ball_x=jnp.where(done, reset_col, ball_x).astype(jnp.int32),
        ball_dx=jnp.where(done, reset_dx, state.ball_dx).astype(jnp.int32),
        paddle_x=paddle.astype(jnp.int32),
        rng=new_rng,
    ), reward, done)


class ActorCritic(nn.Module):
    """Conv torso + policy/value heads (stand-in for the reference's
    A3Clstm; recurrence is unnecessary for a fully observed grid)."""
    hidden: int = 128

    @nn.compact
    def __call__(self, obs):
        x = nn.Conv(16, (3, 3), padding="SAME")(obs)
        x = nn.relu(x)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME")(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        logits = nn.Dense(NUM_ACTIONS)(x)
        value = nn.Dense(1)(x)
        return logits, value[..., 0]


def build_a3c_update(model: ActorCritic, tx, unroll: int = 20,
                     gamma: float = 0.99, tau: float = 1.0,
                     value_coef: float = 0.5, entropy_coef: float = 0.01):
    """One A3C tick: unroll `unroll` env steps with the current policy,
    compute GAE advantages, apply one gradient update. jit-able."""

    def rollout(params, env_state, rng):
        def step(carry, _):
            env_state, rng = carry
            obs = env_observe(env_state)
            logits, value = model.apply({"params": params}, obs)
            rng, sub = jax.random.split(rng)
            action = jax.random.categorical(sub, logits)
            next_state, reward, done = env_step(env_state, action)
            out = (obs, action, reward, done, value)
            return (next_state, rng), out
        (env_state, rng), traj = jax.lax.scan(
            step, (env_state, rng), None, length=unroll)
        return env_state, rng, traj

    def loss_fn(params, traj, last_value):
        obs, actions, rewards, dones, values = traj
        not_done = 1.0 - dones.astype(jnp.float32)
        # GAE over the unroll (time-major [T, B]).
        def scan_adv(carry, t):
            gae, next_value = carry
            delta = (rewards[t] + gamma * next_value * not_done[t]
                     - values[t])
            gae = delta + gamma * tau * not_done[t] * gae
            return (gae, values[t]), gae
        ts = jnp.arange(rewards.shape[0] - 1, -1, -1)
        (_, _), advs = jax.lax.scan(
            scan_adv, (jnp.zeros_like(last_value), last_value), ts)
        advs = advs[::-1]
        returns = advs + values
        # Re-evaluate policy on the stored observations (fresh grads).
        flat_obs = obs.reshape((-1,) + obs.shape[2:])
        logits, value = model.apply({"params": params}, flat_obs)
        logp = jax.nn.log_softmax(logits)
        value = value.reshape(rewards.shape)
        logp = logp.reshape(rewards.shape + (NUM_ACTIONS,))
        taken = jnp.take_along_axis(
            logp, actions[..., None], axis=-1)[..., 0]
        adv = jax.lax.stop_gradient(advs)
        policy_loss = -(taken * adv).mean()
        value_loss = ((value - jax.lax.stop_gradient(returns)) ** 2).mean()
        entropy = -(jnp.exp(logp) * logp).sum(-1).mean()
        loss = (policy_loss + value_coef * value_loss
                - entropy_coef * entropy)
        return loss, {"policy_loss": policy_loss, "value_loss": value_loss,
                      "entropy": entropy,
                      "reward": rewards.sum(0).mean()}

    def update(train_state, env_state):
        params, opt_state, rng, step_no = (
            train_state["params"], train_state["opt_state"],
            train_state["rng"], train_state["step"])
        env_state, rng, traj = rollout(params, env_state, rng)
        _, last_value = model.apply({"params": params},
                                    env_observe(env_state))
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, traj, jax.lax.stop_gradient(last_value))
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["loss"] = loss
        new_train_state = dict(train_state, params=params,
                               opt_state=opt_state, rng=rng,
                               step=step_no + 1)
        return new_train_state, env_state, metrics

    return jax.jit(update, donate_argnums=(0, 1))
