"""Runtime concurrency sanitizer: instrumented locks + ownership checks.

The static lock-discipline pass proves field accesses are *lexically*
covered by a lock; this module closes the dynamic half of the story:

- ``SanitizedLock`` wraps a ``threading.RLock``/``Lock`` and records,
  per acquisition, the set of locks already held by the acquiring
  thread. Those (held -> acquired) edges form the process-wide
  lock-acquisition **order graph**; the moment an edge closes a cycle
  (thread A takes L1 then L2 while thread B takes L2 then L1 — a
  deadlock waiting for the right interleaving) a violation is recorded
  with both edges' stacks of lock names.
- It also tracks per-lock **hold times** (first acquire -> final
  release, recursion-aware), reporting the max per lock — the number
  that says whether an RPC handler is stalling the round pipeline.
- ``@requires_lock`` methods (core/locking.py) report an
  **unowned-access** violation when entered without the receiver's
  lock held.

Enabled by ``SWTPU_SANITIZE=1`` (any non-empty value other than "0").
The tier-1 conftest turns it on for every ``runtime``/``recovery``/
``faults``-marked test and asserts a clean report at teardown; in
production the wrapper is never installed (``maybe_wrap`` returns the
raw lock), so there is zero steady-state overhead.

Under ``SWTPU_SANITIZE_EXPLORE=<seed>`` (analysis/explorer.py) every
instrumented acquire/release additionally injects a seeded scheduling
perturbation, so N seeds exercise N deterministic-by-seed
interleavings of the same critical sections with all of the above
checks evaluated on each.

The wrapper deliberately implements the private RLock hooks
(``_is_owned`` / ``_release_save`` / ``_acquire_restore``) so a
``threading.Condition`` built on it — the scheduler's ``self._cv`` —
routes ``wait()``'s full release/reacquire through the bookkeeping.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Set

from . import explorer


def enabled() -> bool:
    return os.environ.get("SWTPU_SANITIZE", "0") not in ("", "0")


@dataclass
class Violation:
    kind: str      # "lock-order-cycle" | "unowned-access"
    message: str

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message}"


class _Monitor:
    """Process-wide registry: order graph, hold times, violations.

    Lock names (not instances) are the graph nodes, so two scheduler
    incarnations in one test (crash/restart) share one ordering
    discipline — which is exactly the invariant we want checked.
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._cycle_reported: Set[tuple] = set()
        self._violations: List[Violation] = []
        self._max_hold: Dict[str, float] = {}
        self._tls = threading.local()

    # -- per-thread held-lock stack ------------------------------------

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- events from SanitizedLock -------------------------------------

    def note_waiting(self, name: str) -> None:
        """Called BEFORE the (possibly blocking) inner acquire: the
        order edge and the cycle check must land while the thread can
        still report them — in an actual deadlock the acquire never
        returns, and a post-acquire record would name nothing."""
        held = self._held()
        if not held:
            return
        with self._mu:
            for outer in held:
                if outer == name:
                    continue
                self._edges.setdefault(outer, set()).add(name)
                if self._reaches(name, outer):
                    key = tuple(sorted((outer, name)))
                    if key not in self._cycle_reported:
                        self._cycle_reported.add(key)
                        self._violations.append(Violation(
                            "lock-order-cycle",
                            f"acquiring {name!r} while holding "
                            f"{outer!r}, but {outer!r} is also "
                            f"acquired while {name!r} is held "
                            "(deadlock potential)"))

    def note_acquired(self, name: str) -> None:
        self._held().append(name)

    def note_released(self, name: str, held_s: float) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        with self._mu:
            if held_s > self._max_hold.get(name, 0.0):
                self._max_hold[name] = held_s

    def _reaches(self, src: str, dst: str) -> bool:
        """Whether dst is reachable from src in the order graph.
        Caller holds self._mu."""
        seen, frontier = set(), [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            frontier.extend(self._edges.get(node, ()))
        return False

    # -- events from @requires_lock ------------------------------------

    def record_unowned(self, what: str) -> None:
        with self._mu:
            self._violations.append(Violation(
                "unowned-access",
                f"{what} entered without holding the receiver's lock"))

    # -- reporting -----------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                "violations": list(self._violations),
                "max_hold_s": dict(self._max_hold),
                "order_edges": {k: sorted(v)
                                for k, v in self._edges.items()},
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._cycle_reported.clear()
            self._violations.clear()
            self._max_hold.clear()
        # Per-thread held stacks are left alone on purpose: a daemon
        # thread mid-critical-section at reset time must still balance
        # its own acquires/releases.


_monitor = _Monitor()


def monitor() -> _Monitor:
    return _monitor


class SanitizedLock:
    """Instrumented wrapper around an RLock (or Lock).

    Recursion-aware: order edges and hold timing fire on the outermost
    acquire/release only, so ``with self._cv:`` nested inside
    ``with self._lock:`` (same underlying lock) records one hold."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name
        self._local = threading.local()

    # -- depth bookkeeping (per thread) --------------------------------

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _on_outermost_acquire(self) -> None:
        _monitor.note_acquired(self.name)
        self._local.t0 = time.monotonic()

    def _on_outermost_release(self) -> None:
        t0 = getattr(self._local, "t0", None)
        held_s = 0.0 if t0 is None else time.monotonic() - t0
        _monitor.note_released(self.name, held_s)

    # -- lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        outermost = self._depth() == 0
        if outermost:
            # Edge + cycle check BEFORE the potentially blocking inner
            # acquire (see note_waiting) — an attempted-but-failed
            # trylock still records the ordering fact, which is what
            # the discipline is about.
            _monitor.note_waiting(self.name)
            # Seeded interleaving exploration: perturb WHICH thread
            # wins the inner acquire (no-op unless installed).
            explorer.on_lock_event("acquire", self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            if outermost:
                self._on_outermost_acquire()
            self._local.depth = self._depth() + 1
        return got

    def release(self) -> None:
        depth = self._depth()
        self._inner.release()  # raises on unowned release before bookkeeping
        self._local.depth = max(depth - 1, 0)
        if depth <= 1:
            self._on_outermost_release()
            # Post-release perturbation: vary who enters next.
            explorer.on_lock_event("release", self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- private hooks Condition() relies on ---------------------------

    def _is_owned(self) -> bool:
        if self._depth() > 0:
            return True
        probe = getattr(self._inner, "_is_owned", None)
        return bool(probe()) if probe is not None else False

    def _release_save(self):
        depth = self._depth()
        self._local.depth = 0
        self._on_outermost_release()
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        _monitor.note_waiting(self.name)
        explorer.on_lock_event("acquire", self.name)
        self._inner._acquire_restore(inner_state)
        self._on_outermost_acquire()
        self._local.depth = depth

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} wrapping {self._inner!r}>"


def maybe_wrap(lock, name: str):
    """Instrument `lock` when the sanitizer is enabled; otherwise return
    it untouched (the production path — zero overhead)."""
    return SanitizedLock(lock, name) if enabled() else lock
