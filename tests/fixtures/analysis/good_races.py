"""Negative control for the race-detector pass: the same cross-thread
shape as bad_races.py, but every access holds the lock — plus the
exemption surfaces (thread-safe field types, init-frozen config,
documented registries) that must all stay quiet."""
import queue
import threading


class LockedCounter:
    def __init__(self, limit):
        self._lock = threading.Lock()
        self._total = 0
        self._limit = limit                  # init-frozen: read-only
        self._inbox = queue.Queue()          # thread-safe by type
        self._stop = threading.Event()       # thread-safe by type
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            self._inbox.get()
            with self._lock:
                self._total += 1

    def read(self):
        with self._lock:
            return min(self._total, self._limit)


class DocumentedCounter:
    """Registry verdict: the field is documented externally
    synchronized, so the detector stays quiet without a lexical lock."""

    _EXTERNALLY_SYNCHRONIZED = frozenset({"_total"})

    def __init__(self):
        self._total = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self._total += 1

    def read(self):
        return self._total
