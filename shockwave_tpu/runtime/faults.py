"""Deterministic fault-injection harness for the physical runtime.

Tests (and chaos drills) need to make a specific RPC fail, a specific
worker vanish, or a specific dispatch wedge — at an exact, repeatable
point, not by `sleep`-based luck. Rules are matched by method name at
two chokepoints:

- every server-side RPC handler (`rpc.generic_handler` calls
  `fire(service/method, context)` before the real handler), and
- the worker dispatcher (`dispatcher._dispatch_jobs_helper` consults
  `should_freeze("dispatch")` per job).

Actions:
- ``drop``       abort the RPC with UNAVAILABLE (connection-level failure
                 from the client's point of view; exercises retry paths).
- ``blackhole``  hold the RPC for ``delay_s`` (default 60 s) and then
                 abort — a client without a deadline would hang; a client
                 with one observes DEADLINE_EXCEEDED at its own budget.
- ``delay``      sleep ``delay_s`` then answer normally.
- ``freeze``     dispatcher only: launch nothing and report nothing for
                 the job, holding the chip — a wedged process.
- ``degrade``    dispatcher only: a multiplicative slowdown (``factor``
                 in (0, 1], default 0.1) — NOT a freeze. The worker
                 stays live (Ping answers, leases renew) but every
                 dispatched job runs at ``factor`` of its speed: the
                 gray-failure the quarantine layer exists to catch.
                 The dispatcher exports the factor to the training
                 process as ``SWTPU_DEGRADE_FACTOR`` and the job-side
                 LeaseIterator honors it by padding each step to
                 compute/factor (real trainers genuinely slow down);
                 the stub workers (tests/fault_stub_worker.py) consult
                 ``injector.slowdown("execute")`` directly to scale
                 their simulated throughput.

Each rule fires for matching calls number ``after`` .. ``after+times-1``
(per-rule call counter, so a test can say "drop the first two Done RPCs
then behave"). ``times=None`` means forever.

Configuration: programmatic via ``install()`` / ``clear()`` from tests,
or the ``SWTPU_FAULTS`` environment variable (a JSON list of rule
dicts) for subprocess workers, parsed once at first use.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import grpc

logger = logging.getLogger("shockwave_tpu.runtime")

ACTIONS = ("drop", "blackhole", "delay", "freeze", "degrade")


@dataclass
class FaultRule:
    #: Method to match: bare name ("Done"), full path
    #: ("shockwave_tpu.WorkerToScheduler/Done"), "dispatch", or "*".
    method: str
    action: str = "drop"
    delay_s: float = 0.0
    #: degrade only: multiplicative execution-speed factor in (0, 1].
    factor: float = 0.1
    #: Apply to at most this many matching calls (None = every call).
    times: Optional[int] = None
    #: Skip this many matching calls before the rule starts firing.
    after: int = 0
    _matched: int = field(default=0, repr=False)

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"expected one of {ACTIONS}")
        if self.action == "degrade" and not 0.0 < self.factor <= 1.0:
            raise ValueError(f"degrade factor must be in (0, 1], got "
                             f"{self.factor!r}")

    def matches(self, method: str) -> bool:
        if self.method == "*":
            return True
        return self.method == method or method.endswith("/" + self.method)

    def should_fire(self) -> bool:
        """Advance this rule's call counter; True when this call is in
        the rule's [after, after+times) firing window."""
        n = self._matched
        self._matched += 1
        if n < self.after:
            return False
        return self.times is None or n < self.after + self.times


class FaultInjector:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self.fired: List[tuple] = []  # (method, action) log for assertions

    def install(self, rules) -> None:
        """Replace the active rule set (list of FaultRule or rule dicts)."""
        parsed = [r if isinstance(r, FaultRule) else FaultRule(**r)
                  for r in rules]
        with self._lock:
            self._rules = parsed
            self.fired = []

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def active(self) -> bool:
        with self._lock:
            return bool(self._rules)

    def _next_action(self, method: str, actions) -> Optional[FaultRule]:
        """First matching rule whose action the calling chokepoint can
        apply. Rules with inapplicable actions are skipped WITHOUT
        advancing their firing window — a wildcard drop rule must not be
        silently consumed (and logged as fired) by a dispatch hook that
        can only freeze, or vice versa."""
        with self._lock:
            for rule in self._rules:
                if rule.action not in actions or not rule.matches(method):
                    continue
                if rule.should_fire():
                    self.fired.append((method, rule.action))
                    return rule
        return None

    def fire(self, method: str, context=None) -> None:
        """Server-side hook: maybe delay/abort the RPC named `method`."""
        rule = self._next_action(method, ("drop", "blackhole", "delay"))
        if rule is None:
            return
        logger.warning("fault injection: %s on %s", rule.action, method)
        if rule.action == "delay":
            time.sleep(rule.delay_s)
            return
        if rule.action == "blackhole":
            time.sleep(rule.delay_s if rule.delay_s > 0 else 60.0)
        if context is not None:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"fault injection ({rule.action})")
        else:  # no grpc context (direct-call tests): surface as RpcError
            raise _InjectedRpcError(method, rule.action)

    def should_freeze(self, method: str) -> bool:
        """Dispatcher-side hook: True when this dispatch must wedge."""
        rule = self._next_action(method, ("freeze",))
        if rule is None:
            return False
        logger.warning("fault injection: freezing dispatch of %s", method)
        return True

    def slowdown(self, method: str) -> float:
        """Dispatcher-side hook: multiplicative slowdown factor for this
        execution (1.0 = full speed). Each matching degrade rule's
        firing window advances once per call; overlapping rules
        compound, like stacked throttling causes would."""
        factor = 1.0
        with self._lock:
            for rule in self._rules:
                if rule.action != "degrade" or not rule.matches(method):
                    continue
                if rule.should_fire():
                    self.fired.append((method, rule.action))
                    factor *= rule.factor
        if factor < 1.0:
            logger.warning("fault injection: degrading %s to %.3fx speed",
                           method, factor)
        return factor


class _InjectedRpcError(grpc.RpcError):
    def __init__(self, method: str, action: str):
        super().__init__(f"fault injection: {action} on {method}")
        self._code = grpc.StatusCode.UNAVAILABLE

    def code(self):
        return self._code


_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def get_injector() -> FaultInjector:
    """Process-wide injector; seeds rules from $SWTPU_FAULTS on first use."""
    global _injector
    with _injector_lock:
        if _injector is None:
            _injector = FaultInjector()
            raw = os.environ.get("SWTPU_FAULTS")
            if raw:
                try:
                    _injector.install(json.loads(raw))
                    logger.warning("fault injection active from SWTPU_FAULTS")
                except (ValueError, TypeError) as e:
                    logger.error("bad SWTPU_FAULTS (%s); ignoring", e)
        return _injector
