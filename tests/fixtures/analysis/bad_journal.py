"""journal-coverage negative fixture: one emit without a handler, one
handler without an emit (lines marked SEEDED)."""


class BrokenJournaling:
    def _emit(self, etype, **data):
        pass

    def mutate(self):
        self._emit("ghost_event", x=1)  # SEEDED: no _replay_ghost_event
        self._emit("covered_event", y=2)

    def _replay_covered_event(self, data):
        pass

    def _replay_orphan_event(self, data):  # SEEDED: nothing emits it
        pass
