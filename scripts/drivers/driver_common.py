"""Shared machinery for the simulation drivers.

`simulate.py` (trace replay), `simulate_generated.py` (Poisson-generated
jobs) and `sweep_scenarios.py` (Monte Carlo scenario sweep) all build
the same scheduler, run the same simulation loop and persist the same
end-of-run metrics; this module is the single copy of that surface so
the vectorized sim core has one driver stack instead of drifting
copies.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.metrics import unfair_fraction  # noqa: E402
from shockwave_tpu.sched import Scheduler, SchedulerConfig  # noqa: E402
from shockwave_tpu.solver import get_policy  # noqa: E402


def chip_layout(cluster_spec: dict, chips_per_server: int = 1) -> dict:
    """worker_type -> chip ids, matching the registration order
    simulate() uses (sorted worker types, ids incrementing) — shared by
    the sweep and chaos drivers so their seeded fault events target the
    same chips the simulator actually registered."""
    layout = {}
    next_id = 0
    for wt in sorted(cluster_spec):
        layout[wt] = list(range(next_id, next_id + cluster_spec[wt]))
        next_id += cluster_spec[wt]
    return layout


def load_resumable_artifact(path: str, meta: dict,
                            restart: bool) -> Optional[dict]:
    """Resume contract shared by the sweep and chaos harnesses: an
    existing artifact at `path` is loaded for seed-keyed resume IFF its
    recorded meta matches this invocation's exactly; a mismatch refuses
    loudly (resuming different knobs into one artifact would silently
    blend two studies) unless `restart` discards it. Returns the loaded
    document, or None when starting fresh."""
    if not os.path.exists(path) or restart:
        return None
    with open(path) as f:
        existing = json.load(f)
    if existing.get("meta") != meta:
        raise SystemExit(
            f"{path} exists with different sweep parameters; pass "
            "--restart to discard it or change --out")
    return existing


def load_configs(config_path: Optional[str], policy: str,
                 cluster_spec: dict, round_duration: float):
    """(shockwave_config, serving_config, whatif_config, oracle_config)
    from a driver --config file.

    The serving tier, the what-if plane and the learned throughput
    oracle are policy-agnostic; their blocks ride the same config file
    but separate SchedulerConfig fields (the planner would reject the
    unknown keys). A shockwave run without a config file gets the
    planner defaults.
    """
    shockwave_config = None
    serving_config = None
    whatif_config = None
    oracle_config = None
    if config_path:
        with open(config_path) as f:
            shockwave_config = json.load(f)
        serving_config = shockwave_config.pop("serving", None)
        whatif_config = shockwave_config.pop("whatif", None)
        oracle_config = shockwave_config.pop("oracle", None)
    if shockwave_config is None and policy == "shockwave":
        shockwave_config = {}  # planner defaults
    if shockwave_config is not None:
        shockwave_config["num_gpus"] = sum(cluster_spec.values())
        shockwave_config["time_per_iteration"] = round_duration
    return shockwave_config, serving_config, whatif_config, oracle_config


def build_scheduler(policy_name: str, throughputs_file: str, profiles,
                    *, round_duration: float, seed: int = 0,
                    max_rounds: Optional[int] = None,
                    shockwave_config: Optional[dict] = None,
                    serving_config: Optional[dict] = None,
                    whatif_config: Optional[dict] = None,
                    oracle_config: Optional[dict] = None,
                    rate_override: Optional[dict] = None,
                    vectorized: bool = True) -> Scheduler:
    """One simulation-mode scheduler, configured the way every driver
    configures it."""
    policy = get_policy(policy_name, seed=seed)
    return Scheduler(
        policy, simulate=True, throughputs_file=throughputs_file,
        profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=round_duration, seed=seed,
            max_rounds=max_rounds, shockwave=shockwave_config,
            rate_override=rate_override, serving=serving_config,
            whatif=whatif_config, oracle=oracle_config,
            vectorized_sim=vectorized))


def collect_metrics(sched: Scheduler, makespan: float,
                    round_duration: float, policy_name: str) -> dict:
    """The common end-of-run metrics dict the drivers persist (each
    driver adds its own provenance keys on top). `policy_name` is the
    CLI-facing registry name (e.g. "max_min_fairness"), not the policy
    class's display name."""
    jct = sched.get_average_jct()
    ftf_static, ftf_themis = sched.get_finish_time_fairness()
    util, util_list = sched.get_cluster_utilization()
    ext_pct, ext, opp = sched.get_num_lease_extensions()
    envy_ratios, envy_pairwise = sched.get_envy_ratios()
    metrics = {
        "policy": policy_name,
        "makespan": makespan,
        "avg_jct": jct[0] if jct else None,
        "geometric_mean_jct": jct[1] if jct else None,
        "harmonic_mean_jct": jct[2] if jct else None,
        "jct_list": jct[3] if jct else [],
        "finish_time_fairness_list": ftf_static,
        "finish_time_fairness_themis_list": ftf_themis,
        "cluster_util": util,
        "utilization_list": util_list,
        "envy_ratios": envy_ratios,
        "envy_list": envy_pairwise,
        "extension_percentage": ext_pct,
        "num_lease_extensions": ext,
        "num_lease_extension_opportunities": opp,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "time_per_iteration": round_duration,
        "throughput_timeline": sched.get_throughput_timeline(),
        "milp_solve_stats": sched.get_solve_stats(),
    }
    serving = sched.serving_summary()
    if serving is not None:
        metrics["serving"] = serving
    if sched._whatif is not None:
        # The full decision evidence rides the metrics pickle; only
        # deterministic counts reach summary lines (status() carries
        # fork WALL telemetry, which must stay out of byte-reproducible
        # artifacts).
        metrics["whatif"] = {
            "decision_log": sched._whatif.decision_log,
            "knob_log": sched._whatif.knob_log,
            "forecast_log": sched._whatif.forecast_log,
            "shadow_log": sched._whatif.shadow_log,
        }
    return metrics


def summary_core(metrics: dict, sched: Scheduler) -> dict:
    """The one-JSON-line summary shared by the drivers."""
    summary = {
        "policy": metrics["policy"],
        "makespan": round(metrics["makespan"], 2),
        "avg_jct": (round(metrics["avg_jct"], 2)
                    if metrics["avg_jct"] else None),
        "unfair_fraction": round(
            unfair_fraction(metrics["finish_time_fairness_list"]), 4),
        "cluster_util": round(metrics["cluster_util"], 4),
        "lease_extension_pct": round(metrics["extension_percentage"], 2),
        "rounds": sched.rounds.num_completed_rounds,
    }
    serving = metrics.get("serving")
    if serving is not None:
        summary["serving_slo_attainment"] = serving["slo_attainment"]
        summary["serving_requests_offered"] = serving["requests_offered"]
        summary["serving_services"] = serving["services"]
    whatif = metrics.get("whatif")
    if whatif is not None:
        decisions = whatif["decision_log"]
        summary["whatif_decisions"] = len(decisions)
        summary["whatif_deferrals"] = sum(
            1 for d in decisions if d["decision"] == "defer")
    return summary


def milp_summary(solve_stats: list) -> dict:
    """Aggregate MILP solve telemetry for a summary line: solve count,
    per-path counts, greedy rate, worst achieved gap, and total solver
    wall (the canonical shockwave replay spends ~90% of its wall here —
    see EXPERIMENTS.md "Fleet-scale simulation")."""
    if not solve_stats:
        return {}
    paths = [s["path"] for s in solve_stats]
    gaps = [s["mip_gap"] for s in solve_stats if s["mip_gap"] is not None]
    out = {
        "milp_solves": len(paths),
        "milp_paths": {p: paths.count(p) for p in sorted(set(paths))},
        "milp_greedy_rate": round(paths.count("greedy") / len(paths), 4),
        "milp_wall_s": round(sum(s["wall_s"] for s in solve_stats), 2),
    }
    if gaps:
        out["milp_max_gap"] = round(max(gaps), 6)
    return out
