#!/usr/bin/env python3
"""Headline benchmark: replay the reference's canonical experiment,
plus measured single-chip TPU numbers.

Phase 1 runs the Shockwave policy on the canonical 120-job trace against
a 32-chip cluster (120 s rounds) — the reference's own headline result
(EXPERIMENTS.md:42, reproduce/tacc_32gpus.sh) — and reports makespan vs
the reference's shipped result pickle (BASELINE.md: 24197.42 s).
Phase 2 (scripts/profiling/bench_tpu.py, skipped when no TPU backend is
reachable) measures the flagship Transformer train step (steps/s, MFU)
and flash-vs-einsum attention latency on the real chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": value/baseline,
   ...tpu fields when measured...}
(vs_baseline < 1.0 means faster/better than the reference.)
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_MAKESPAN_S = 24197.42350629904  # reference shockwave pickle


def committed_tpu_result():
    """Newest committed raw TPU measurement (reproduce/tpu/bench_*.json,
    written by bench_tpu.py), provenance-marked with its capture time —
    so hardware numbers stay reportable when the chip is unreachable,
    the way the reference's committed oracle JSONs carry its measured
    GPU numbers."""
    import glob
    best = None
    for path in glob.glob(os.path.join(REPO, "reproduce/tpu/bench_*.json")):
        try:
            with open(path) as f:
                saved = json.load(f)
        except Exception:  # noqa: BLE001 - a bad artifact must not sink bench
            continue
        # Newest by capture time, not filename (filenames lead with the
        # device kind, which would sort v5 artifacts after newer v4 ones).
        stamp = saved.get("measured_at", "")
        if best is None or stamp > best[0]:
            best = (stamp, path, saved)
    if best is None:
        return {}
    _, path, saved = best
    saved["tpu_as_of"] = saved.pop("measured_at", "unknown")
    saved["tpu_source"] = os.path.relpath(path, REPO)
    return saved


def tpu_phase():
    """Run the single-chip TPU bench in a subprocess; on failure fall
    back to the newest committed measurement (provenance-marked)."""
    # Subprocess-isolated liveness probe with bounded backoff retry
    # (reproduce/tpu/liveness_probe.py — shared with
    # capture_tpu_evidence.sh): a wedged accelerator tunnel blocks
    # backend init forever, and transient relay hiccups often clear
    # within a minute.
    sys.path.insert(0, os.path.join(REPO, "reproduce", "tpu"))
    from liveness_probe import probe_backend
    err = probe_backend(cwd=REPO)
    if err is not None:
        committed = committed_tpu_result()
        if committed:
            # An unreachable chip must not poison the bench row: degrade
            # to the last-good committed evidence, provenance-marked
            # with why this run could not refresh it (tpu_probe, not
            # tpu_error — the numbers themselves are good).
            return {"tpu_probe": f"skipped: {err}", **committed}
        return {"tpu_error": err}
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts/profiling/bench_tpu.py")],
            capture_output=True, text=True, timeout=1200, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"tpu_error": "bench_tpu timeout", **committed_tpu_result()}
    if out.returncode == 75:
        return {}  # no TPU backend — sim-only result
    if out.returncode != 0:
        return {"tpu_error": out.stderr[-300:], **committed_tpu_result()}
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001
        return {"tpu_error": out.stdout[-300:], **committed_tpu_result()}


def sweep_phase():
    """Monte Carlo sweep throughput: 8 seeded subsampled scenarios of
    the canonical trace through the process-pool harness
    (scripts/drivers/sweep_scenarios.py) — the fleet-scale-study metric
    the vectorized sim core exists for."""
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "scripts/drivers/sweep_scenarios.py"),
                 "--trace", os.path.join(REPO,
                                         "data/canonical_120job.trace"),
                 "--policy", "max_min_fairness",
                 "--throughputs",
                 os.path.join(REPO, "data/tacc_throughputs.json"),
                 "--cluster_spec", "v100:32", "--round_duration", "120",
                 "--num_scenarios", "8", "--subsample", "0.2:0.5",
                 "--load_scale", "0.8:1.3", "--arrival_jitter_s", "600",
                 "--fault_rate", "1",
                 "--out", os.path.join(td, "sweep.json")],
                capture_output=True, text=True, timeout=600)
        except subprocess.TimeoutExpired:
            return {"sweep_error": "sweep timeout"}
        if out.returncode != 0:
            return {"sweep_error": out.stderr[-300:]}
        sweep = json.loads(out.stdout.strip().splitlines()[-1])
        return {"sweep_scenarios": sweep["scenarios"],
                "sweep_completed": sweep["completed"],
                "sweep_scenarios_per_min": sweep["scenarios_per_min"]}


def whatif_phase():
    """What-if control-plane overhead: forks/min + rollouts/min on a
    mid-run canonical scheduler (scripts/microbenchmarks/
    bench_whatif.py) — the trajectory row that keeps the digital-twin
    plane's cost visible beside sim_core_wall_s / milp_wall_s."""
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts/microbenchmarks/bench_whatif.py"),
             "--forks", "20", "--rollouts", "10"],
            capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        return {"whatif_error": "bench_whatif timeout"}
    if out.returncode != 0:
        return {"whatif_error": out.stderr[-300:]}
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001
        return {"whatif_error": out.stdout[-300:]}
    return {"whatif_forks_per_min": row["forks_per_min"],
            "whatif_rollouts_per_min": row["rollouts_per_min"],
            "whatif_mean_capture_s": row["mean_capture_s"]}


def tracing_phase():
    """Fleet-tracing overhead: spans/s + estimated per-round cost of
    context propagation and shard flushing (scripts/microbenchmarks/
    bench_tracing.py) — keeps the distributed-tracing tax visible
    beside the what-if and sweep rows."""
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts/microbenchmarks/bench_tracing.py"),
             "--spans", "100000", "--propagations", "50000",
             "--flushes", "10"],
            capture_output=True, text=True, timeout=300)
    except subprocess.TimeoutExpired:
        return {"tracing_error": "bench_tracing timeout"}
    if out.returncode != 0:
        return {"tracing_error": out.stderr[-300:]}
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001
        return {"tracing_error": out.stdout[-300:]}
    return {"tracing_spans_per_s": row["spans_per_s"],
            "tracing_round_overhead_est_s": row["round_overhead_est_s"],
            "tracing_shard_flush_mean_s": row["shard_flush_mean_s"]}


def serving_phase():
    """Serving decode throughput: the ROADMAP-named tokens/s-per-chip
    row (scripts/microbenchmarks/bench_serving_decode.py) — the
    measured number the serving tier's declared decode rate (and so
    its analytic mu) is calibrated against; the measured-vs-analytic
    p99 envelope lives in reproduce/serving/measured_calibration.json."""
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO,
                          "scripts/microbenchmarks/bench_serving_decode.py")],
            capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"serving_decode_error": "bench_serving_decode timeout"}
    if out.returncode != 0:
        return {"serving_decode_error": out.stderr[-300:]}
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001
        return {"serving_decode_error": out.stdout[-300:]}
    return {"serving_tokens_per_s_per_chip": row["tokens_per_s_per_chip"],
            "serving_requests_per_s": row["requests_per_s"],
            "serving_decode_backend": row["backend"]}


def oracle_phase():
    """Learned throughput oracle overhead: fit wall + predictions/s +
    online updates/s (scripts/microbenchmarks/bench_oracle.py) — keeps
    the cold-start estimator's cost visible beside the what-if and
    tracing rows; the scheduler charges one predict per never-profiled
    (job, worker type) and one observe per Done report."""
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts/microbenchmarks/bench_oracle.py")],
            capture_output=True, text=True, timeout=300)
    except subprocess.TimeoutExpired:
        return {"oracle_error": "bench_oracle timeout"}
    if out.returncode != 0:
        return {"oracle_error": out.stderr[-300:]}
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001
        return {"oracle_error": out.stdout[-300:]}
    return {"oracle_mean_fit_s": row["mean_fit_s"],
            "oracle_predictions_per_s": row["predictions_per_s"],
            "oracle_observations_per_s": row["observations_per_s"]}


def main():
    sim_start = time.monotonic()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/drivers/simulate.py"),
         "--trace", os.path.join(REPO, "data/canonical_120job.trace"),
         "--policy", "shockwave",
         "--throughputs", os.path.join(REPO, "data/tacc_throughputs.json"),
         "--cluster_spec", "v100:32", "--round_duration", "120",
         "--config", os.path.join(REPO, "configs/tacc_32gpus.json")],
        capture_output=True, text=True, timeout=3600)
    if out.returncode != 0:
        print(json.dumps({"metric": "canonical_shockwave_makespan",
                          "value": None, "unit": "s", "vs_baseline": None,
                          "error": out.stderr[-500:]}))
        sys.exit(1)
    result = json.loads(out.stdout.strip().splitlines()[-1])
    makespan = result["makespan"]
    line = {
        "metric": "canonical_shockwave_makespan",
        "value": round(makespan, 2),
        "unit": "s",
        "vs_baseline": round(makespan / BASELINE_MAKESPAN_S, 4),
        "avg_jct": result["avg_jct"],
        "unfair_fraction": result["unfair_fraction"],
        # Scheduler-core speed: wall time to replay the whole canonical
        # trace, MILP solves included (reference: ~600 s, README.md:48).
        "sim_wall_s": round(time.monotonic() - sim_start, 1),
        # Wall split from the driver (virtual imports excluded): the
        # canonical shockwave replay is ~90% HiGHS MILP B&B — the
        # vectorized sim core's effect shows in sim_core_wall_s and in
        # the sweep throughput row, not in the solver-bound total
        # (EXPERIMENTS.md "Fleet-scale simulation").
        "sim_core_wall_s": result.get("sim_core_wall_s"),
        "milp_wall_s": result.get("milp_wall_s"),
    }
    line.update(sweep_phase())
    line.update(whatif_phase())
    line.update(tracing_phase())
    line.update(serving_phase())
    line.update(oracle_phase())
    line.update(tpu_phase())
    print(json.dumps(line))


if __name__ == "__main__":
    main()
