"""Job identity and description.

`JobIdPair` is the hashable key used throughout the scheduler: either a
single job id or an (unordered) pair of co-located jobs. Behavioral parity
with reference scheduler/job_id_pair.py; the pairing-function hash and
ordering semantics are preserved because policy code sorts on these keys.

`Job` carries everything the scheduler needs to dispatch and account for a
training job (reference: scheduler/job.py). Commands are stored as shell
strings whose final batch-size token can be rewritten on dynamic adaptation.
"""
from __future__ import annotations

import re
from typing import Optional, Tuple


class JobIdPair:
    """A single job id, or an unordered pair of co-located job ids."""

    __slots__ = ("_lo", "_hi", "_hash", "_singles")

    #: `_singles` is an idempotent lazy memo over immutable inputs
    #: (_lo/_hi never change): two threads racing the first
    #: `singletons()` call compute the same tuple and the losing write
    #: is identical — benign by construction (race-detector verdict).
    _EXTERNALLY_SYNCHRONIZED = frozenset({"_singles"})

    def __init__(self, a: Optional[int], b: Optional[int] = None):
        if a is None:
            raise ValueError("first id of a JobIdPair must not be None")
        if b is None:
            self._lo, self._hi = a, None
            self._hash = a
        else:
            self._lo, self._hi = (a, b) if a <= b else (b, a)
            # Pairing function matching the reference's hash; collisions with
            # small single ids exist but __eq__ disambiguates.
            self._hash = self._lo + self._hi * self._hi
        self._singles = None

    def __getitem__(self, i: int) -> Optional[int]:
        if i == 0:
            return self._lo
        if i == 1:
            return self._hi
        raise IndexError(i)

    def is_pair(self) -> bool:
        return self._hi is not None

    def singletons(self) -> Tuple["JobIdPair", ...]:
        if self._singles is None:
            if self._hi is None:
                self._singles = (self,)
            else:
                self._singles = (JobIdPair(self._lo), JobIdPair(self._hi))
        return self._singles

    def as_tuple(self):
        return (self._lo, self._hi)

    def as_set(self):
        return {self._lo, self._hi}

    def overlaps_with(self, other: "JobIdPair") -> bool:
        if self.is_pair():
            raise ValueError("overlaps_with is only valid on single ids")
        return self._lo == other._lo or self._lo == other._hi

    def integer_job_id(self) -> int:
        assert self._hi is None, "not a single job id"
        return self._lo

    def __eq__(self, other) -> bool:
        if isinstance(other, int):
            return self._lo == other and self._hi is None
        return self._lo == other._lo and self._hi == other._hi

    def __lt__(self, other: "JobIdPair") -> bool:
        # Singles sort before pairs; otherwise lexicographic.
        if other._hi is not None:
            if self._hi is None:
                return True
            if self._lo == other._lo:
                return self._hi < other._hi
        elif self._hi is not None:
            return False
        return self._lo < other._lo

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        if self._hi is None:
            return str(self._lo)
        return f"({self._lo}, {self._hi})"


class Job:
    """Description of one training job.

    job_type has the canonical form "<Model> (batch size <N>)"; the model
    and batch size are recoverable from it, and `with_batch_size` rewrites
    both the type string and the trailing batch-size token of the command.
    """

    def __init__(
        self,
        job_id: Optional[JobIdPair],
        job_type: str,
        command: str,
        working_directory: str = "",
        num_steps_arg: str = "--num_steps",
        total_steps: int = 0,
        duration: float = 0,
        scale_factor: int = 1,
        mode: str = "static",
        priority_weight: float = 1.0,
        SLO: Optional[float] = None,
        needs_data_dir: bool = False,
        mps_thread_percentage: int = 100,
    ):
        self.job_id = job_id
        self.job_type = job_type
        self.command = command
        self.working_directory = working_directory
        self.num_steps_arg = num_steps_arg
        self.total_steps = int(total_steps)
        self._duration = duration
        self.scale_factor = int(scale_factor)
        self.mode = mode
        self.priority_weight = priority_weight
        self.SLO = None if (SLO is not None and SLO < 0) else SLO
        self.needs_data_dir = needs_data_dir
        self.mps_thread_percentage = mps_thread_percentage

    @property
    def duration(self) -> int:
        return int(float(self._duration))

    @duration.setter
    def duration(self, v):
        self._duration = v

    @property
    def model(self) -> str:
        return self.job_type.split(" ", 1)[0]

    @property
    def batch_size(self) -> int:
        m = re.search(r"batch size (\d+)\)", self.job_type)
        if m is None:
            from .constants import DEFAULT_BS
            if self.model in DEFAULT_BS:
                return DEFAULT_BS[self.model]
            raise ValueError(f"job_type has no batch size: {self.job_type!r}")
        return int(m.group(1))

    def update_bs(self, new_bs: int) -> None:
        """Rewrite batch size in the job type and launch command.

        The batch size is the last numeric token of the command for most
        workloads; translation/imagenet commands carry a trailing data path,
        so there it is the second-to-last token (reference: job.py:142-166).
        """
        tokens = self.command.split(" ")
        idx = -1 if ("translation" not in self.command and "imagenet" not in self.command) else -2
        tokens[idx] = str(new_bs)
        self.command = " ".join(tokens)
        self.job_type = re.sub(r"batch size \d+\)", f"batch size {new_bs})", self.job_type)

    def __repr__(self) -> str:
        return f"Job({self.job_id}, {self.job_type!r}, sf={self.scale_factor}, mode={self.mode})"
