"""CLI driver: ``python -m shockwave_tpu.analysis [--root R] [--select a,b]``.

Runs every pass (or the ``--select``ed subset) over the repo tree and
prints findings as ``path:line: [pass-id] message``. Exit status: 0 on
a clean tree, 1 when any finding survives, 2 on usage errors.

After the selected passes, the ``suppression-audit`` pass runs over
the same index: an inline ``swtpu-check: ignore[<pass-id>]`` that the
named pass never matched (nothing would fire on that line) is itself a
finding, so stale exceptions cannot rot in place.

``--json`` emits a machine-readable report: the findings list plus a
per-pass ``{id, findings, wall_s}`` timing table (the findings content
is deterministic — byte-identical across runs; wall times are
telemetry). ``--sarif`` emits the findings as a SARIF 2.1.0 log for
code-scanning UIs (CI uploads it from the analysis-smoke job).
``--list`` runs each pass once to report its wall beside its
description. The parsed-AST index (and the concurrency passes' shared
call graph) is cached process-wide with mtime validation, so repeated
runs parse each file once.

Two lockflow-specific modes skip the passes entirely:
``--lock-graph`` prints the static lock-order graph as JSON, and
``--assert-contains RUNTIME.json`` checks that a runtime graph dumped
by the sanitizer (``SWTPU_SANITIZE_GRAPH_OUT``) is a subgraph of the
static one — the runtime ⊆ static containment gate. Exit 1 names any
runtime edge the static analysis missed.

The tier-1 gate (tests/test_analysis.py) runs exactly this entry
point, so CI and a local ``scripts/utils/check.py`` see the same
verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from .core import Finding, cached_index
from .passes import SUPPRESSION_AUDIT_ID, ALL_PASSES, check_suppression_audit

#: Repo-relative directories scanned by default.
DEFAULT_INCLUDE_DIRS = ("shockwave_tpu", "scripts")
#: Generated code is not ours to lint.
DEFAULT_EXCLUDE_GLOBS = ("shockwave_tpu/runtime/proto/*",)


def default_root() -> str:
    """The repo root: the directory holding the shockwave_tpu package."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def run_timed(root: Optional[str] = None,
              select: Optional[List[str]] = None
              ) -> Tuple[List[Finding], Dict[str, dict]]:
    """Run the selected passes (plus the suppression audit) with
    repo-default scopes. Returns (findings sorted by location,
    per-pass {id: {findings, wall_s}} timing table)."""
    index = cached_index(root or default_root(),
                         include_dirs=DEFAULT_INCLUDE_DIRS,
                         exclude_globs=DEFAULT_EXCLUDE_GLOBS)
    index.reset_suppression_hits()
    findings: List[Finding] = []
    timing: Dict[str, dict] = {}
    selected = [p for p in (select or sorted(ALL_PASSES))
                if p != SUPPRESSION_AUDIT_ID]
    for name in selected:
        t0 = time.perf_counter()
        got = ALL_PASSES[name](index)
        timing[name] = {"findings": len(got),
                        "wall_s": round(time.perf_counter() - t0, 4)}
        findings.extend(got)
    # The audit must see every selected pass's suppression hits, so it
    # always runs last.
    t0 = time.perf_counter()
    got = check_suppression_audit(index, ran_pass_ids=selected)
    timing[SUPPRESSION_AUDIT_ID] = {
        "findings": len(got),
        "wall_s": round(time.perf_counter() - t0, 4)}
    findings.extend(got)
    return (sorted(findings, key=lambda f: (f.path, f.line, f.pass_id)),
            timing)


def run(root: Optional[str] = None,
        select: Optional[List[str]] = None) -> List[Finding]:
    """Back-compat entry point (tests, check.py): findings only."""
    return run_timed(root=root, select=select)[0]


def sarif_report(findings: List[Finding]) -> dict:
    """The findings as a SARIF 2.1.0 log (code-scanning upload shape).

    One rule per pass id (description = the pass docstring's first
    line); every finding is an ``error``-level result. Deterministic:
    rules sorted by id, results already location-sorted by the caller.
    """
    rules = []
    for name, fn in sorted(ALL_PASSES.items()):
        first_line = (fn.__doc__ or name).strip().splitlines()[0]
        rules.append({
            "id": name,
            "shortDescription": {"text": first_line},
        })
    rules.append({
        "id": SUPPRESSION_AUDIT_ID,
        "shortDescription": {
            "text": check_suppression_audit.__doc__
            .strip().splitlines()[0]},
    })
    results = [{
        "ruleId": f.pass_id,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line},
            },
        }],
    } for f in findings]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "swtpu-check",
                "informationUri":
                    "https://github.com/shockwave-tpu/shockwave-tpu",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def assert_contains(root: Optional[str], runtime_path: str) -> int:
    """The containment gate: every lock-order edge observed at runtime
    must appear in the static lock-order graph. Returns an exit code;
    prints the verdict (and any missing edges) to stdout/stderr."""
    from .lockflow import static_lock_order_graph
    try:
        with open(runtime_path, "r", encoding="utf-8") as f:
            runtime = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"cannot read runtime graph {runtime_path!r}: {exc}",
              file=sys.stderr)
        return 2
    index = cached_index(root or default_root(),
                         include_dirs=DEFAULT_INCLUDE_DIRS,
                         exclude_globs=DEFAULT_EXCLUDE_GLOBS)
    static = static_lock_order_graph(index)
    runtime_edges = set(runtime.get("edges", []))
    missing = sorted(runtime_edges - set(static["edges"]))
    if missing:
        print("runtime lock-order edges NOT in the static graph "
              "(the analyzer is blind to a real acquisition order):",
              file=sys.stderr)
        for edge in missing:
            print(f"  {edge}", file=sys.stderr)
        return 1
    print(f"containment OK: {len(runtime_edges)} runtime edge(s) "
          f"⊆ {len(static['edges'])} static edge(s)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis",
        description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: autodetect "
                             "from the installed package location)")
    parser.add_argument("--select", default=None,
                        help="comma-separated pass ids "
                             f"(default: all of {', '.join(sorted(ALL_PASSES))}"
                             "; the suppression audit always rides along)")
    parser.add_argument("--list", action="store_true",
                        help="list pass ids with their wall time on this "
                             "tree, and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report (findings + per-pass "
                             "wall) instead of text")
    parser.add_argument("--sarif", action="store_true",
                        help="emit the findings as a SARIF 2.1.0 log "
                             "(for code-scanning upload)")
    parser.add_argument("--lock-graph", action="store_true",
                        help="print the static lock-order graph as "
                             "JSON and exit (no passes run)")
    parser.add_argument("--assert-contains", metavar="RUNTIME_JSON",
                        default=None,
                        help="check that the runtime order graph "
                             "dumped by SWTPU_SANITIZE_GRAPH_OUT is a "
                             "subgraph of the static one; exit 1 on "
                             "any uncovered runtime edge")
    args = parser.parse_args(argv)

    if args.lock_graph:
        from .lockflow import static_lock_order_graph
        index = cached_index(args.root or default_root(),
                             include_dirs=DEFAULT_INCLUDE_DIRS,
                             exclude_globs=DEFAULT_EXCLUDE_GLOBS)
        print(json.dumps(static_lock_order_graph(index),
                         indent=1, sort_keys=True))
        return 0

    if args.assert_contains:
        return assert_contains(args.root, args.assert_contains)

    if args.list:
        _, timing = run_timed(root=args.root)
        for name, fn in sorted(ALL_PASSES.items()):
            first_line = (fn.__doc__ or "").strip().splitlines()[0]
            t = timing.get(name, {})
            print(f"{name}: {first_line} "
                  f"[wall {t.get('wall_s', 0.0):.3f}s, "
                  f"{t.get('findings', 0)} finding(s)]")
        t = timing.get(SUPPRESSION_AUDIT_ID, {})
        print(f"{SUPPRESSION_AUDIT_ID}: "
              f"{check_suppression_audit.__doc__.strip().splitlines()[0]} "
              f"[wall {t.get('wall_s', 0.0):.3f}s, "
              f"{t.get('findings', 0)} finding(s)]")
        total = sum(v.get("wall_s", 0.0) for v in timing.values())
        print(f"total analyzer wall: {total:.3f}s")
        return 0

    select = None
    if args.select:
        select = [p.strip() for p in args.select.split(",") if p.strip()]
        # The audit is not in ALL_PASSES (it must run after the others
        # and always rides along), but selecting it is legal: alone, it
        # still flags unknown-id suppressions.
        unknown = [p for p in select
                   if p not in ALL_PASSES and p != SUPPRESSION_AUDIT_ID]
        if unknown:
            print(f"unknown pass id(s): {', '.join(unknown)} "
                  f"(try --list)", file=sys.stderr)
            return 2

    findings, timing = run_timed(root=args.root, select=select)
    if args.sarif:
        print(json.dumps(sarif_report(findings), indent=1,
                         sort_keys=True))
    elif args.json:
        report = {
            "findings": [{"file": f.path, "line": f.line,
                          "pass": f.pass_id, "message": f.message}
                         for f in findings],
            "count": len(findings),
            "passes": [{"id": name, **timing[name]}
                       for name in sorted(timing)],
        }
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f)
        print(f"swtpu-check: {len(findings)} finding(s)"
              + ("" if findings else " — tree is clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
