"""Mergeable fixed-log-bucket quantile sketch for measured latencies.

The serving tier needs per-replica request-latency quantiles that can be
shipped as compact deltas on the existing Done heartbeats and folded
per-service on the scheduler — across any number of replicas, arriving
in any order, possibly duplicating a round boundary. A fixed bucket
layout makes that algebra exact:

- every process maps a latency to the same bucket index
  (``floor(log(v / MIN_VALUE) / log(GAMMA))``, clamped), so a sketch is
  just ``{bucket_index: count}``;
- **merge is integer addition per bucket** — associative, commutative,
  and lossless, so the merged quantile is independent of shard arrival
  order (asserted byte-for-byte by the tests and the calibration CI
  gate);
- quantiles are read as the upper edge of the bucket holding the
  ``ceil(q * n)``-th sample — deterministic, with bounded relative
  error ``GAMMA - 1`` (~5%) over [MIN_VALUE, MAX_VALUE].

The sketch is pure data + arithmetic: no clocks (values are measured by
the caller against its own timebase), no RNG, no floats in the
serialized form except the two counters — ``encode()`` emits canonical
JSON (sorted buckets, integer counts) so two equal sketches are
byte-equal, which is what lets CI ``cmp`` calibration artifacts.
"""
from __future__ import annotations

import json
import math
from typing import Dict, Iterable, List, Optional, Tuple

#: Bucket geometry: shared by every producer and consumer (a layout
#: change is a wire-format change; bump VERSION with it).
MIN_VALUE = 1e-4          # 0.1 ms: below this, latency is bucket 0
MAX_VALUE = 1e4           # beyond ~2.7 h everything lands in the top bucket
GAMMA = 1.05              # per-bucket growth => <=5% relative error
VERSION = 1

_LOG_GAMMA = math.log(GAMMA)
#: Highest regular bucket index (values above MAX_VALUE clamp here).
MAX_BUCKET = int(math.ceil(math.log(MAX_VALUE / MIN_VALUE) / _LOG_GAMMA))


def bucket_index(value: float) -> int:
    """The fixed bucket of `value` (clamped to [0, MAX_BUCKET])."""
    if value <= MIN_VALUE:
        return 0
    idx = int(math.floor(math.log(value / MIN_VALUE) / _LOG_GAMMA))
    return min(max(idx, 0), MAX_BUCKET)


def bucket_upper(index: int) -> float:
    """Upper edge of bucket `index` — the value a quantile read
    reports (an over-estimate by at most GAMMA-1 relative)."""
    return MIN_VALUE * GAMMA ** (index + 1)


class QuantileSketch:
    """One mergeable latency distribution: {bucket: count} + sum."""

    __slots__ = ("buckets", "count", "total")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0    # sum of raw values (mean readback)

    def add(self, value: float) -> None:
        idx = bucket_index(value)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.count += 1
        self.total += float(value)

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold `other` into this sketch (exact: integer bucket adds)."""
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        return self

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (upper bucket edge), or None when empty."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {q}")
        if self.count == 0:
            return None
        rank = max(int(math.ceil(q * self.count)), 1)
        seen = 0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen >= rank:
                return bucket_upper(idx)
        return bucket_upper(MAX_BUCKET)   # unreachable; defensive

    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    # -- wire format ----------------------------------------------------

    def to_payload(self) -> dict:
        """Plain-data form: sorted [index, count] pairs (JSON keys must
        be strings, and sorted pairs keep encodings canonical)."""
        return {
            "v": VERSION,
            "b": [[idx, self.buckets[idx]] for idx in sorted(self.buckets)],
            "n": self.count,
            "s": round(self.total, 9),
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "QuantileSketch":
        if payload.get("v") != VERSION:
            raise ValueError(
                f"quantile sketch version {payload.get('v')!r} != {VERSION}")
        sketch = cls()
        for idx, n in payload.get("b", []):
            if n < 0:
                raise ValueError("negative bucket count")
            sketch.buckets[int(idx)] = sketch.buckets.get(int(idx), 0) + int(n)
        sketch.count = int(payload.get("n", 0))
        sketch.total = float(payload.get("s", 0.0))
        if sketch.count != sum(sketch.buckets.values()):
            raise ValueError("bucket counts disagree with sample count")
        return sketch

    def encode(self) -> str:
        """Canonical (byte-deterministic) JSON encoding."""
        return json.dumps(self.to_payload(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def decode(cls, text: str) -> "QuantileSketch":
        return cls.from_payload(json.loads(text))

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, QuantileSketch)
                and self.buckets == other.buckets
                and self.count == other.count
                and round(self.total, 9) == round(other.total, 9))

    def __repr__(self) -> str:
        return (f"QuantileSketch(n={self.count}, "
                f"p50={self.quantile(0.5)}, p99={self.quantile(0.99)})")


def merge_all(sketches: Iterable[QuantileSketch]) -> QuantileSketch:
    """Fold any number of sketches into a fresh one (order-free)."""
    out = QuantileSketch()
    for sketch in sketches:
        out.merge(sketch)
    return out


def quantiles(sketch: QuantileSketch,
              qs: Tuple[float, ...] = (0.5, 0.99)) -> List[Optional[float]]:
    return [sketch.quantile(q) for q in qs]


__all__ = ["QuantileSketch", "merge_all", "quantiles", "bucket_index",
           "bucket_upper", "MIN_VALUE", "MAX_VALUE", "GAMMA", "MAX_BUCKET",
           "VERSION"]
