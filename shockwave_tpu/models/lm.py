"""LSTM language model (Wikitext-2-class workloads).

Capability parity with the reference's word-level LSTM LM
(workloads/pytorch/language_modeling/main.py). The recurrence is an
`nn.scan` over the sequence — compiler-friendly static control flow — and
the embedding/projection matmuls carry the FLOPs onto the MXU.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class StackedLSTMCell(nn.Module):
    hidden_size: int
    num_layers: int

    @nn.compact
    def __call__(self, carry, x):
        new_carry = []
        inp = x
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden_size, name=f"lstm_{i}")
            new_c, inp = cell(carry[i], inp)
            new_carry.append(new_c)
        return new_carry, inp


class LSTMLanguageModel(nn.Module):
    vocab_size: int = 33278  # wikitext-2 vocab
    embed_dim: int = 256
    hidden_size: int = 256
    num_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        """tokens: (batch, seq_len) int32 -> logits (batch, seq_len, vocab)."""
        emb = nn.Embed(self.vocab_size, self.embed_dim, name="embedding")(tokens)
        batch = tokens.shape[0]
        cell = StackedLSTMCell(self.hidden_size, self.num_layers)
        scan = nn.scan(
            lambda mdl, carry, x: mdl(carry, x),
            variable_broadcast="params", split_rngs={"params": False},
            in_axes=1, out_axes=1)
        carry = [
            nn.OptimizedLSTMCell(self.hidden_size).initialize_carry(
                jax.random.PRNGKey(0), (batch, self.embed_dim))
            for _ in range(self.num_layers)
        ]
        _, hidden = scan(cell, carry, emb)
        return nn.Dense(self.vocab_size, name="proj")(hidden)
