"""Per-process span shards: how worker daemons and trainers get their
spans into the fleet trace.

The scheduler's tracer lives in one long-lived process; the rest of a
round's story happens in worker daemons and short-lived trainer
subprocesses. Each of those keeps a bounded in-memory ring of spans
(the same `Tracer`) and periodically rewrites ONE shard file —
``spans-<role>-<pid>.json`` in the drive's trace directory — via
`core/durable_io.write_text_atomic`, so a reader never sees a torn
shard and a crashed process leaves its last complete flush behind.
``python -m shockwave_tpu.obs.merge`` fuses every shard in a directory
into a single Perfetto/Chrome trace, aligning per-host clocks from the
RPC send/recv timestamp pairs the spans carry.

The clock is injected (obs/clock.py) and every timestamp a shard span
carries is stamped HERE — runtime modules call `open_span`/`close_span`
and never read a wall clock for span purposes (enforced by the
obs-discipline pass, whose clock rule covers the span-emitting runtime
module `runtime/spans.py`).
"""
from __future__ import annotations

import json
import os
import socket
from typing import Optional

from . import names
from .clock import Clock, wall_clock
from .propagation import SpanContext
from .tracing import Tracer

#: Shard rings are small: a worker daemon emits a handful of spans per
#: dispatch, a trainer a handful per lifetime.
DEFAULT_MAX_SPANS = 20_000

SHARD_SCHEMA = 1


class OpenSpan:
    """Handle for a span whose lifetime does not nest lexically (a
    trainer's whole lease window, a dispatcher's process launch)."""

    __slots__ = ("name", "t0", "context", "parent", "args")

    def __init__(self, name: str, t0: float, context: SpanContext,
                 parent: Optional[SpanContext], args: dict):
        self.name = name
        self.t0 = t0
        self.context = context
        self.parent = parent
        self.args = args


class ShardSpanWriter:
    """A Tracer plus the atomic shard-file flush, for one process."""

    def __init__(self, directory: str, role: str,
                 clock: Optional[Clock] = None,
                 max_spans: int = DEFAULT_MAX_SPANS, obs=None,
                 host: Optional[str] = None, pid: Optional[int] = None):
        self.directory = directory
        self.role = role
        self._clock: Clock = clock or wall_clock
        self.tracer = Tracer(clock=self._clock, max_events=max_spans)
        self._obs = obs
        self._pid = os.getpid() if pid is None else int(pid)
        self._host = host if host is not None else socket.gethostname()
        self.path = os.path.join(directory,
                                 names.shard_filename(role, self._pid))
        os.makedirs(directory, exist_ok=True)

    # -- span recording -------------------------------------------------

    def span(self, name: str, parent: Optional[SpanContext] = None,
             **args):
        """Context-manager span (delegates to the tracer)."""
        return self.tracer.span(name, parent=parent, **args)

    def open_span(self, name: str, parent: Optional[SpanContext] = None,
                  **args) -> OpenSpan:
        """Begin a non-lexical span; stamp its start with the injected
        clock. Close with `close_span` (or it is lost, by design — a
        crash mid-span has no honest duration)."""
        from .propagation import child_context, new_root_context
        ctx = child_context(parent) if parent else new_root_context()
        return OpenSpan(name, self._clock(), ctx, parent, dict(args))

    def close_span(self, span: OpenSpan, **more_args) -> None:
        args = dict(span.args)
        args.update(more_args)
        self.tracer.record_span(
            span.name, ts=span.t0, dur=self._clock() - span.t0,
            context=span.context, parent=span.parent, **args)

    # -- flush ----------------------------------------------------------

    def flush(self) -> Optional[str]:
        """Atomically rewrite the shard file from the current ring.
        Returns the path (None when there is nothing to write). Cheap
        enough to call per dispatch: shards are bounded and the write
        is one buffered JSON dump + rename."""
        events = self.tracer.events()
        if not events:
            return None
        payload = shard_payload(self.role, self._pid, self._host,
                                events)
        from ..core.durable_io import write_text_atomic
        write_text_atomic(self.path, json.dumps(payload))
        if self._obs is not None:
            from . import names as obs_names
            self._obs.inc(obs_names.TRACE_SHARD_FLUSHES_TOTAL)
            self._obs.set_gauge(obs_names.TRACE_SHARD_SPANS, len(events))
        return self.path


def shard_payload(role: str, pid: int, host: str,
                  events: list) -> dict:
    """The ONE serialization of tracer events into a shard file's JSON
    shape — shared by ShardSpanWriter.flush and export_tracer_shard so
    the scheduler shard can never fork shape from worker/trainer
    shards. `tid` rides along: per-thread tracks must survive into the
    merge (concurrent dispatch threads on one daemon)."""
    return {
        "schema": SHARD_SCHEMA,
        "role": role,
        "pid": int(pid),
        "host": host,
        "spans": [
            {"name": e["name"], "ts": e["ts"], "dur": e["dur"],
             "tid": e.get("tid", 0),
             "trace_id": e.get("trace_id"),
             "span_id": e.get("span_id"),
             "parent_id": e.get("parent_id"),
             "args": e.get("args") or {}}
            for e in events],
    }


def export_tracer_shard(directory: str, role: str, tracer,
                        obs=None, host: Optional[str] = None,
                        pid: Optional[int] = None) -> Optional[str]:
    """Dump an EXISTING tracer's ring as a shard file (the scheduler's
    collection path: its spans already live in the scheduler tracer).
    Returns the shard path (None when the ring is empty)."""
    events = tracer.events()
    if not events:
        return None
    the_pid = os.getpid() if pid is None else int(pid)
    payload = shard_payload(
        role, the_pid,
        host if host is not None else socket.gethostname(), events)
    path = os.path.join(directory, names.shard_filename(role, the_pid))
    os.makedirs(directory, exist_ok=True)
    from ..core.durable_io import write_text_atomic
    write_text_atomic(path, json.dumps(payload))
    if obs is not None:
        obs.inc(names.TRACE_SHARD_FLUSHES_TOTAL)
        obs.set_gauge(names.TRACE_SHARD_SPANS, len(events))
    return path


def load_shard(path: str) -> Optional[dict]:
    """Read one shard file; None when unreadable/foreign (a torn or
    alien file must not sink the merge)."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or "spans" not in payload:
        return None
    return payload


def discover_shards(directory: str):
    """Shard paths in `directory`, sorted by filename (deterministic
    merge order)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        os.path.join(directory, name) for name in entries
        if name.startswith(names.SHARD_FILE_PREFIX)
        and name.endswith(names.SHARD_FILE_SUFFIX))
