"""CLI driver: ``python -m shockwave_tpu.analysis [--root R] [--select a,b]``.

Runs every pass (or the ``--select``ed subset) over the repo tree and
prints findings as ``path:line: [pass-id] message``. Exit status: 0 on
a clean tree, 1 when any finding survives, 2 on usage errors.

After the selected passes, the ``suppression-audit`` pass runs over
the same index: an inline ``swtpu-check: ignore[<pass-id>]`` that the
named pass never matched (nothing would fire on that line) is itself a
finding, so stale exceptions cannot rot in place.

``--json`` emits a machine-readable report: the findings list plus a
per-pass ``{id, findings, wall_s}`` timing table (the findings content
is deterministic — byte-identical across runs; wall times are
telemetry). ``--list`` runs each pass once to report its wall beside
its description. The parsed-AST index (and the concurrency passes'
shared call graph) is cached process-wide with mtime validation, so
repeated runs parse each file once.

The tier-1 gate (tests/test_analysis.py) runs exactly this entry
point, so CI and a local ``scripts/utils/check.py`` see the same
verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from .core import Finding, cached_index
from .passes import SUPPRESSION_AUDIT_ID, ALL_PASSES, check_suppression_audit

#: Repo-relative directories scanned by default.
DEFAULT_INCLUDE_DIRS = ("shockwave_tpu", "scripts")
#: Generated code is not ours to lint.
DEFAULT_EXCLUDE_GLOBS = ("shockwave_tpu/runtime/proto/*",)


def default_root() -> str:
    """The repo root: the directory holding the shockwave_tpu package."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def run_timed(root: Optional[str] = None,
              select: Optional[List[str]] = None
              ) -> Tuple[List[Finding], Dict[str, dict]]:
    """Run the selected passes (plus the suppression audit) with
    repo-default scopes. Returns (findings sorted by location,
    per-pass {id: {findings, wall_s}} timing table)."""
    index = cached_index(root or default_root(),
                         include_dirs=DEFAULT_INCLUDE_DIRS,
                         exclude_globs=DEFAULT_EXCLUDE_GLOBS)
    index.reset_suppression_hits()
    findings: List[Finding] = []
    timing: Dict[str, dict] = {}
    selected = [p for p in (select or sorted(ALL_PASSES))
                if p != SUPPRESSION_AUDIT_ID]
    for name in selected:
        t0 = time.perf_counter()
        got = ALL_PASSES[name](index)
        timing[name] = {"findings": len(got),
                        "wall_s": round(time.perf_counter() - t0, 4)}
        findings.extend(got)
    # The audit must see every selected pass's suppression hits, so it
    # always runs last.
    t0 = time.perf_counter()
    got = check_suppression_audit(index, ran_pass_ids=selected)
    timing[SUPPRESSION_AUDIT_ID] = {
        "findings": len(got),
        "wall_s": round(time.perf_counter() - t0, 4)}
    findings.extend(got)
    return (sorted(findings, key=lambda f: (f.path, f.line, f.pass_id)),
            timing)


def run(root: Optional[str] = None,
        select: Optional[List[str]] = None) -> List[Finding]:
    """Back-compat entry point (tests, check.py): findings only."""
    return run_timed(root=root, select=select)[0]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis",
        description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: autodetect "
                             "from the installed package location)")
    parser.add_argument("--select", default=None,
                        help="comma-separated pass ids "
                             f"(default: all of {', '.join(sorted(ALL_PASSES))}"
                             "; the suppression audit always rides along)")
    parser.add_argument("--list", action="store_true",
                        help="list pass ids with their wall time on this "
                             "tree, and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report (findings + per-pass "
                             "wall) instead of text")
    args = parser.parse_args(argv)

    if args.list:
        _, timing = run_timed(root=args.root)
        for name, fn in sorted(ALL_PASSES.items()):
            first_line = (fn.__doc__ or "").strip().splitlines()[0]
            t = timing.get(name, {})
            print(f"{name}: {first_line} "
                  f"[wall {t.get('wall_s', 0.0):.3f}s, "
                  f"{t.get('findings', 0)} finding(s)]")
        t = timing.get(SUPPRESSION_AUDIT_ID, {})
        print(f"{SUPPRESSION_AUDIT_ID}: "
              f"{check_suppression_audit.__doc__.strip().splitlines()[0]} "
              f"[wall {t.get('wall_s', 0.0):.3f}s, "
              f"{t.get('findings', 0)} finding(s)]")
        total = sum(v.get("wall_s", 0.0) for v in timing.values())
        print(f"total analyzer wall: {total:.3f}s")
        return 0

    select = None
    if args.select:
        select = [p.strip() for p in args.select.split(",") if p.strip()]
        # The audit is not in ALL_PASSES (it must run after the others
        # and always rides along), but selecting it is legal: alone, it
        # still flags unknown-id suppressions.
        unknown = [p for p in select
                   if p not in ALL_PASSES and p != SUPPRESSION_AUDIT_ID]
        if unknown:
            print(f"unknown pass id(s): {', '.join(unknown)} "
                  f"(try --list)", file=sys.stderr)
            return 2

    findings, timing = run_timed(root=args.root, select=select)
    if args.json:
        report = {
            "findings": [{"file": f.path, "line": f.line,
                          "pass": f.pass_id, "message": f.message}
                         for f in findings],
            "count": len(findings),
            "passes": [{"id": name, **timing[name]}
                       for name in sorted(timing)],
        }
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f)
        print(f"swtpu-check: {len(findings)} finding(s)"
              + ("" if findings else " — tree is clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
