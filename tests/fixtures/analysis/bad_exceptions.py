"""exception-hygiene negative fixture: a bare except and a silent
broad handler (lines marked SEEDED); logged/narrow handlers must NOT
be reported."""
import logging


def run(task):
    try:
        task()
    except:  # SEEDED: bare except  # noqa: E722
        pass
    try:
        task()
    except Exception:  # SEEDED: silently swallowed
        pass
    try:
        task()
    except Exception:
        logging.exception("task failed")  # logged: not a finding
    try:
        task()
    except OSError:  # narrow type: not a finding
        pass
