#!/usr/bin/env python3
"""Autoencoder recommender / ML-20M workload
(trace: "Recommendation (batch size N)").

CLI parity with the reference's recommendation train.py — the trace
command is `python3 train.py --data_dir %s/ml-20m/pro_sg/ --batch_size N`
with `-n` (steps) appended by the dispatcher.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

import jax

from shockwave_tpu.models import data
from shockwave_tpu.models.recommendation import AutoEncoder, multinomial_nll
from shockwave_tpu.models.train_common import Trainer, common_parser, parse_args


def main():
    p = common_parser("AutoEncoder on ML-20M", steps_args=("-n", "--num_steps"))
    p.add_argument("--data_dir", default=None)
    p.add_argument("--batch_size", type=int, default=2048)
    args = parse_args(p)

    model = AutoEncoder()
    rng = jax.random.PRNGKey(0)
    import jax.numpy as jnp
    sample = jnp.zeros((1, model.num_items), jnp.float32)
    variables = model.init(rng, sample)
    init_state = {"params": variables["params"]}

    def loss_fn(params, state, interactions):
        logits = model.apply({"params": params}, interactions)
        return multinomial_nll(logits, interactions), {}

    trainer = Trainer(
        args, loss_fn, init_state,
        data.ml20m(args.batch_size, num_items=model.num_items,
                   data_dir=args.data_dir),
        initial_bs=args.batch_size, max_bs=8192, learning_rate=1e-3)
    trainer.run()


if __name__ == "__main__":
    main()
