"""Gang-member worker process for the 2-process gang barrier test.

Launched by tests/test_runtime.py::TestGangBarrier in two subprocesses.
Each process: joins the gang via jax.distributed.initialize, runs a
LeaseIterator-driven loop over the global 2-process CPU mesh, and on
lease expiry hits the synchronized exit barrier before writing its
checkpoint — the TPU-native equivalent of the reference's
torch.distributed.barrier() on expiry (gavel_iterator.py:148-149).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--process_id", type=int, required=True)
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--gang_sync_every", type=int, default=16)
    p.add_argument("--skew_ms", type=float, default=0.0,
                   help="artificial per-step slowdown for this member, to "
                        "prove time-based exits still land on the same step")
    args = p.parse_args()

    import jax

    jax.distributed.initialize(args.coordinator, args.num_processes,
                               args.process_id)
    from jax.experimental import multihost_utils

    import jax.numpy as jnp

    from shockwave_tpu.runtime.iterator import LeaseIterator

    import numpy as np

    barrier_times = []

    def barrier():
        multihost_utils.sync_global_devices("gang_test_exit")
        barrier_times.append(time.time())

    def gang_allreduce(value, op):
        arr = np.asarray(multihost_utils.process_allgather(
            np.float32(value)))
        return float(arr.max() if op == "max" else arr.min())

    ckpt = os.path.join(args.checkpoint_dir,
                        f"proc{args.process_id}.ckpt")

    it = LeaseIterator(
        data_loader=list(range(8)), checkpoint_dir=args.checkpoint_dir,
        load_checkpoint_func=lambda p: None,
        save_checkpoint_func=lambda p, s: open(p, "w").write(s),
        synthetic_data=True, distributed_barrier=barrier,
        gang_allreduce=gang_allreduce, gang_sync_every=args.gang_sync_every)

    steps = 0
    x = jnp.zeros(())
    while not it.done:
        try:
            for _ in it:
                # A real cross-process collective each step: the gang is
                # actually coupled, not just co-scheduled. An unmatched
                # exit would therefore hang, not just skew counters.
                x = multihost_utils.process_allgather(x + 1.0).sum()
                it.set_sync_ref(x)
                steps += 1
                if args.skew_ms:
                    time.sleep(args.skew_ms / 1e3)
        except StopIteration:
            pass
    it.save_checkpoint(ckpt, f"steps={steps}")
    print(f"EXITED process={args.process_id} steps={steps} "
          f"barriers={len(barrier_times)} x={float(x):.1f}", flush=True)


if __name__ == "__main__":
    main()
