"""GPipe-style pipeline parallelism over the mesh's "pp" axis.

Temporal pipelining, not just layer-sharded memory: stage s holds only
its own block's parameters (leading stage dim sharded over "pp"), and a
`lax.scan` over ticks streams microbatches through the stage chain with
one `lax.ppermute` hop per tick — activations ride ICI to the next
stage while that stage's compute for the next microbatch overlaps.
Bubble fraction is the standard (S - 1) / (M + S - 1).

The reference has no pipeline parallelism at all (its jobs are
single-model DDP, workloads/pytorch/*); this is part of the TPU-native
scaling surface (dp x pp x tp x sp x ep) the framework adds.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax spells it jax.experimental.shard_map
    from jax.experimental.shard_map import shard_map

from .compat import to_varying


def _pipeline_local(stage_params, microbatches, *, stage_fn: Callable,
                    axis_name: str, varying_axes=()):
    """Per-device body. stage_params: this stage's params (leading dim 1
    after sharding); microbatches: (M, mb, ...) local dp/sp shard."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = n_micro + n_stages - 1

    # Rotate activations one stage forward per tick; stage 0 injects
    # microbatch t, the last stage's outputs accumulate into `outs`.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, outs = carry
        # Stage 0 consumes microbatch t; once the trace drains it keeps
        # re-injecting the last microbatch, whose outputs never reach the
        # out_idx window and are discarded.
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, n_micro - 1), keepdims=False)
        x = jnp.where(stage == 0, inject, buf)
        y = stage_fn(params, x)
        # Microbatch index flowing OUT of the last stage at tick t
        # entered at tick t - (S - 1); a masked select keeps the carry's
        # varying-axis type uniform (a cond's branches would not).
        out_idx = t - (n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(
            outs, y, jnp.maximum(out_idx, 0), axis=0)
        outs = jnp.where(out_idx >= 0, updated, outs)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    axes = (axis_name,) + tuple(varying_axes)
    buf0 = to_varying(jnp.zeros(mb_shape, microbatches.dtype), axes)
    outs0 = to_varying(jnp.zeros((n_micro,) + mb_shape,
                                 microbatches.dtype), axes)
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # Only the last stage holds real outputs; broadcast over the ring.
    outs = jnp.where(stage == n_stages - 1, outs, 0)
    return lax.psum(outs, axis_name)


def pipeline_apply(stage_params, x, mesh: Mesh, num_microbatches: int,
                   stage_fn: Callable, axis_name: str = "pp"):
    """Run x (batch, ...) through the staged blocks.

    stage_params: pytree whose leaves have leading dim = pp size (one
    slice per stage), sharded P(axis_name). stage_fn(params, mb) must
    map a microbatch to an output of the same shape/dtype. The
    microbatch dim stays sharded over "dp" and dim 2 (sequence, when
    present) over "sp" — each dp/sp shard pipelines only its own slice;
    microbatch size must divide by the dp extent (and seq by sp).
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    assert batch % num_microbatches == 0, (batch, num_microbatches)
    mbs = x.reshape((num_microbatches, batch // num_microbatches)
                    + x.shape[1:])

    # mbs is (micro, mb, ...): shard mb over dp, and the sequence dim
    # over sp when the payload is (batch, seq, features)-shaped.
    if mbs.ndim >= 4:
        data_spec, varying = P(None, "dp", "sp"), ("dp", "sp")
    else:
        data_spec, varying = P(None, "dp"), ("dp",)
    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis_name=axis_name,
                varying_axes=varying),
        mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=data_spec)
    out = fn(stage_params, mbs)
    return out.reshape((batch,) + out.shape[2:])
