#!/usr/bin/env python3
"""Simulation driver over generated jobs with Poisson arrivals.

Instead of replaying a fixed trace, samples `--num_jobs` jobs from the
template table (Philly scale-factor/duration mixes) with exponential
interarrival gaps, then runs the same simulator loop as simulate.py
(reference: scheduler/scripts/drivers/simulate_scheduler_with_generated_jobs.py).

Example:
    python scripts/drivers/simulate_generated.py \
        --num_jobs 64 --lam 600 --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json --cluster_spec v100:16
"""
import argparse
import json
import logging
import os
import pickle
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.generator import generate_trace
from shockwave_tpu.core.metrics import (parse_cluster_spec,
                                        unfair_fraction)
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", type=int, default=64)
    p.add_argument("--lam", type=float, default=0.0,
                   help="mean interarrival seconds (0 = all arrive at t=0)")
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--cluster_spec", default="v100:32")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--multi_gpu", action="store_true", default=True)
    p.add_argument("--no_multi_gpu", dest="multi_gpu", action="store_false")
    p.add_argument("--dynamic", action="store_true", default=True,
                   help="include accordion/gns jobs")
    p.add_argument("--static_only", dest="dynamic", action="store_false")
    p.add_argument("--min_duration_hours", type=float, default=0.2)
    p.add_argument("--max_duration_hours", type=float, default=5.0)
    p.add_argument("--reference_worker_type", default=None,
                   help="oracle worker type that anchors duration->steps "
                        "(default: v100 when present, else the first "
                        "cluster_spec type — e.g. v5e for a TPU oracle)")
    p.add_argument("--config", default=None,
                   help="JSON file of shockwave hyperparameters")
    p.add_argument("--output", default=None, help="metrics pickle path")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(name)s:%(levelname)s %(message)s")

    throughputs = read_throughputs(args.throughputs)
    cluster_spec = parse_cluster_spec(args.cluster_spec)
    reference_worker_type = (
        args.reference_worker_type
        or ("v100" if "v100" in throughputs else next(iter(cluster_spec))))
    jobs, arrival_times = generate_trace(
        args.num_jobs, throughputs, lam=args.lam, seed=args.seed,
        generate_multi_gpu_jobs=args.multi_gpu,
        generate_dynamic_jobs=args.dynamic,
        min_duration_hours=args.min_duration_hours,
        max_duration_hours=args.max_duration_hours,
        reference_worker_type=reference_worker_type)
    profiles = build_profiles(jobs, throughputs,
                              worker_type=reference_worker_type)

    shockwave_config = None
    if args.config:
        with open(args.config) as f:
            shockwave_config = json.load(f)
    elif args.policy == "shockwave":
        shockwave_config = {}
    if shockwave_config is not None:
        shockwave_config["num_gpus"] = sum(cluster_spec.values())
        shockwave_config["time_per_iteration"] = args.round_duration

    policy = get_policy(args.policy, seed=args.seed)
    sched = Scheduler(
        policy, simulate=True, throughputs_file=args.throughputs,
        profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=args.round_duration, seed=args.seed,
            max_rounds=args.max_rounds, shockwave=shockwave_config))

    makespan = sched.simulate(cluster_spec, arrival_times, jobs)

    jct = sched.get_average_jct()
    ftf_static, ftf_themis = sched.get_finish_time_fairness()
    util, util_list = sched.get_cluster_utilization()
    unfair = unfair_fraction(ftf_static)
    solve_stats = sched.get_solve_stats()
    if args.output:
        with open(args.output, "wb") as f:
            ext_pct, ext, opp = sched.get_num_lease_extensions()
            pickle.dump({
                "policy": args.policy, "num_jobs": args.num_jobs,
                "lam": args.lam, "seed": args.seed, "makespan": makespan,
                "avg_jct": jct[0] if jct else None,
                "geometric_mean_jct": jct[1] if jct else None,
                "harmonic_mean_jct": jct[2] if jct else None,
                "jct_list": jct[3] if jct else [],
                "finish_time_fairness_list": ftf_static,
                "finish_time_fairness_themis_list": ftf_themis,
                "cluster_util": util,
                "utilization_list": util_list,
                "extension_percentage": ext_pct,
                "per_round_schedule": sched.rounds.per_round_schedule,
                "time_per_iteration": args.round_duration,
                "milp_solve_stats": solve_stats,
            }, f)
    summary = {
        "policy": args.policy,
        "num_jobs": args.num_jobs,
        "lam": args.lam,
        "makespan": round(makespan, 2),
        "avg_jct": round(jct[0], 2) if jct else None,
        "unfair_fraction": round(unfair, 4),
        "cluster_util": round(util, 4),
    }
    if solve_stats:
        paths = [s["path"] for s in solve_stats]
        gaps = [s["mip_gap"] for s in solve_stats
                if s["mip_gap"] is not None]
        summary["milp_solves"] = len(paths)
        summary["milp_paths"] = {p: paths.count(p) for p in sorted(set(paths))}
        summary["milp_greedy_rate"] = round(
            paths.count("greedy") / len(paths), 4)
        if gaps:
            summary["milp_max_gap"] = round(max(gaps), 6)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
