from .lease import Lease
from .resilience import (CircuitBreaker, CircuitOpenError, RetryPolicy,
                         RpcUnavailableError)

__all__ = ["Lease", "RetryPolicy", "CircuitBreaker", "RpcUnavailableError",
           "CircuitOpenError"]
