"""Stand-in name catalog for the obs-discipline propagation-contract
test: declares reserved span-context/shard constants the way
obs/names.py does (module-level NAME = "literal" assignments matching
OBS_RESERVED_CONST_RE). Never a violation itself."""

TRACEPARENT_METADATA_KEY = "fixture-traceparent"
TRACE_SENDTS_METADATA_KEY = "fixture-trace-sendts"
SHARD_FILE_PREFIX = "fixture-spans-"
