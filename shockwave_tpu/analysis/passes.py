"""The swtpu-check passes.

Each pass is a function ``check_<name>(index, ...) -> List[Finding]``
taking a ``core.RepoIndex``; scope/allowlist arguments default to the
repo's real configuration (``__main__`` runs them with defaults) and
are injectable so the fixture-based negative tests can point a pass at
a deliberately-broken module.

| pass id            | invariant                                             |
|--------------------|-------------------------------------------------------|
| lock-discipline    | ``_LOCK_PROTECTED`` fields only touched under the     |
|                    | lock / in ``@requires_lock`` methods                  |
| journal-coverage   | emitted journal event types <-> ``_replay_*`` handlers|
|                    | is a bijection                                        |
| durability         | no raw write-mode ``open`` in state-owning modules,   |
|                    | no ``os.rename/replace`` outside ``core/durable_io``  |
| determinism        | no wall clock / unseeded RNG in simulator, solver and |
|                    | shockwave modules                                     |
| exception-hygiene  | no bare ``except:``, no silent ``except Exception:    |
|                    | pass``                                                |
| obs-discipline     | metric/span names are attribute references into       |
|                    | ``obs/names.py`` (no inline literals); ``obs/`` takes |
|                    | its clock by injection (``obs/clock.py`` only)        |
| thread-roots       | every thread spawn (Thread/Timer/HTTP handler/gRPC    |
|                    | callback dict) resolves to a function in the tree     |
|                    | (analysis/threads.py)                                 |
| race-detector      | every cross-thread field holds a consistent lockset   |
|                    | or a documented registry verdict (analysis/races.py)  |
| deadlock           | the static lock-order graph (held-locks dataflow over |
|                    | the call graph) is acyclic, or every cycle edge is    |
|                    | sanctioned in ``_LOCK_ORDER_JUSTIFIED``               |
|                    | (analysis/lockflow.py)                                |
| hold-discipline    | no blocking op (RPC, fsync, solve, sleep, timeout-    |
|                    | less wait, subprocess, queue/socket) reachable with a |
|                    | lock held, or a ``_HOLD_DISCIPLINE_JUSTIFIED`` verdict|
|                    | (analysis/lockflow.py)                                |
| suppression-audit  | every inline ignore[] still matches a finding the     |
|                    | named pass would otherwise report (runs last)         |
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import (Finding, RepoIndex, SourceFile, call_name, const_str,
                   decorated_requires_lock, finding, is_self_attr,
                   literal_str_set)

# ----------------------------------------------------------------------
# 1. lock-discipline
# ----------------------------------------------------------------------

LOCK_ATTRS = frozenset({"_lock", "_cv"})
#: Methods that run before the object escapes its constructor thread.
LOCK_EXEMPT_METHODS = frozenset({"__init__"})
PROTECTED_REGISTRY_NAME = "_LOCK_PROTECTED"


def _is_lock_expr(node: ast.AST, lock_attrs: frozenset) -> bool:
    return (isinstance(node, ast.Attribute) and is_self_attr(node)
            and node.attr in lock_attrs)


def check_lock_discipline(index: RepoIndex,
                          lock_attrs: frozenset = LOCK_ATTRS,
                          exempt_methods: frozenset = LOCK_EXEMPT_METHODS
                          ) -> List[Finding]:
    """Every class that declares ``_LOCK_PROTECTED = frozenset({...})``
    gets its methods checked: a read or write of ``self.<field>`` for a
    protected field must sit lexically inside ``with self._lock`` /
    ``with self._cv``, or in a method annotated ``@requires_lock``
    (whose callers are runtime-checked by the sanitizer), or in
    ``__init__`` (single-threaded by construction). Nested function
    bodies run at call time, not at definition time, so they reset the
    lock context — a timer callback defined inside a locked region is
    NOT covered by it."""
    pass_id = "lock-discipline"
    findings: List[Finding] = []

    def scan(src: SourceFile, protected: Set[str], node: ast.AST,
             locked: bool, fn_line: int) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(
                _is_lock_expr(item.context_expr, lock_attrs)
                for item in node.items)
            for child in ast.iter_child_nodes(node):
                scan(src, protected, child, inner, fn_line)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = (decorated_requires_lock(node)
                     or node.name in exempt_methods)
            # No early return on a def-line suppression: the per-access
            # path below consults it only when a finding would actually
            # fire, so the suppression-audit can tell a load-bearing
            # function-level ignore from a stale one.
            for child in node.body:
                scan(src, protected, child, inner, node.lineno)
            return
        if isinstance(node, ast.Lambda):
            scan(src, protected, node.body, False, fn_line)
            return
        if (isinstance(node, ast.Attribute) and is_self_attr(node)
                and node.attr in protected and not locked):
            f = finding(src, node, pass_id,
                        f"unlocked access to protected field "
                        f"'self.{node.attr}' (hold self._lock/_cv, or "
                        f"annotate the method @requires_lock)")
            if f is not None and not src.suppressed(fn_line, pass_id):
                findings.append(f)
            return
        for child in ast.iter_child_nodes(node):
            scan(src, protected, child, locked, fn_line)

    for src in index.files:
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            protected: Optional[Set[str]] = None
            for stmt in cls.body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == PROTECTED_REGISTRY_NAME):
                    protected = literal_str_set(stmt.value)
            if not protected:
                continue
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan(src, protected, item, False, item.lineno)
    return findings


# ----------------------------------------------------------------------
# 2. journal-coverage
# ----------------------------------------------------------------------

#: Methods whose first positional argument is a journal event type.
EMIT_METHODS = frozenset({"self._emit", "self._emit_audit",
                          "self._emit_event", "self._journal_event"})
REPLAY_PREFIX = "_replay_"


def check_journal_coverage(index: RepoIndex) -> List[Finding]:
    """Journaled event types and ``_replay_*`` handlers must form a
    bijection across the indexed tree: an emit without a handler is
    state that recovery silently drops; a handler without an emit is
    dead replay code masking a renamed/removed event."""
    pass_id = "journal-coverage"
    emits: Dict[str, Tuple[SourceFile, int]] = {}
    handlers: Dict[str, Tuple[SourceFile, int]] = {}
    for src in index.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                if call_name(node) in EMIT_METHODS and node.args:
                    etype = const_str(node.args[0])
                    if etype is not None:
                        emits.setdefault(etype, (src, node.lineno))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name.startswith(REPLAY_PREFIX):
                    etype = node.name[len(REPLAY_PREFIX):]
                    handlers.setdefault(etype, (src, node.lineno))
    findings: List[Finding] = []
    for etype, (src, line) in sorted(emits.items()):
        if etype not in handlers:
            f = finding(src, line, pass_id,
                        f"journal event '{etype}' is emitted but has no "
                        f"_replay_{etype} handler: recovery would "
                        "silently drop it")
            if f is not None:
                findings.append(f)
    for etype, (src, line) in sorted(handlers.items()):
        if etype not in emits:
            f = finding(src, line, pass_id,
                        f"replay handler _replay_{etype} has no matching "
                        "emit site: dead recovery code (renamed or "
                        "removed event?)")
            if f is not None:
                findings.append(f)
    return findings


# ----------------------------------------------------------------------
# 3. durability
# ----------------------------------------------------------------------

#: Modules that own durable state: raw write-mode opens here must go
#: through core/durable_io instead.
DURABILITY_STATE_GLOBS = (
    "shockwave_tpu/sched/*.py",
    "shockwave_tpu/models/train_common.py",
    "shockwave_tpu/core/durable_io.py",
)
#: The durable-write implementation itself (and the CRC-framed journal
#: writer built directly on fsync) — the only places the primitives may
#: appear.
DURABILITY_ALLOW_GLOBS = (
    "shockwave_tpu/core/durable_io.py",
    "shockwave_tpu/sched/journal.py",
)
#: Modules allowed to use the rename/delete primitives, where every
#: use must pair with a containing-directory fsync (the durability-
#: pass dir-fsync rule): the durable-io core plus the HA lease/epoch
#: store, whose O_EXCL claim files are fencing decisions a crash must
#: not un-happen.
DURABILITY_DIR_FSYNC_GLOBS = DURABILITY_ALLOW_GLOBS + (
    "shockwave_tpu/sched/ha.py",
)
#: Directory-entry mutations that POSIX only makes durable after an
#: fsync of the containing directory.
_DIR_MUTATION_CALLS = frozenset({"os.rename", "os.replace", "os.remove",
                                 "os.unlink"})
_WRITE_MODE_CHARS = set("wax+")


def _open_write_mode(node: ast.Call) -> Optional[str]:
    """The constant mode string of an `open` call when it enables
    writing, else None. A non-constant mode counts as a write (it can't
    be proven safe)."""
    mode_node: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode_node = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None  # default "r"
    mode = const_str(mode_node)
    if mode is None:
        return "<dynamic>"
    return mode if _WRITE_MODE_CHARS & set(mode) else None


def _check_dir_fsync_pairing(src: SourceFile,
                             findings: List[Finding]) -> None:
    """Dir-fsync rule for the durable-io modules themselves: every
    function that renames/deletes a durable file must also fsync the
    containing directory in that same function — a rename a crash can
    lose (the dirent never became durable) silently un-rotates a
    journal segment or un-promotes a snapshot ``.prev`` on some
    filesystems, and recovery then replays against the wrong
    generation."""
    pass_id = "durability"
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mutations = []
        has_dir_fsync = False
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = call_name(sub)
            if name in _DIR_MUTATION_CALLS:
                mutations.append((sub, name))
            # fsync_dir / _fsync_dir, bare or module-qualified — and
            # write_durable / write_text_atomic, which fsync the
            # directory internally.
            tail = name.rsplit(".", 1)[-1]
            if tail in ("fsync_dir", "_fsync_dir", "write_durable",
                        "write_text_atomic"):
                has_dir_fsync = True
        if mutations and not has_dir_fsync:
            for sub, name in mutations:
                f = finding(src, sub, pass_id,
                            f"{name} in a durable-io function with no "
                            "containing-directory fsync: the rename/"
                            "delete may not survive a crash (call "
                            "fsync_dir in the same function)")
                if f is not None:
                    findings.append(f)


def check_durability(index: RepoIndex,
                     state_globs: Iterable[str] = DURABILITY_STATE_GLOBS,
                     allow_globs: Iterable[str] = DURABILITY_ALLOW_GLOBS,
                     dir_fsync_globs: Iterable[str]
                     = DURABILITY_DIR_FSYNC_GLOBS) -> List[Finding]:
    """State/checkpoint bytes must reach disk only through
    ``core/durable_io.write_durable`` (CRC footer + fsync + atomic
    rename + dir fsync). Flags raw write-mode ``open`` calls in
    state-owning modules, and the rename/replace primitives anywhere in
    the indexed tree outside durable_io. Inside the durable-io modules
    themselves, every rename/delete must pair with a directory fsync
    (`_check_dir_fsync_pairing`)."""
    pass_id = "durability"
    findings: List[Finding] = []
    for src in index.files:
        if src.matches(dir_fsync_globs):
            _check_dir_fsync_pairing(src, findings)
        if src.matches(allow_globs):
            continue
        in_state_scope = src.matches(state_globs)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in ("os.rename", "os.replace"):
                f = finding(src, node, pass_id,
                            f"{name} outside core/durable_io.py: atomic "
                            "replacement of durable files must use "
                            "write_durable (CRC footer + fsync + dir "
                            "fsync)")
                if f is not None:
                    findings.append(f)
            elif name == "open" and in_state_scope:
                mode = _open_write_mode(node)
                if mode is not None:
                    f = finding(src, node, pass_id,
                                f"raw open(..., {mode!r}) in a "
                                "state-owning module: durable writes "
                                "must go through core/durable_io."
                                "write_durable")
                    if f is not None:
                        findings.append(f)
    return findings


# ----------------------------------------------------------------------
# 4. determinism
# ----------------------------------------------------------------------

#: Modules whose behavior must replay bit-identically (the simulator
#: core, every policy, and the shockwave planner/MILP stack).
DETERMINISM_SCOPE_GLOBS = (
    "shockwave_tpu/solver/*.py",
    "shockwave_tpu/shockwave/*.py",
    "shockwave_tpu/sched/scheduler.py",
    "shockwave_tpu/sched/simcore.py",
    "shockwave_tpu/sched/state.py",
    # The what-if plane's decisions must replay identically: twin
    # forks, admission verdicts and knob sweeps are derived only from
    # scheduler state + seeded RNG (fork-cost wall telemetry is
    # inline-suppressed).
    "shockwave_tpu/whatif/*.py",
    # The Monte Carlo sweep's and the chaos campaign's artifacts must
    # be byte-reproducible from their seeds: scenario content is
    # seeded-RNG only, and wall clocks are confined to inline-
    # suppressed throughput telemetry / subprocess babysitting.
    "scripts/drivers/sweep_scenarios.py",
    "scripts/drivers/chaos_campaign.py",
    "scripts/drivers/whatif_overload_study.py",
    # The measured-serving path: the replica-side arrival clock and
    # the mergeable quantile sketch must be pure functions of (spec,
    # seed, measured durations) — a wall clock or unseeded RNG here
    # would fork replica request streams across dispatches and break
    # the byte-stable calibration artifact CI cmp's.
    "shockwave_tpu/serving/*.py",
    "shockwave_tpu/obs/quantiles.py",
    "scripts/drivers/serving_measured_calibration.py",
    # The learned throughput oracle: model fits, featurization (hash
    # buckets are md5-of-string, never Python hash()) and online
    # corrections must be pure functions of (history rows, seed) —
    # the trained model file and the mixed-generation cold-start
    # study are byte-compared in CI.
    "shockwave_tpu/oracle/*.py",
    "scripts/drivers/oracle_coldstart_study.py",
)
#: Wall-clock measurement utilities (two-point marginal timing) are the
#: sanctioned home for real clocks.
DETERMINISM_ALLOW_GLOBS = ("shockwave_tpu/core/timing.py",)

_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})
#: numpy.random constructors that are deterministic WHEN SEEDED.
_SEEDABLE_RNG = frozenset({
    "numpy.random.RandomState", "numpy.random.default_rng",
    "random.Random",
})
_RNG_MODULES = ("random", "numpy.random")


def _alias_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> canonical dotted prefix, for the modules the
    determinism pass cares about."""
    aliases: Dict[str, str] = {}
    interesting = {"time", "datetime", "random", "numpy", "numpy.random"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in interesting:
                    aliases[alias.asname or alias.name] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module in interesting:
            for alias in node.names:
                aliases[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}")
    return aliases


def _canonical(name: str, aliases: Dict[str, str]) -> str:
    head, _, rest = name.partition(".")
    base = aliases.get(head)
    if base is None:
        return name
    return f"{base}.{rest}" if rest else base


def _is_seeded_call(node: ast.Call) -> bool:
    """Whether an RNG constructor is given a real seed: any positional
    arg or a seed= keyword counts, UNLESS it is a literal None (which
    all of these constructors treat as 'seed from OS entropy')."""

    def real(value: ast.AST) -> bool:
        return not (isinstance(value, ast.Constant) and value.value is None)

    if any(real(a) for a in node.args):
        return True
    return any(kw.arg == "seed" and real(kw.value) for kw in node.keywords)


def check_determinism(index: RepoIndex,
                      scope_globs: Iterable[str] = DETERMINISM_SCOPE_GLOBS,
                      allow_globs: Iterable[str] = DETERMINISM_ALLOW_GLOBS
                      ) -> List[Finding]:
    """Simulator/solver/shockwave modules must not read wall clocks or
    unseeded RNGs: PR 2's recovery acceptance (and the fidelity
    methodology) rely on bit-identical replay, and one ``time.time()``
    in a policy silently breaks it for every future run."""
    pass_id = "determinism"
    findings: List[Finding] = []
    for src in index.files:
        if not src.matches(scope_globs) or src.matches(allow_globs):
            continue
        aliases = _alias_map(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _canonical(call_name(node), aliases)
            message = None
            if name in _CLOCK_CALLS:
                message = (f"wall-clock call {name}() in a "
                           "replay-deterministic module (route time "
                           "through get_current_timestamp / journaled "
                           "events)")
            elif any(name == m or name.startswith(m + ".")
                     for m in _RNG_MODULES):
                if name in _SEEDABLE_RNG and _is_seeded_call(node):
                    pass  # seeded constructor: deterministic
                else:
                    message = (f"unseeded RNG call {name}(...) in a "
                               "replay-deterministic module (use a "
                               "seeded Random/RandomState instance)")
            if message is not None:
                f = finding(src, node, pass_id, message)
                if f is not None:
                    findings.append(f)
    return findings


# ----------------------------------------------------------------------
# 5. exception-hygiene
# ----------------------------------------------------------------------

_BROAD_NAMES = frozenset({"Exception", "BaseException"})


def _is_broad_handler(type_node: ast.AST) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD_NAMES
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_handler(elt) for elt in type_node.elts)
    return False


def _body_is_silent(body: List[ast.stmt]) -> bool:
    """True when the handler neither logs, re-raises, nor produces a
    value — i.e. the error evaporates."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def check_exception_hygiene(index: RepoIndex) -> List[Finding]:
    """No bare ``except:`` anywhere; no ``except Exception: pass`` —
    in the daemon threads and gRPC servicers that keep the control
    plane alive, a swallowed exception IS the outage, just deferred.
    Handlers that log, re-raise, or return a fallback are fine."""
    pass_id = "exception-hygiene"
    findings: List[Finding] = []
    for src in index.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                f = finding(src, node, pass_id,
                            "bare 'except:' catches SystemExit/"
                            "KeyboardInterrupt too; name the exception "
                            "types")
                if f is not None:
                    findings.append(f)
            elif _is_broad_handler(node.type) and _body_is_silent(node.body):
                f = finding(src, node, pass_id,
                            "'except Exception: pass' silently swallows "
                            "the error; log it (or narrow the type and "
                            "say why it is ignorable)")
                if f is not None:
                    findings.append(f)
    return findings


# ----------------------------------------------------------------------
# 6. obs-discipline
# ----------------------------------------------------------------------

#: The central name catalog — the only module where metric/span name
#: string literals may appear.
OBS_NAMES_GLOBS = ("shockwave_tpu/obs/names.py",)
#: The observability package itself, which must take its clock by
#: injection...
OBS_MODULE_GLOBS = ("shockwave_tpu/obs/*.py",)
#: ...plus every span-emitting runtime module: span timestamps must be
#: stamped through the injected obs clock (obs/shard.py), so a raw wall
#: clock here would fork the fleet-trace timebase — and the measured-
#: serving reporter, whose virtual request clock is driven ONLY by
#: caller-injected durations (serve.py measures; the module never
#: reads a clock itself).
OBS_CLOCK_EXTRA_GLOBS = ("shockwave_tpu/runtime/spans.py",
                         "shockwave_tpu/serving/measured.py")
#: ...except the one designated clock adapter.
OBS_CLOCK_ALLOW_GLOBS = ("shockwave_tpu/obs/clock.py",)
#: Instrument entry points whose first argument is a metric/span name.
OBS_INSTRUMENT_METHODS = frozenset({
    "inc", "observe", "set_gauge", "timed", "span", "phase",
})
#: names.py module-level constants whose VALUES are reserved literals:
#: span-context propagation keys (gRPC metadata, env vars) and shard
#: filename parts. Their string values may appear ONLY in names.py —
#: a literal copy anywhere else is a cross-process contract fork.
OBS_RESERVED_CONST_RE = r"^(TRACEPARENT|TRACE_SENDTS|SHARD_DIR|SHARD_FILE|MERGED_TRACE|HISTORY_FILE)"


def _reserved_literals(index: RepoIndex,
                       names_globs: Iterable[str]) -> Dict[str, str]:
    """value -> declaring constant name, harvested from names.py
    module-level assignments matching OBS_RESERVED_CONST_RE."""
    import re as _re
    pattern = _re.compile(OBS_RESERVED_CONST_RE)
    reserved: Dict[str, str] = {}
    for src in index.files:
        if not src.matches(names_globs):
            continue
        for node in src.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and pattern.match(node.targets[0].id)):
                value = const_str(node.value)
                # Too-generic fragments (e.g. a bare ".json" suffix)
                # would flag every unrelated artifact path; only values
                # long enough to be unmistakably the contract are
                # reserved.
                if value is not None and len(value) >= 6:
                    reserved[value] = node.targets[0].id
    return reserved


def check_obs_discipline(index: RepoIndex,
                         names_globs: Iterable[str] = OBS_NAMES_GLOBS,
                         obs_globs: Iterable[str] = OBS_MODULE_GLOBS,
                         clock_allow_globs: Iterable[str]
                         = OBS_CLOCK_ALLOW_GLOBS,
                         clock_extra_globs: Iterable[str]
                         = OBS_CLOCK_EXTRA_GLOBS) -> List[Finding]:
    """Three parts of the instrumentation discipline: (1) every
    metric/span name at an instrument call site (``.inc(...)``,
    ``.observe(...)``, ``.span(...)``, ...) must be an attribute
    reference into ``obs/names.py``, never an inline string literal —
    ad-hoc names fork the catalog and rot silently out of the docs and
    dashboards; (2) span-context keys and shard filename parts (the
    cross-process propagation contract) are declared ONLY in names.py —
    any other file repeating one of those string values verbatim forks
    the contract between the scheduler, worker daemon, dispatcher and
    trainer; (3) neither ``obs/`` nor any span-emitting runtime module
    (``runtime/spans.py``) reads a wall clock outside the designated
    adapter ``obs/clock.py`` — the injected clock is what lets the same
    instrumentation run under the simulator's virtual clock without
    breaking bit-identical replay, and what keeps shard timestamps on
    one timebase for the merge."""
    pass_id = "obs-discipline"
    findings: List[Finding] = []
    reserved = _reserved_literals(index, names_globs)
    clock_scope = tuple(obs_globs) + tuple(clock_extra_globs)
    for src in index.files:
        if not src.matches(names_globs):
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value in reserved):
                    f = finding(
                        src, node, pass_id,
                        f"reserved span-context/shard literal "
                        f"{node.value!r} outside obs/names.py: "
                        f"reference names.{reserved[node.value]} "
                        "instead (the propagation contract is declared "
                        "in one place)")
                    if f is not None:
                        findings.append(f)
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = call_name(node)
                if "." not in name:
                    continue
                method = name.rsplit(".", 1)[-1]
                if method not in OBS_INSTRUMENT_METHODS:
                    continue
                literal = const_str(node.args[0])
                if literal is None:
                    continue
                f = finding(src, node, pass_id,
                            f"inline metric/span name {literal!r} at an "
                            f"instrument call site (.{method}): declare "
                            "it in obs/names.py and reference it as an "
                            "attribute")
                if f is not None:
                    findings.append(f)
        if src.matches(clock_scope) and not src.matches(clock_allow_globs):
            aliases = _alias_map(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                cname = _canonical(call_name(node), aliases)
                if cname in _CLOCK_CALLS:
                    f = finding(src, node, pass_id,
                                f"wall-clock call {cname}() in a "
                                "clock-disciplined obs/span module "
                                "outside the clock adapter: obs and "
                                "span-emitting runtime components take "
                                "their clock by injection (obs/clock.py "
                                "is the only sanctioned reader)")
                    if f is not None:
                        findings.append(f)
    return findings


# ----------------------------------------------------------------------
# 7. suppression-audit
# ----------------------------------------------------------------------

SUPPRESSION_AUDIT_ID = "suppression-audit"


def check_suppression_audit(index: RepoIndex,
                            ran_pass_ids: Optional[Iterable[str]] = None
                            ) -> List[Finding]:
    """Every inline ``swtpu-check: ignore[<pass-id>]`` must still be
    load-bearing: if the named pass ran over the file and never matched
    the suppression (no finding would fire on that line), the
    suppression itself is a finding — stale exceptions are how
    invariants rot invisibly. A suppression naming an unknown pass id
    is flagged unconditionally (a typo'd id suppresses nothing and
    documents a lie).

    Must run AFTER the passes it audits (the CLI driver orders this);
    only the passes in `ran_pass_ids` are audited, so a ``--select``
    subset never misreports the others' suppressions as stale."""
    ran = set(ran_pass_ids if ran_pass_ids is not None else ALL_PASSES)
    findings: List[Finding] = []
    for src in index.files:
        for line in sorted(src.suppressions):
            for pid in sorted(src.suppressions[line]):
                if pid == SUPPRESSION_AUDIT_ID:
                    continue  # the audit's own escape hatch
                if pid not in ALL_PASSES:
                    f = finding(src, line, SUPPRESSION_AUDIT_ID,
                                f"suppression names unknown pass id "
                                f"'{pid}' (typo? see --list)")
                    if f is not None:
                        findings.append(f)
                elif (pid in ran
                      and (line, pid) not in src.suppression_hits):
                    f = finding(src, line, SUPPRESSION_AUDIT_ID,
                                f"unused suppression: no [{pid}] "
                                "finding would fire on this line — "
                                "delete the stale ignore")
                    if f is not None:
                        findings.append(f)
    return findings


# ----------------------------------------------------------------------
# Registry
# ----------------------------------------------------------------------

def _check_thread_roots(index: RepoIndex) -> List[Finding]:
    """Every thread spawn (Thread/Timer/HTTP handler/gRPC callback)
    resolves statically to a function in the tree."""
    from .threads import check_thread_roots
    return check_thread_roots(index)


def _check_race_detector(index: RepoIndex) -> List[Finding]:
    """Lockset race detection: cross-thread fields hold a consistent
    lockset or carry a documented registry verdict."""
    from .races import check_race_detector
    return check_race_detector(index)


def _check_deadlock(index: RepoIndex) -> List[Finding]:
    """Static lock-order acyclicity: a cycle in the held-locks order
    graph reachable from multiple thread roots is a deadlock."""
    from .lockflow import check_deadlock
    return check_deadlock(index)


def _check_hold_discipline(index: RepoIndex) -> List[Finding]:
    """No blocking operation (RPC/fsync/solve/sleep/wait/subprocess/
    queue/socket) statically reachable with a lock held."""
    from .lockflow import check_hold_discipline
    return check_hold_discipline(index)


ALL_PASSES = {
    "lock-discipline": check_lock_discipline,
    "journal-coverage": check_journal_coverage,
    "durability": check_durability,
    "determinism": check_determinism,
    "exception-hygiene": check_exception_hygiene,
    "obs-discipline": check_obs_discipline,
    "thread-roots": _check_thread_roots,
    "race-detector": _check_race_detector,
    "deadlock": _check_deadlock,
    "hold-discipline": _check_hold_discipline,
}
