#!/usr/bin/env python3
"""Microbenchmark: simulator round-bookkeeping wall, scalar vs vectorized.

Times one round of the scheduling core's per-round bookkeeping
(priority recompute + round selection + worker assignment + round
record — `_schedule_jobs_on_workers`) over synthetic clusters at
several job counts, on both sim-core paths (sched/simcore.py vs the
retained scalar oracle), asserting the two produce identical
assignment sequences. Also replays the canonical 120-job trace end to
end on both paths and compares the full metrics pickles.

This is the evidence artifact for the ISSUE-9 tentpole: the sim-core
wall must drop >= 5x at fleet scale with replays bit-identical. (The
canonical *shockwave* replay's end-to-end wall is dominated ~90% by
HiGHS MILP solves, which no bookkeeping vectorization can touch — see
EXPERIMENTS.md "Fleet-scale simulation" for the committed profile;
this benchmark therefore measures the sim core, the thing the tentpole
vectorizes.)

Example:
    python scripts/microbenchmarks/bench_sim_round.py \
        --num_jobs 120 900 2000 --rounds 20
    python scripts/microbenchmarks/bench_sim_round.py --smoke
"""
import argparse
import json
import os
import pickle
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.generator import generate_trace  # noqa: E402
from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs import get_observability  # noqa: E402
from shockwave_tpu.obs import names as obs_names  # noqa: E402
from shockwave_tpu.sched import Scheduler, SchedulerConfig  # noqa: E402
from shockwave_tpu.solver import get_policy  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
DEFAULT_THROUGHPUTS = os.path.join(REPO, "data", "tacc_throughputs.json")
CANONICAL_TRACE = os.path.join(REPO, "data", "canonical_120job.trace")


def build_scheduler(policy_name, throughputs_path, njobs, chips,
                    vectorized, seed, round_duration):
    throughputs = read_throughputs(throughputs_path)
    jobs, _ = generate_trace(njobs, throughputs, lam=0.0, seed=seed,
                             generate_multi_gpu_jobs=True,
                             generate_dynamic_jobs=True)
    profiles = build_profiles(jobs, throughputs)
    sched = Scheduler(
        get_policy(policy_name, seed=seed), simulate=True,
        throughputs_file=throughputs_path, profiles=profiles,
        config=SchedulerConfig(time_per_iteration=round_duration,
                               seed=seed, vectorized_sim=vectorized))
    for _ in range(chips):
        sched.register_worker("v100", 1)
    for job in jobs:
        sched.add_job(job, timestamp=0.0)
    return sched


def freeze_assignments(assignments):
    return [(repr(job_id), tuple(ids)) for job_id, ids in assignments.items()]


def time_rounds(sched, rounds, obs, path):
    """Per-round wall of `_schedule_jobs_on_workers` after one warmup
    call (the warmup absorbs the one-time allocation LP solve, leaving
    the pure bookkeeping pass under the clock)."""
    sched._schedule_jobs_on_workers()
    walls, frozen = [], []
    for _ in range(rounds):
        t0 = time.perf_counter()
        assignments = sched._schedule_jobs_on_workers()
        dt = time.perf_counter() - t0
        walls.append(dt)
        obs.observe(obs_names.SIM_ROUND_CORE_SECONDS, dt, path=path)
        frozen.append(freeze_assignments(assignments))
    return walls, frozen


def bench_round_pass(policy, throughputs_path, njobs, chips, rounds,
                     seed, round_duration, obs):
    results = {}
    frozen = {}
    for path, vectorized in (("scalar", False), ("vectorized", True)):
        sched = build_scheduler(policy, throughputs_path, njobs, chips,
                                vectorized, seed, round_duration)
        walls, assignments = time_rounds(sched, rounds, obs, path)
        results[path] = statistics.median(walls)
        frozen[path] = assignments
    return {
        "kind": "round_pass",
        "policy": policy,
        "njobs": njobs,
        "chips": chips,
        "rounds": rounds,
        "scalar_ms_per_round": round(results["scalar"] * 1e3, 3),
        "vectorized_ms_per_round": round(results["vectorized"] * 1e3, 3),
        "speedup": round(results["scalar"]
                         / max(results["vectorized"], 1e-9), 2),
        "assignments_equal": frozen["scalar"] == frozen["vectorized"],
    }


def bench_replay(policy, throughputs_path, trace, round_duration, seed):
    """End-to-end replay wall on both paths + metrics-pickle equality
    (no MILP policy here, so the pickles carry no wall telemetry and
    compare byte-for-byte)."""
    throughputs = read_throughputs(throughputs_path)
    out = {"kind": "replay", "policy": policy,
           "trace": os.path.relpath(trace, REPO)}
    pickles = {}
    for path, vectorized in (("scalar", False), ("vectorized", True)):
        jobs, arrivals = parse_trace(trace)
        profiles = build_profiles(jobs, throughputs)
        sched = Scheduler(
            get_policy(policy, seed=seed), simulate=True,
            throughputs_file=throughputs_path, profiles=profiles,
            config=SchedulerConfig(time_per_iteration=round_duration,
                                   seed=seed, vectorized_sim=vectorized))
        t0 = time.perf_counter()
        makespan = sched.simulate({"v100": 32}, arrivals, jobs)
        out[f"{path}_wall_s"] = round(time.perf_counter() - t0, 3)
        pickles[path] = pickle.dumps({
            "makespan": makespan,
            "jct": sched.get_average_jct(),
            "ftf": sched.get_finish_time_fairness(),
            "rounds": sched.rounds.num_completed_rounds,
            "per_round_schedule": sched.rounds.per_round_schedule,
        })
    out["replay_speedup"] = round(
        out["scalar_wall_s"] / max(out["vectorized_wall_s"], 1e-9), 2)
    out["bit_identical"] = pickles["scalar"] == pickles["vectorized"]
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", nargs="*", type=int,
                   default=[120, 900, 2000])
    p.add_argument("--chips", type=int, default=None,
                   help="cluster size (default: 32 for <=120 jobs, "
                        "256 otherwise)")
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", default=DEFAULT_THROUGHPUTS)
    p.add_argument("--trace", default=CANONICAL_TRACE,
                   help="trace for the end-to-end replay phase")
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip_replay", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: small grid, assert bit-identity and "
                        "a speedup floor")
    p.add_argument("--min_speedup", type=float, default=5.0,
                   help="--smoke fails unless the largest round-pass "
                        "grid point reaches this speedup")
    p.add_argument("--metrics_out", default=None, metavar="PROM_TXT")
    args = p.parse_args()

    if args.smoke:
        args.num_jobs = [120, 900]
        args.rounds = min(args.rounds, 10)

    obs = get_observability()
    rows = []
    for njobs in args.num_jobs:
        chips = args.chips or (32 if njobs <= 120 else 256)
        row = bench_round_pass(args.policy, args.throughputs, njobs,
                               chips, args.rounds, args.seed,
                               args.round_duration, obs)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if not args.skip_replay:
        row = bench_replay(args.policy, args.throughputs, args.trace,
                           args.round_duration, args.seed)
        rows.append(row)
        print(json.dumps(row), flush=True)

    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(obs.registry.render_prometheus())

    if args.smoke:
        for row in rows:
            if not row.get("assignments_equal",
                           row.get("bit_identical", False)):
                print("FAIL: scalar/vectorized divergence", file=sys.stderr)
                sys.exit(1)
        top = max((r for r in rows if r["kind"] == "round_pass"),
                  key=lambda r: r["njobs"])
        if top["speedup"] < args.min_speedup:
            print(f"FAIL: round-pass speedup {top['speedup']}x at "
                  f"{top['njobs']} jobs below the {args.min_speedup}x "
                  "floor", file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main()
