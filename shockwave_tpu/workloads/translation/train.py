#!/usr/bin/env python3
"""Transformer / Multi30k translation workload
(trace: "Transformer (batch size N)").

CLI parity with the reference's translation train.py — the trace command
is `python3 train.py -data %s/... -batch_size N -proj_share_weight` with
`-step` appended by the dispatcher.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.models import data
from shockwave_tpu.models.train_common import Trainer, common_parser, parse_args
from shockwave_tpu.models.transformer import Seq2SeqTransformer


def main():
    p = common_parser("Transformer on Multi30k", steps_args=("-step", "--step"))
    p.add_argument("-data", dest="data", default=None)
    p.add_argument("-batch_size", dest="batch_size", type=int, default=64)
    p.add_argument("-proj_share_weight", action="store_true")
    p.add_argument("--use_flash", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="fused pallas attention (default: on for TPU; "
                        "--no-use_flash forces the einsum path)")
    args = parse_args(p)

    use_flash = (jax.default_backend() == "tpu"
                 if args.use_flash is None else args.use_flash)
    model = Seq2SeqTransformer(use_flash=use_flash)
    rng = jax.random.PRNGKey(0)
    src = jnp.zeros((1, 32), jnp.int32)
    variables = model.init(rng, src, src)
    init_state = {"params": variables["params"]}

    def loss_fn(params, state, src_tokens, tgt_tokens):
        logits = model.apply({"params": params}, src_tokens, tgt_tokens[:, :-1])
        targets = tgt_tokens[:, 1:]
        mask = (targets != 0).astype(jnp.float32)
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        loss = (losses * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return loss, {}

    trainer = Trainer(
        args, loss_fn, init_state,
        data.multi30k(args.batch_size, tgt_len=33, data_dir=args.data),
        initial_bs=args.batch_size, max_bs=128, learning_rate=1e-3)
    trainer.run()


if __name__ == "__main__":
    main()
