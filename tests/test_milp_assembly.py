"""Golden equivalence suite for the vectorized MILP assembler.

The historical pure-python loop assembler is the oracle — ONE shared
copy in scripts/microbenchmarks/milp_loop_reference.py (also the
benchmark's `--assembler loop` arm, so the published before/after
numbers come from the same code these tests certify). The vectorized
assembler (milp._ShapeStructure / _InstanceAssembler) must produce
byte-identical (c, A_ub, b_ub, A_eq, b_eq, integrality, ub) on every
instance shape — that is what anchors the canonical 120-job replay's
bit-identity, so these comparisons are exact, not approximate."""
import math
import os
import sys
import time

import numpy as np
import pytest
from scipy import sparse

from shockwave_tpu.shockwave import milp as M

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts", "microbenchmarks"))
from milp_loop_reference import (reference_assemble,  # noqa: E402
                                 reference_rank_model)

BASES6 = [0.0, 0.2, 0.4, 0.6, 0.8, 1.0]
BASES3 = [0.0, 0.5, 1.0]


def synth(njobs, seed, boost_priorities=False, force_neg_cap=False):
    rng = np.random.RandomState(seed)
    data = dict(
        nworkers=[int(rng.choice([1, 1, 1, 2, 4])) for _ in range(njobs)],
        durations=[float(rng.uniform(10, 500)) for _ in range(njobs)],
        dirichlet=[float(rng.uniform(0, 5000)) for _ in range(njobs)],
        epochs=[int(rng.randint(1, 60)) for _ in range(njobs)],
    )
    data["progress"] = [int(rng.randint(0, e + 1)) for e in data["epochs"]]
    data["ftf_caps"] = [float(rng.uniform(1, 8000)) for _ in range(njobs)]
    if force_neg_cap:
        data["ftf_caps"][njobs // 2] = -5.0
    if boost_priorities:
        # Post-normalization relaxation priorities: rank keys spanning
        # the full 1.0 .. 1e6 objective-coefficient range.
        data["priorities"] = [float(rng.uniform(0.5, 1e6))
                              for _ in range(njobs)]
    else:
        data["priorities"] = [1.0] * njobs
    return data


def assert_canonical_equal(name, a, b):
    a = a.copy()
    b = b.copy()
    a.sum_duplicates(); a.sort_indices()
    b.sum_duplicates(); b.sort_indices()
    assert a.shape == b.shape, name
    assert np.array_equal(a.indptr, b.indptr), name
    assert np.array_equal(a.indices, b.indices), name
    assert np.array_equal(a.data, b.data), name


def both_models(njobs, R, bases, data, with_ftf, k=1e-3,
                round_duration=120.0, ngpus=32):
    base_logs = [math.log(1e-6)] + [math.log(b) for b in bases[1:]]
    L = M._Layout(njobs, R, len(bases))
    ref = reference_assemble(
        L, njobs, R, round_duration, ngpus, bases, base_logs,
        data["nworkers"], data["durations"], data["dirichlet"],
        data["progress"], data["epochs"], data["ftf_caps"], k,
        data["priorities"], with_ftf)
    inst = M._InstanceAssembler(
        M._structure_for(njobs, R, len(bases)), bases, base_logs,
        data["nworkers"], data["durations"], data["dirichlet"],
        data["progress"], data["epochs"], data["ftf_caps"],
        round_duration, ngpus, k)
    return ref, inst.model(data["priorities"], with_ftf)


class TestGoldenAssemblyEquivalence:
    """Exact sparse-matrix compare across shapes, both fallback arms."""

    @pytest.mark.parametrize("njobs,R,bases,boost", [
        (1, 5, BASES6, False),        # degenerate single job
        (1, 1, BASES3, False),        # single job, single round
        (7, 20, BASES6, False),
        (40, 20, BASES6, True),       # boosted relaxation priorities
        (13, 8, BASES3, True),
        (3, 4, [0.0, 1.0], False),    # B=2: no adjacency rows at all
        (120, 20, BASES6, True),      # canonical scale
    ])
    @pytest.mark.parametrize("with_ftf", [True, False])
    def test_byte_identical(self, njobs, R, bases, boost, with_ftf):
        data = synth(njobs, seed=njobs * 31 + R, boost_priorities=boost)
        ref, new = both_models(njobs, R, bases, data, with_ftf)
        assert ref is not None and new is not None
        names = ["c", "A_ub", "b_ub", "A_eq", "b_eq", "integrality", "ub"]
        for name, a, b in zip(names, ref, new):
            if sparse.issparse(a):
                assert_canonical_equal(name, a, b)
            else:
                assert np.array_equal(a, b), name

    def test_ftf_infeasible_both_none(self):
        data = synth(9, seed=99, force_neg_cap=True)
        ref, new = both_models(9, 6, BASES6, data, with_ftf=True)
        assert ref is None and new is None
        # The relaxed arm of the same instance must still assemble.
        ref_r, new_r = both_models(9, 6, BASES6, data, with_ftf=False)
        assert ref_r is not None and new_r is not None

    def test_shared_instance_across_arms(self):
        """One assembler serves both arms: the equality block object is
        literally shared, and each arm's inequalities are built once."""
        data = synth(11, seed=5)
        bases = BASES6
        base_logs = [math.log(1e-6)] + [math.log(b) for b in bases[1:]]
        inst = M._InstanceAssembler(
            M._structure_for(11, 10, len(bases)), bases, base_logs,
            data["nworkers"], data["durations"], data["dirichlet"],
            data["progress"], data["epochs"], data["ftf_caps"],
            120.0, 32, 1e-3)
        m_ftf = inst.model([1.0] * 11, True)
        m_rel = inst.model(data["priorities"], False)
        assert m_ftf[3] is m_rel[3]  # A_eq shared, not rebuilt
        assert inst.model([1.0] * 11, False)[1] is m_rel[1]  # A_ub cached

    def test_structure_cache_interleaving(self):
        """LRU-cached shapes must not cross-contaminate when instances
        of different sizes alternate (job count changes between
        re-solves as the trace drains)."""
        for njobs in (4, 9, 4, 9, 4):
            data = synth(njobs, seed=njobs)
            ref, new = both_models(njobs, 6, BASES6, data, True)
            assert_canonical_equal("A_ub", ref[1], new[1])
            assert np.array_equal(ref[2], new[2])


class TestRankModelEquivalence:
    def test_rank_model_byte_identical(self):
        rng = np.random.RandomState(3)
        x = rng.rand(17, 9) > 0.6
        x[3, :] = False  # a zero-count job must contribute zero cost
        prios = [float(rng.uniform(0.1, 1e6)) for _ in range(17)]
        nw = [int(rng.choice([1, 2, 4])) for _ in range(17)]
        ref = reference_rank_model(x, prios, nw, 32)
        new = M._rank_model(x, prios, nw, 32)
        for name, a, b in zip("c A_ub b_ub A_eq b_eq".split(), ref, new):
            if sparse.issparse(a):
                assert_canonical_equal(name, a, b)
            else:
                assert np.array_equal(np.asarray(a, dtype=float),
                                      np.asarray(b, dtype=float)), name


class TestVectorizedRunningAverages:
    def test_matches_scalar_exactly(self):
        rng = np.random.RandomState(0)
        series_list = []
        for _ in range(60):
            length = rng.randint(1, 12)
            rounds = np.cumsum(rng.randint(0, 4, size=length))
            series_list.append(
                [(int(r), float(rng.uniform(100, 9000))) for r in rounds])
        series_list.append([(5, 123.0)])           # single entry
        series_list.append([(0, 1.0), (0, 2.0)])   # all-zero windows
        vec = M.finish_time_momentumed_averages(series_list, 7)
        for i, series in enumerate(series_list):
            ref = M.finish_time_momentumed_average(series, 7)
            assert vec[i] == ref, (i, vec[i], ref)
            # Python floats, so ratio**power overflow still RAISES in
            # _relaxation_priorities instead of yielding numpy inf.
            assert type(vec[i]) is float


class TestExtract:
    def test_matches_per_entry_round(self):
        rng = np.random.RandomState(1)
        njobs, R, B = 6, 5, 3
        L = M._Layout(njobs, R, B)
        xvec = rng.rand(L.n)
        got = M._extract(xvec, L, njobs, R)
        for j in range(njobs):
            for r in range(R):
                assert got[j, r] == (round(xvec[L.x(j, r)]) == 1)


@pytest.mark.slow
class TestAssemblyTimingSanity:
    def test_460_jobs_assembly_beats_loop_oracle(self):
        """Vectorized assembly at 460 jobs must be several times faster
        than the loop oracle in the same process (the acceptance bar is
        5x at 900 jobs via bench_milp_assembly.py; 3x here leaves a
        wide margin against shared-runner noise)."""
        njobs, R, bases = 460, 20, BASES6
        data = synth(njobs, seed=460, boost_priorities=True)
        base_logs = [math.log(1e-6)] + [math.log(b) for b in bases[1:]]
        L = M._Layout(njobs, R, len(bases))

        def run_loop():
            reference_assemble(
                L, njobs, R, 120.0, 128, bases, base_logs,
                data["nworkers"], data["durations"], data["dirichlet"],
                data["progress"], data["epochs"], data["ftf_caps"],
                1e-3, data["priorities"], True)

        def run_vec():
            inst = M._InstanceAssembler(
                M._structure_for(njobs, R, len(bases)), bases, base_logs,
                data["nworkers"], data["durations"], data["dirichlet"],
                data["progress"], data["epochs"], data["ftf_caps"],
                120.0, 128, 1e-3)
            inst.model(data["priorities"], True)

        run_vec()  # warm the structure cache (steady-state behavior)
        loop_s = min(self._timed(run_loop) for _ in range(3))
        vec_s = min(self._timed(run_vec) for _ in range(3))
        assert vec_s * 3 < loop_s, (vec_s, loop_s)

    @staticmethod
    def _timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0
