"""Online what-if control plane: fork fidelity, Monte-Carlo admission
control, knob auto-tuning, and the physical-loopback drive.

The acceptance gates:

- **Fork fidelity** — a twin rolled forward from a mid-run canonical
  capture must be pickle-equal to the uninterrupted simulator
  continuing from the same round (fast subsampled variant here; the
  slow full-canonical variant is marked `slow`).
- **Bit-identity** — a run carrying a default (advisory) plane must be
  byte-identical to a run with no plane at all.
- **Admission control** — on a seeded overload trace the gate must
  strictly improve worst-case FTF over always-admit with serving SLO
  attainment no worse (the committed study's invariant).
- **Physical loopback** — the autoscaler-headroom knob auto-tuned
  end-to-end through the REAL round pipeline (stub daemons), the
  chosen value journaled, and the fork's lock hold-time bounded (this
  suite runs under the conftest lock sanitizer).
"""
import json
import os
import pickle
import socket
import subprocess
import sys
import threading
import time

import pytest

from shockwave_tpu.core.job import Job
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.core.trace import parse_trace, serving_command
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy
from shockwave_tpu.whatif import fork
from shockwave_tpu.whatif.knobs import get_knob
from shockwave_tpu.whatif.plane import WhatIfConfig

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.abspath(os.path.join(TESTS_DIR, ".."))
DATA = os.path.join(REPO, "data")
TRACE = os.path.join(DATA, "canonical_120job.trace")
SERVING_TRACE = os.path.join(DATA, "serving_mixed.trace")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")
STUDY = os.path.join(REPO, "scripts", "drivers",
                     "whatif_overload_study.py")
SWEEP = os.path.join(REPO, "scripts", "drivers", "sweep_scenarios.py")


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def build_sched(policy="max_min_fairness", trace=TRACE, max_jobs=None,
                whatif=None, config=None, max_rounds=None, seed=0,
                num_chips=16):
    jobs, arrivals = parse_trace(trace)
    if max_jobs is not None:
        jobs, arrivals = jobs[:max_jobs], arrivals[:max_jobs]
    profiles = build_profiles(jobs, read_throughputs(THROUGHPUTS))
    shockwave_config = serving_config = None
    if config is not None:
        with open(config) as f:
            shockwave_config = json.load(f)
        serving_config = shockwave_config.pop("serving", None)
        if policy != "shockwave":
            shockwave_config = None
    elif policy == "shockwave":
        shockwave_config = {}
    if shockwave_config is not None:
        shockwave_config["num_gpus"] = num_chips
        shockwave_config["time_per_iteration"] = 120.0
    sched = Scheduler(
        get_policy(policy, seed=seed), simulate=True,
        throughputs_file=THROUGHPUTS, profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=120.0, seed=seed, max_rounds=max_rounds,
            shockwave=shockwave_config, serving=serving_config,
            whatif=whatif))
    return sched, jobs, arrivals, num_chips


def result_bundle(sched):
    """The replay-identity bundle. Solve stats are compared as JSON:
    values must match exactly, but cross-entry float-object SHARING
    differs after a restore's pickle round trip, which changes
    pickle.dumps bytes without any value differing."""
    solve = [{k: v for k, v in s.items()
              if k not in ("wall_s", "assembly_s")}
             for s in sched.get_solve_stats()]
    return {
        "makespan": sched.get_current_timestamp(),
        "jct": sched.get_average_jct(),
        "ftf": sched.get_finish_time_fairness(),
        "util": sched.get_cluster_utilization(),
        "rounds": sched.rounds.num_completed_rounds,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "timelines": sched._job_timelines,
        "solve_json": json.dumps(solve, sort_keys=True),
        "serving": sched.serving_summary(),
    }


class TestForkFidelity:
    """A twin thawed from a mid-run capture and rolled to completion
    must land on the exact state of the uninterrupted run."""

    def _run_pair(self, policy, trace, config, max_jobs, capture_round,
                  max_rounds=None):
        a, jobs, arrivals, chips = build_sched(
            policy, trace=trace, max_jobs=max_jobs, config=config,
            max_rounds=max_rounds)
        a.simulate({"v100": chips}, arrivals, jobs)
        bundle_a = result_bundle(a)

        b, jobs2, arrivals2, chips = build_sched(
            policy, trace=trace, max_jobs=max_jobs, config=config,
            max_rounds=max_rounds,
            whatif={"capture_at_round": capture_round})
        b.simulate({"v100": chips}, arrivals2, jobs2)
        # Bit-identity: the capture-only plane must not perturb the run.
        assert pickle.dumps(result_bundle(b)) == pickle.dumps(bundle_a)
        assert b._whatif.captured is not None

        blob, queued, remaining = b._whatif.captured
        twin = fork.thaw(b, blob)
        twin._config.max_rounds = max_rounds
        fork.rollforward(twin, queued=queued, remaining_jobs=remaining)
        bundle_t = result_bundle(twin)
        for key in bundle_a:
            assert pickle.dumps(bundle_t[key]) == \
                pickle.dumps(bundle_a[key]), key

    def test_subsampled_canonical(self):
        self._run_pair("max_min_fairness", TRACE, None, 25, 30)

    def test_subsampled_shockwave(self):
        self._run_pair("shockwave", TRACE,
                       os.path.join(REPO, "configs", "tacc_32gpus.json"),
                       20, 25, max_rounds=120)

    def test_serving_mixed(self):
        self._run_pair("max_min_fairness", SERVING_TRACE,
                       os.path.join(REPO, "configs", "serving_mixed.json"),
                       None, 20, max_rounds=120)

    @pytest.mark.slow
    def test_full_canonical(self):
        """Full 120-job canonical trace, max_min_fairness. The
        shockwave variant is pinned at subsampled scale above instead:
        the full canonical instance drives HiGHS into its WALL-CLOCK
        solve budget, where two identical runs can report mip_gaps a
        few ulps apart and diverge — verified to reproduce with the
        plane absent entirely, i.e. solver wall-sensitivity, not a
        fork artifact."""
        self._run_pair("max_min_fairness", TRACE, None, None, 60,
                       max_rounds=None)

    def test_plane_absent_by_default(self):
        sched, _, _, _ = build_sched(max_jobs=2)
        assert sched._whatif is None


class TestWhatIfConfig:
    def test_unknown_keys_refused(self):
        with pytest.raises(ValueError, match="unknown what-if"):
            WhatIfConfig.from_dict({"not_a_knob": 1})

    def test_bad_admission_mode_refused(self):
        with pytest.raises(ValueError, match="admission"):
            WhatIfConfig.from_dict({"admission": "maybe"})

    def test_defaults_always_admit(self):
        assert WhatIfConfig.from_dict(None).admission == "always_admit"


class TestKnobs:
    def test_unknown_knob_refused(self):
        with pytest.raises(ValueError, match="unknown what-if knob"):
            get_knob("frobnicator")

    def test_headroom_knob_roundtrip(self):
        sched, jobs, arrivals, chips = build_sched(
            trace=SERVING_TRACE,
            config=os.path.join(REPO, "configs", "serving_mixed.json"),
            max_rounds=10)
        sched.simulate({"v100": chips}, arrivals, jobs)
        knob = get_knob("autoscaler_headroom")
        assert knob.applicable(sched)
        before = knob.get(sched)
        knob.set(sched, before * 2)
        assert knob.get(sched) == before * 2
        with pytest.raises(ValueError):
            sched._serving_tier.set_headroom(0.0)

    def test_tuned_knob_survives_snapshot_restore(self):
        """Tuned values must ride the SNAPSHOT, not just the journal:
        compaction deletes whatif_knob events behind the snapshot
        horizon, and knobs like the solver budget live outside the
        snapshot field lists."""
        import pickle as _pickle
        sched, jobs, arrivals, chips = build_sched(
            trace=SERVING_TRACE,
            config=os.path.join(REPO, "configs", "serving_mixed.json"),
            max_rounds=10)
        sched.simulate({"v100": chips}, arrivals, jobs)
        sched._emit_whatif_knob("autoscaler_headroom", 2.5, 9, [])
        state = _pickle.loads(_pickle.dumps(sched.snapshot_state()))
        fresh, _, _, _ = build_sched(
            trace=SERVING_TRACE,
            config=os.path.join(REPO, "configs", "serving_mixed.json"))
        fresh.restore_state(state)
        assert fresh._whatif_knob_values == {"autoscaler_headroom": 2.5}
        assert fresh._serving_tier.autoscaler_config.headroom == 2.5

    def test_quarantine_backoff_clamped(self):
        from shockwave_tpu.runtime.resilience import HealthConfig
        cfg = HealthConfig()
        assert cfg.with_quarantine_backoff(60.0).quarantine_backoff_s == 60.0
        clamped = cfg.with_quarantine_backoff(1e9)
        assert clamped.quarantine_backoff_s == cfg.quarantine_backoff_max_s
        with pytest.raises(ValueError):
            cfg.with_quarantine_backoff(0.0)


class TestAdmissionGate:
    """The committed overload study's invariant, at smoke scale."""

    def _study(self, out, extra=()):
        from conftest import cpu_subprocess_env
        res = subprocess.run(
            [sys.executable, STUDY, "--trace", SERVING_TRACE,
             "--throughputs", THROUGHPUTS, "--cluster_spec", "v100:8",
             "--round_duration", "120", "--num_jobs", "12",
             "--load_scale", "6", "--out", out, *extra],
            capture_output=True, text=True, cwd=REPO, timeout=600,
            env=cpu_subprocess_env())
        assert res.returncode == 0, res.stderr[-2000:]
        return json.loads(res.stdout.strip().splitlines()[-1])

    def test_gate_improves_worst_case_ftf(self, tmp_path):
        out = str(tmp_path / "study.json")
        summary = self._study(out, extra=("--check",))
        assert summary["improved"]
        doc = json.load(open(out))
        imp = doc["improvement"]
        assert imp["worst_ftf_gate"] < imp["worst_ftf_always"]
        assert imp["all_jobs_completed"]
        assert imp.get("serving_no_worse", True)
        # The decision log is the committed evidence: deferrals with
        # their with/without scores.
        deferred = [d for d in doc["gate"]["decision_log"]
                    if d["decision"] == "defer"]
        assert deferred and all("scores" in d for d in deferred)

    def test_study_byte_reproducible(self, tmp_path):
        a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        self._study(a)
        self._study(b)
        assert open(a, "rb").read() == open(b, "rb").read()

    def test_deferral_preserves_profile_lookup(self):
        """Deferral reorders admission; ids then diverge from trace
        positions and the profile lookup must follow the remap."""
        whatif = {"admission": "gate", "admission_rho_limit": 0.9,
                  "admission_horizon_rounds": 30,
                  "admission_max_defers": 12}
        sched, jobs, arrivals, _ = build_sched(
            trace=SERVING_TRACE, max_jobs=12, whatif=whatif,
            config=os.path.join(REPO, "configs", "serving_mixed.json"))
        arrivals = [a / 6.0 for a in arrivals]
        sched.simulate({"v100": 8}, arrivals, jobs)
        assert sched._profile_map, "expected deferral to remap ids"
        for int_id, position in sched._profile_map.items():
            assert sched._profile_for(int_id) is sched._profiles[position]
        # Every completed training job (a completion-times entry that is
        # not a serving replica) resolves a real profile — no job lost
        # its FTF row to the reordering, and no serving line aliased a
        # training profile.
        static, _ = sched.get_finish_time_fairness()
        completed_training = [
            j for j in sched.acct.completion_times
            if j not in sched._serving_job_ids]
        assert len(static) == len(completed_training)
        for j in completed_training:
            assert sched._profile_for(j.integer_job_id()) is not None


class TestSweepFromState:
    def test_checkpoint_seeded_sweep_byte_equal(self, tmp_path):
        from conftest import cpu_subprocess_env
        sched, jobs, arrivals, chips = build_sched(max_jobs=20)
        ckpt = str(tmp_path / "ckpt.pkl")
        sched.simulate({"v100": chips}, arrivals, jobs,
                       checkpoint_file=ckpt, checkpoint_threshold=0.4)
        outs = []
        for name, procs in (("a.json", 1), ("b.json", 2)):
            out = str(tmp_path / name)
            res = subprocess.run(
                [sys.executable, SWEEP, "--trace", TRACE,
                 "--policy", "max_min_fairness",
                 "--throughputs", THROUGHPUTS,
                 "--cluster_spec", "v100:16", "--round_duration", "120",
                 "--num_scenarios", "3", "--fault_rate", "1",
                 "--processes", str(procs),
                 "--from_state", ckpt, "--out", out],
                capture_output=True, text=True, cwd=REPO, timeout=600,
                env=cpu_subprocess_env())
            assert res.returncode == 0, res.stderr[-2000:]
            outs.append(out)
        assert open(outs[0], "rb").read() == open(outs[1], "rb").read()
        doc = json.load(open(outs[0]))
        assert doc["aggregate"]["num_ok"] == 3
        assert doc["meta"]["from_state"] == ckpt
        for record in doc["scenarios"].values():
            assert record["params"]["from_round"] > 0

    def test_trace_zero_knobs_refused(self, tmp_path):
        from conftest import cpu_subprocess_env
        res = subprocess.run(
            [sys.executable, SWEEP, "--trace", TRACE,
             "--policy", "max_min_fairness",
             "--throughputs", THROUGHPUTS, "--num_scenarios", "2",
             "--from_state", str(tmp_path / "nope"),
             "--subsample", "0.2:0.4",
             "--out", str(tmp_path / "out.json")],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env=cpu_subprocess_env())
        assert res.returncode != 0
        assert "incompatible" in res.stderr


class TestChaosTwinSchedules:
    def test_twin_shadow_campaign_clean(self, tmp_path):
        from conftest import cpu_subprocess_env
        out = str(tmp_path / "chaos.json")
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "drivers",
                          "chaos_campaign.py"),
             "--trace", TRACE, "--policy", "max_min_fairness",
             "--throughputs", THROUGHPUTS, "--cluster_spec", "v100:8",
             "--round_duration", "120", "--num_schedules", "0",
             "--twin_schedules", "2", "--out", out],
            capture_output=True, text=True, cwd=REPO, timeout=600,
            env=cpu_subprocess_env())
        assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
        doc = json.load(open(out))
        assert doc["summary"]["passed"] == 2
        for record in doc["twin"].values():
            assert record["invariants"]["live_untouched"]


# ---------------------------------------------------------------------------
# Physical loopback: headroom auto-tuned end-to-end + fork-cost bound
# ---------------------------------------------------------------------------

class _StubHost:
    """One stub worker host (same shape as test_health's)."""

    def __init__(self, sched_port, num_chips=1, throughput=100.0,
                 execution_time=0.2):
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self._iter_client = IteratorToSchedulerClient
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.port = free_port()
        self.server = serve_worker(self.port, {
            "RunJob": self._run_job, "KillJob": lambda j: None,
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", self.port, num_chips)

    def _run_job(self, jobs, worker_id, round_id):
        def execute():
            max_steps = 10**9
            for j in jobs:
                it = self._iter_client(j["job_id"], worker_id,
                                       "localhost", self.sched_port)
                max_steps, _, _ = it.init()
            time.sleep(self.execution_time)
            steps = [min(int(self.throughput * self.round_duration),
                         j["num_steps"], int(max_steps)) for j in jobs]
            self._client.notify_done([j["job_id"] for j in jobs],
                                     worker_id, steps,
                                     [self.execution_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    def stop(self):
        self.server.stop(grace=0)


def _training_job(total_steps=600):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=10000)


def _serving_job(lifetime_s=40.0):
    command = serving_command(
        base_rps=10.0, peak_rps=10.0, period_s=0.0,
        tokens_per_request=64, decode_tokens_per_s=1600.0,
        max_replicas=2)
    return Job(None, "Serving (batch size 1)", command, "serving",
               "--num_steps", total_steps=0, duration=lifetime_s,
               scale_factor=1, mode="serving", SLO=0.5)


@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestPhysicalWhatIfLoopback:
    """Acceptance drive: the REAL round pipeline with an over-provisioned
    autoscaler headroom (3.0 — two chips of two reserved for serving at
    10 req/s against a 25 req/s replica). The what-if plane must sweep
    the knob on twin rollouts, commit a smaller headroom, journal the
    decision, and keep the fork's lock hold-time bounded (the suite
    runs under the conftest lock sanitizer)."""

    def test_headroom_tuned_and_fork_bounded(self, tmp_path):
        from shockwave_tpu.sched import journal as journal_mod
        from shockwave_tpu.sched.physical import PhysicalScheduler
        state_dir = str(tmp_path / "state")
        sched_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(
                time_per_iteration=2.0, heartbeat_interval_s=0.5,
                worker_timeout_s=5.0, first_init_grace_s=0.0,
                state_dir=state_dir, snapshot_interval_rounds=5,
                serving={"headroom": 3.0},
                whatif={"tune_knob": "autoscaler_headroom",
                        "tune_interval_rounds": 2,
                        "tune_horizon_rounds": 6,
                        "tune_candidates": [1.15, 3.0],
                        "forecast_interval_rounds": 5,
                        "forecast_samples": 2,
                        "forecast_horizon_rounds": 6,
                        "shadow_chaos": True}),
            expected_num_workers=2, port=sched_port)
        hosts = [_StubHost(sched_port), _StubHost(sched_port)]
        try:
            sched.add_job(_serving_job(lifetime_s=40.0))
            for _ in range(2):
                sched.add_job(_training_job(600))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()

            deadline = time.time() + 60
            committed = None
            while time.time() < deadline:
                with sched._lock:
                    if any(rec["changed"]
                           for rec in sched._whatif.knob_log):
                        committed = [rec for rec in sched._whatif.knob_log
                                     if rec["changed"]][-1]
                        break
                time.sleep(0.2)
            assert committed is not None, (
                f"headroom was never retuned: {sched._whatif.knob_log}")
            assert committed["knob"] == "autoscaler_headroom"
            assert committed["chosen"] < committed["previous"], committed
            with sched._lock:
                assert (sched._serving_tier.autoscaler_config.headroom
                        == committed["chosen"])
                # Sweep evidence: every candidate scored.
                assert {e["value"] for e in committed["sweep"]} >= {
                    1.15, 3.0}

            # Fork-cost satellite: the state copy under the scheduler
            # lock must be bounded and recorded in both the dedicated
            # histogram and the round-phase histogram.
            assert sched._whatif.max_fork_s < 1.0, sched._whatif.max_fork_s
            reg = sched._obs.registry
            count, _ = reg.histogram_stats(obs_names.WHATIF_FORK_SECONDS)
            assert count >= 1
            count, _ = reg.histogram_stats(obs_names.ROUND_PHASE_SECONDS,
                                           phase=obs_names.SPAN_WHATIF_FORK)
            assert count >= 1
            assert reg.value(obs_names.WHATIF_ROLLOUTS_TOTAL,
                             purpose="tune") >= 2

            # Low-rate shadow chaos against the twin in physical
            # loopback: probes ran and none violated the
            # zero-failure-charge invariant.
            deadline = time.time() + 30
            while time.time() < deadline:
                with sched._lock:
                    if sched._whatif.shadow_log:
                        break
                time.sleep(0.2)
            with sched._lock:
                assert sched._whatif.shadow_log, "no shadow chaos probes"
                assert all(r["outcome"] == "ok"
                           for r in sched._whatif.shadow_log), (
                    sched._whatif.shadow_log)
            assert reg.value(obs_names.WHATIF_SHADOW_CHAOS_TOTAL,
                             outcome="violation") == 0
        finally:
            sched._done_event.set()
            for host in hosts:
                host.stop()
            sched._server.stop(grace=0)
            if sched._durability is not None:
                sched._durability.close()

        # The chosen value is durable: the journal carries the
        # whatif_knob event with its sweep evidence.
        recovered = journal_mod.load_state(state_dir)
        knob_events = [e for e in recovered.events
                       if e.get("type") == "whatif_knob"]
        snapshot_ok = recovered.snapshot is not None
        assert knob_events or snapshot_ok, "knob commit never journaled"
        if knob_events:
            data = knob_events[-1]["data"]
            assert data["knob"] == "autoscaler_headroom"
            assert data["sweep"]
