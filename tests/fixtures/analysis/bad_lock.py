"""lock-discipline negative fixture: one seeded violation.

`poke_unlocked` reads a protected field with no lock held (line marked
SEEDED below); every other method demonstrates the sanctioned shapes
(with-block, @requires_lock, __init__) and must NOT be reported.
"""
import threading

from shockwave_tpu.core.locking import requires_lock


class BrokenScheduler:
    _LOCK_PROTECTED = frozenset({"state"})

    def __init__(self):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self.state = {}  # constructor: exempt

    def poke_unlocked(self):
        return self.state.get("x")  # SEEDED VIOLATION

    def poke_locked(self):
        with self._lock:
            return self.state.get("x")

    def poke_cv(self):
        with self._cv:
            self.state["x"] = 1

    @requires_lock
    def poke_annotated(self):
        return len(self.state)
