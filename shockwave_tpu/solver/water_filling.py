"""Max-min fairness via water filling.

Iteratively raises the minimum normalized effective throughput: solve the
max-min LP, detect saturated jobs (those that cannot rise above the
current water level), freeze them, and repeat with the rest. This yields
the lexicographically max-min allocation the reference computes with a
parameterized LP + MILP pair (reference:
scheduler/policies/max_min_fairness_water_filling.py); here saturation is
detected with per-job probe LPs, which is equivalent and solver-free.

Supports entity-based priority reweighting ("fairness" and "fifo"
policies) for multi-entity clusters.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .lp import LinearProgram
from .policy import Policy
from .simple import ProportionalPolicy

_EPS = 1e-5


class WaterFillingAlgorithm:
    def __init__(self, priority_reweighting_policies=None):
        self._priority_reweighting_policies = priority_reweighting_policies

    def _reweight(self, entity_weights, priority_weights, entity_to_job_mapping,
                  saturated, job_ids):
        """Redistribute entity weights over that entity's unsaturated jobs."""
        if self._priority_reweighting_policies is None:
            return priority_weights
        out = {}
        for entity_id, entity_jobs in entity_to_job_mapping.items():
            policy = self._priority_reweighting_policies[entity_id]
            weight = entity_weights[entity_id]
            if policy == "fairness":
                active = [j for j in entity_jobs if j not in saturated]
                total = sum(float(priority_weights[j]) for j in active)
                for j in entity_jobs:
                    out[j] = 0.0 if j in saturated else (
                        weight * float(priority_weights[j]) / total)
            elif policy == "fifo":
                entity_jobs = sorted(entity_jobs)
                granted = False
                for j in entity_jobs:
                    if j in saturated or granted:
                        out[j] = 0.0
                    else:
                        out[j] = weight
                        granted = True
            else:
                raise ValueError(f"unknown priority reweighting policy {policy!r}")
        return out

    def _solve_level(self, coeff, sf, num_workers, weights, saturated_levels, m, n,
                     objective_job=None):
        """Max water level t (or one job's throughput) s.t. frozen jobs keep
        their levels and unsaturated jobs get >= w_i * t."""
        lp = LinearProgram(m * n + 1)
        t = m * n
        lp.bounds[t] = (None, None)
        for i in range(m):
            row = lp.row()
            row[i * n:(i + 1) * n] = -coeff[i]
            if i in saturated_levels:
                lp.add_le(row, -saturated_levels[i])
            elif weights[i] > 0:
                row[t] = weights[i]
                lp.add_le(row, 0.0)
        for row, rhs in zip(*Policy.cluster_capacity_rows(m, n, sf, num_workers, 1)):
            lp.add_le(row, rhs)
        for row, rhs in zip(*Policy.job_time_rows(m, n, 1)):
            lp.add_le(row, rhs)
        c = np.zeros(m * n + 1)
        if objective_job is None:
            c[t] = -1.0
        else:
            c[objective_job * n:(objective_job + 1) * n] = -coeff[objective_job]
        res = lp.minimize(c).solve()
        return res

    def run(self, coeff, sf, num_workers, priority_weights, m, n,
            entity_weights=None, entity_to_job_mapping=None, job_ids=None):
        """coeff[i, j]: normalized effective throughput per unit time share."""
        saturated_levels: Dict[int, float] = {}
        saturated_ids = set()
        x = None
        for _ in range(m):
            if len(saturated_levels) == m:
                break
            if entity_to_job_mapping is not None:
                pw = self._reweight(entity_weights, priority_weights,
                                    entity_to_job_mapping, saturated_ids, job_ids)
                weights = np.array([float(pw[job_ids[i]]) for i in range(m)])
            else:
                weights = np.array([
                    0.0 if i in saturated_levels else float(priority_weights[job_ids[i]])
                    for i in range(m)])
            if weights.sum() <= 0:
                break
            res = self._solve_level(coeff, sf, num_workers, weights,
                                    saturated_levels, m, n)
            if not res.success:
                break
            level = -res.fun
            x = res.x[:m * n].reshape((m, n))
            # Probe each unsaturated job: can it exceed its waterline?
            newly = []
            for i in range(m):
                if i in saturated_levels or weights[i] <= 0:
                    continue
                trial = dict(saturated_levels)
                for k in range(m):
                    if k != i and k not in trial and weights[k] > 0:
                        trial[k] = level * weights[k]
                probe = self._solve_level(coeff, sf, num_workers, weights, trial,
                                          m, n, objective_job=i)
                best = -probe.fun if probe.success else level * weights[i]
                if best <= level * weights[i] * (1 + _EPS) + _EPS:
                    newly.append((i, level * weights[i]))
            if not newly:
                # Numerical fallback: freeze the argmin to guarantee progress.
                rates = (coeff * x).sum(axis=1)
                active = [i for i in range(m) if i not in saturated_levels
                          and weights[i] > 0]
                i = min(active, key=lambda k: rates[k] / weights[k])
                newly = [(i, level * weights[i])]
            for i, lvl in newly:
                saturated_levels[i] = lvl
                if job_ids is not None:
                    saturated_ids.add(job_ids[i])
        return x


class MaxMinFairnessWaterFillingPolicyWithPerf(Policy):
    name = "MaxMinFairnessWaterFilling_Perf"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._algorithm = WaterFillingAlgorithm(priority_reweighting_policies)
        self._proportional = ProportionalPolicy()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, cluster_spec,
                       entity_weights=None, entity_to_job_mapping=None,
                       verbose=False, return_effective_throughputs=False):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        m, n = throughputs.shape
        job_ids, worker_types = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        proportional = self._proportional.get_throughputs(throughputs, index,
                                                          cluster_spec)
        coeff = throughputs * sf / proportional.reshape((m, 1))
        x = self._algorithm.run(
            coeff, sf, self._num_workers, unflattened_priority_weights, m, n,
            entity_weights=entity_weights,
            entity_to_job_mapping=entity_to_job_mapping, job_ids=job_ids)
        if x is None:
            return None
        return self.unflatten(x.clip(0.0, 1.0), index)


class MaxMinFairnessWaterFillingPolicy(Policy):
    """Throughput-agnostic water filling (all throughputs forced to 1)."""

    name = "MaxMinFairnessWaterFilling"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._perf = MaxMinFairnessWaterFillingPolicyWithPerf(
            priority_reweighting_policies)

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       priority_weights, cluster_spec, **kwargs):
        ones = {
            job_id: {wt: 1.0 for wt in per_wt}
            for job_id, per_wt in unflattened_throughputs.items()
        }
        if not ones:
            return None
        return self._perf.get_allocation(ones, scale_factors, priority_weights,
                                         cluster_spec, **kwargs)
