"""Autoencoder recommender (ML-20M-class workloads).

Capability parity with the reference's Recoder autoencoder
(workloads/pytorch/recommendation/recoder/model.py): a sparse user
interaction row in, reconstruction scores out, multinomial log-likelihood
loss. Dense bf16 matmuls; the sparse input is materialized as a dense
multi-hot row per example (the TPU-friendly layout).
"""
from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class AutoEncoder(nn.Module):
    num_items: int = 20108  # ml-20m items after preprocessing
    hidden_dims: Sequence[int] = (200,)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, interactions, train: bool = True):
        """interactions: (batch, num_items) multi-hot float -> scores."""
        x = nn.LayerNorm(dtype=jnp.float32)(interactions)
        x = x.astype(self.dtype)
        for i, dim in enumerate(self.hidden_dims):
            x = nn.Dense(dim, dtype=self.dtype, name=f"enc_{i}")(x)
            x = nn.tanh(x)
        for i, dim in enumerate(reversed(self.hidden_dims[:-1])):
            x = nn.Dense(dim, dtype=self.dtype, name=f"dec_{i}")(x)
            x = nn.tanh(x)
        return nn.Dense(self.num_items, dtype=jnp.float32, name="out")(x)


def multinomial_nll(logits, targets):
    """Multinomial negative log-likelihood over interaction rows."""
    log_softmax = nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(log_softmax * targets, axis=-1))
