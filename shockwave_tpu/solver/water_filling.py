"""Max-min fairness via water filling.

Iteratively raises the minimum normalized effective throughput: solve the
max-min LP, detect saturated jobs (those that cannot rise above the
current water level), freeze them, and repeat with the rest. This yields
the lexicographically max-min allocation the reference computes with a
parameterized LP + MILP pair (reference:
scheduler/policies/max_min_fairness_water_filling.py); here saturation is
detected with per-job probe LPs, which is equivalent and solver-free.

The algorithm is expressed over generic effective-throughput rows
E[i] . x so the same code serves both the per-job ("perf") variant and
the packing variant, where x ranges over job *combinations* and a single
job's effective throughput sums over every combination containing it
(reference: max_min_fairness_water_filling.py:569-706).

Supports entity-based priority reweighting ("fairness" and "fifo"
policies) for multi-entity clusters.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from .lp import LinearProgram
from .policy import Policy, PolicyWithPacking
from .simple import ProportionalPolicy

_EPS = 1e-5


class WaterFillingAlgorithm:
    def __init__(self, priority_reweighting_policies=None):
        self._priority_reweighting_policies = priority_reweighting_policies

    def _reweight(self, entity_weights, priority_weights, entity_to_job_mapping,
                  saturated, job_ids):
        """Redistribute entity weights over that entity's unsaturated jobs."""
        if self._priority_reweighting_policies is None:
            return priority_weights
        out = {}
        for entity_id, entity_jobs in entity_to_job_mapping.items():
            policy = self._priority_reweighting_policies[entity_id]
            weight = entity_weights[entity_id]
            if policy == "fairness":
                active = [j for j in entity_jobs if j not in saturated]
                total = sum(float(priority_weights[j]) for j in active)
                for j in entity_jobs:
                    out[j] = 0.0 if j in saturated else (
                        weight * float(priority_weights[j]) / total)
            elif policy == "fifo":
                entity_jobs = sorted(entity_jobs)
                granted = False
                for j in entity_jobs:
                    if j in saturated or granted:
                        out[j] = 0.0
                    else:
                        out[j] = weight
                        granted = True
            else:
                raise ValueError(f"unknown priority reweighting policy {policy!r}")
        return out

    def _solve_level(self, E, weights, saturated_levels, shared_rows, num_x,
                     fixed_vars, objective_job=None):
        """Max water level t (or one job's throughput) s.t. frozen jobs keep
        their levels and unsaturated jobs get >= w_i * t.

        E: (num_levels, num_x) effective-throughput rows; shared_rows:
        prebuilt (row, rhs) <= constraints over x (capacity + time);
        fixed_vars: variable indices pinned to 0 (e.g. mismatched-scale
        combos in the packing variant)."""
        num_levels = E.shape[0]
        lp = LinearProgram(num_x + 1)
        t = num_x
        lp.bounds[t] = (None, None)
        for v in fixed_vars:
            lp.bounds[v] = (0, 0)
        for i in range(num_levels):
            row = lp.row()
            row[:num_x] = -E[i]
            if i in saturated_levels:
                lp.add_le(row, -saturated_levels[i])
            elif weights[i] > 0:
                row[t] = weights[i]
                lp.add_le(row, 0.0)
        for row, rhs in shared_rows:
            lp.add_le(row, rhs)  # rows are built with one extra var for t
        c = np.zeros(num_x + 1)
        if objective_job is None:
            c[t] = -1.0
        else:
            c[:num_x] = -E[objective_job]
        res = lp.minimize(c).solve()
        return res

    def run(self, E, shared_rows, priority_weights, num_x,
            entity_weights=None, entity_to_job_mapping=None, job_ids=None,
            fixed_vars=()):
        """E[i] . x is level-job i's normalized effective throughput."""
        num_levels = E.shape[0]
        saturated_levels: Dict[int, float] = {}
        saturated_ids = set()
        x = None
        for _ in range(num_levels):
            if len(saturated_levels) == num_levels:
                break
            if entity_to_job_mapping is not None:
                pw = self._reweight(entity_weights, priority_weights,
                                    entity_to_job_mapping, saturated_ids, job_ids)
                weights = np.array([float(pw[job_ids[i]])
                                    for i in range(num_levels)])
            else:
                weights = np.array([
                    0.0 if i in saturated_levels
                    else float(priority_weights[job_ids[i]])
                    for i in range(num_levels)])
            if weights.sum() <= 0:
                break
            res = self._solve_level(E, weights, saturated_levels, shared_rows,
                                    num_x, fixed_vars)
            if not res.success:
                break
            level = -res.fun
            x = res.x[:num_x]
            # Probe each unsaturated job: can it exceed its waterline?
            newly = []
            for i in range(num_levels):
                if i in saturated_levels or weights[i] <= 0:
                    continue
                trial = dict(saturated_levels)
                for k in range(num_levels):
                    if k != i and k not in trial and weights[k] > 0:
                        trial[k] = level * weights[k]
                probe = self._solve_level(E, weights, trial, shared_rows,
                                          num_x, fixed_vars, objective_job=i)
                best = -probe.fun if probe.success else level * weights[i]
                if best <= level * weights[i] * (1 + _EPS) + _EPS:
                    newly.append((i, level * weights[i]))
            if not newly:
                # Numerical fallback: freeze the argmin to guarantee progress.
                rates = E @ x
                active = [i for i in range(num_levels)
                          if i not in saturated_levels and weights[i] > 0]
                i = min(active, key=lambda k: rates[k] / weights[k])
                newly = [(i, level * weights[i])]
            for i, lvl in newly:
                saturated_levels[i] = lvl
                if job_ids is not None:
                    saturated_ids.add(job_ids[i])
        return x


class MaxMinFairnessWaterFillingPolicyWithPerf(Policy):
    name = "MaxMinFairnessWaterFilling_Perf"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._algorithm = WaterFillingAlgorithm(priority_reweighting_policies)
        self._proportional = ProportionalPolicy()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, cluster_spec,
                       entity_weights=None, entity_to_job_mapping=None,
                       verbose=False, return_effective_throughputs=False):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        m, n = throughputs.shape
        job_ids, worker_types = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        proportional = self._proportional.get_throughputs(throughputs, index,
                                                          cluster_spec)
        coeff = throughputs * sf / proportional.reshape((m, 1))
        E = np.zeros((m, m * n))
        for i in range(m):
            E[i, i * n:(i + 1) * n] = coeff[i]
        shared_rows = list(zip(*Policy.cluster_capacity_rows(
            m, n, sf, self._num_workers, 1)))
        shared_rows += list(zip(*Policy.job_time_rows(m, n, 1)))
        x = self._algorithm.run(
            E, shared_rows, unflattened_priority_weights, m * n,
            entity_weights=entity_weights,
            entity_to_job_mapping=entity_to_job_mapping, job_ids=job_ids)
        if x is None:
            return None
        return self.unflatten(x.reshape((m, n)).clip(0.0, 1.0), index)


class MaxMinFairnessWaterFillingPolicy(Policy):
    """Throughput-agnostic water filling (all throughputs forced to 1)."""

    name = "MaxMinFairnessWaterFilling"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._perf = MaxMinFairnessWaterFillingPolicyWithPerf(
            priority_reweighting_policies)

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       priority_weights, cluster_spec, **kwargs):
        ones = {
            job_id: {wt: 1.0 for wt in per_wt}
            for job_id, per_wt in unflattened_throughputs.items()
        }
        if not ones:
            return None
        return self._perf.get_allocation(ones, scale_factors, priority_weights,
                                         cluster_spec, **kwargs)


class MaxMinFairnessWaterFillingPolicyWithPacking(PolicyWithPacking):
    """Water filling over job combinations: x ranges over (combo, worker
    type) shares; a single job's level is the sum of its normalized
    throughput inside every combination that contains it (reference:
    max_min_fairness_water_filling.py:569-706)."""

    name = "MaxMinFairnessWaterFilling_Packing"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._algorithm = WaterFillingAlgorithm(priority_reweighting_policies)
        self._proportional = ProportionalPolicy()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, cluster_spec,
                       entity_weights=None, entity_to_job_mapping=None,
                       verbose=False, return_effective_throughputs=False):
        tensor, index = self.flatten(unflattened_throughputs, cluster_spec)
        if tensor is None or len(tensor) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        num_singles, m, n = tensor.shape
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        E, fixed = self.normalized_effective_rows(
            tensor, index, sf, unflattened_throughputs, cluster_spec,
            self._proportional)
        shared_rows = list(zip(*self.cluster_capacity_rows(
            m, n, sf, self._num_workers, 1)))
        shared_rows += list(zip(*self.per_job_time_rows(
            job_ids, single_job_ids, relevant, n, 1)))
        x = self._algorithm.run(
            E, shared_rows, unflattened_priority_weights, m * n,
            entity_weights=entity_weights,
            entity_to_job_mapping=entity_to_job_mapping,
            job_ids=single_job_ids, fixed_vars=fixed)
        if x is None:
            return None
        return self.unflatten(x.reshape((m, n)).clip(0.0, 1.0), index)
