"""Per-round phase summary of an exported Chrome trace.

    python -m shockwave_tpu.obs.report <trace.json> [--phases a,b,...]
    python -m shockwave_tpu.obs.report --compare A.json B.json \
        [--threshold 0.25]

Reads a trace written by ``Tracer.export_chrome_trace`` and prints one
row per round with the total seconds spent in each pipeline phase
(solve / dispatch / wait / end_round / journal-fsync by default), plus
per-phase totals, counts and means. Spans that carry no ``round`` arg
(journal fsyncs fire from RPC threads that don't know the round) are
attributed to the round whose [start, next-start) window contains their
start timestamp; spans outside every window land in the "-" row.

``--compare A B`` diffs two traces' per-phase mean durations (B
against baseline A) and exits nonzero when any phase regressed past
``--threshold`` (default +25%) — the CI smoke jobs' overhead gate.
"""
from __future__ import annotations

import argparse
import bisect
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from . import names


def load_spans(path: str) -> List[dict]:
    """Chrome-trace events -> [{name, ts, dur, args}] in seconds."""
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    spans = []
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        spans.append({"name": e.get("name", "?"),
                      "ts": float(e.get("ts", 0.0)) / 1e6,
                      "dur": float(e.get("dur", 0.0)) / 1e6,
                      "args": e.get("args", {}) or {}})
    return spans


def _round_windows(spans: List[dict]) -> Tuple[List[float], List[int]]:
    """Sorted (start_ts, round) windows from spans that carry a round
    arg, for attributing round-less spans by timestamp."""
    starts: Dict[int, float] = {}
    for s in spans:
        rnd = s["args"].get("round")
        if isinstance(rnd, int):
            starts[rnd] = min(starts.get(rnd, s["ts"]), s["ts"])
    ordered = sorted(starts.items(), key=lambda kv: kv[1])
    return [ts for _, ts in ordered], [rnd for rnd, _ in ordered]


def assign_round(span: dict, window_ts: List[float],
                 window_round: List[int]) -> Optional[int]:
    rnd = span["args"].get("round")
    if isinstance(rnd, int):
        return rnd
    if not window_ts:
        return None
    i = bisect.bisect_right(window_ts, span["ts"]) - 1
    return window_round[i] if i >= 0 else None


def phase_table(spans: List[dict],
                phases: Tuple[str, ...] = names.REPORT_PHASES):
    """-> (sorted round keys, {round: {phase: seconds}},
    {phase: (count, total)})."""
    window_ts, window_round = _round_windows(spans)
    per_round: Dict[object, Dict[str, float]] = defaultdict(
        lambda: defaultdict(float))
    totals: Dict[str, List[float]] = {p: [0, 0.0] for p in phases}
    for s in spans:
        if s["name"] not in phases:
            continue
        rnd = assign_round(s, window_ts, window_round)
        key = rnd if rnd is not None else "-"
        per_round[key][s["name"]] += s["dur"]
        totals[s["name"]][0] += 1
        totals[s["name"]][1] += s["dur"]
    rounds = sorted((k for k in per_round if k != "-"),
                    key=lambda r: int(r))
    if "-" in per_round:
        rounds.append("-")
    return rounds, per_round, {p: (int(c), t)
                               for p, (c, t) in totals.items()}


def render(spans: List[dict],
           phases: Tuple[str, ...] = names.REPORT_PHASES) -> str:
    rounds, per_round, totals = phase_table(spans, phases)
    header = ["round"] + [p for p in phases] + ["row_total"]
    widths = [max(len(h), 13) for h in header]

    def fmt_row(cells):
        return "  ".join(str(c).rjust(w) for c, w in zip(cells, widths))

    lines = [fmt_row(header), fmt_row(["-" * w for w in widths])]
    for rnd in rounds:
        row = [per_round[rnd].get(p, 0.0) for p in phases]
        lines.append(fmt_row([rnd] + [f"{v:.3f}" for v in row]
                             + [f"{sum(row):.3f}"]))
    lines.append(fmt_row(["-" * w for w in widths]))
    total_row = [totals[p][1] for p in phases]
    lines.append(fmt_row(["total_s"] + [f"{v:.3f}" for v in total_row]
                         + [f"{sum(total_row):.3f}"]))
    lines.append(fmt_row(["count"] + [str(totals[p][0]) for p in phases]
                         + [str(sum(totals[p][0] for p in phases))]))
    lines.append(fmt_row(
        ["mean_s"]
        + [f"{(totals[p][1] / totals[p][0]):.4f}" if totals[p][0]
           else "-" for p in phases] + [""]))
    return "\n".join(lines)


def compare(path_a: str, path_b: str,
            phases: Tuple[str, ...] = names.REPORT_PHASES,
            threshold: float = 0.25):
    """Diff per-phase means of trace B against baseline A.

    Returns (report text, regressed phase list). A phase regresses when
    its mean duration grew by more than `threshold` (fractional) over a
    baseline mean that is large enough to measure (>= 1 ms — diffing
    noise against noise flags nothing)."""
    stats = {}
    for path in (path_a, path_b):
        spans = load_spans(path)
        _, _, totals = phase_table(spans, phases)
        stats[path] = totals
    header = ["phase", "mean_A_s", "mean_B_s", "delta"]
    widths = [max(len(h), 14) for h in header]

    def fmt(cells):
        return "  ".join(str(c).rjust(w) for c, w in zip(cells, widths))

    lines = [f"A = {path_a}", f"B = {path_b}", fmt(header),
             fmt(["-" * w for w in widths])]
    regressed = []
    for phase in phases:
        count_a, total_a = stats[path_a].get(phase, (0, 0.0))
        count_b, total_b = stats[path_b].get(phase, (0, 0.0))
        mean_a = total_a / count_a if count_a else 0.0
        mean_b = total_b / count_b if count_b else 0.0
        if mean_a >= 1e-3:
            delta = (mean_b - mean_a) / mean_a
            delta_str = f"{delta * 100:+.1f}%"
            if delta > threshold:
                regressed.append(phase)
                delta_str += " REGRESSED"
        else:
            delta_str = "-"
        lines.append(fmt([phase, f"{mean_a:.4f}", f"{mean_b:.4f}",
                          delta_str]))
    return "\n".join(lines), regressed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.obs.report",
        description=__doc__.splitlines()[0])
    p.add_argument("trace", nargs="+",
                   help="Chrome-trace JSON exported by the tracer "
                        "(--obs_trace / export_chrome_trace); with "
                        "--compare, exactly two: baseline then "
                        "candidate")
    p.add_argument("--phases", default=None,
                   help="comma-separated span names to tabulate "
                        f"(default: {','.join(names.REPORT_PHASES)})")
    p.add_argument("--compare", action="store_true",
                   help="diff two traces' per-phase means; exit 2 when "
                        "any phase regressed past --threshold")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="fractional mean-duration regression tolerance "
                        "for --compare (default 0.25 = +25%%)")
    args = p.parse_args(argv)
    phases = (tuple(s.strip() for s in args.phases.split(",") if s.strip())
              if args.phases else names.REPORT_PHASES)
    if args.compare:
        if len(args.trace) != 2:
            p.error("--compare takes exactly two traces: baseline "
                    "then candidate")
        text, regressed = compare(args.trace[0], args.trace[1],
                                  phases, args.threshold)
        print(text)
        if regressed:
            print(f"REGRESSION: phases {regressed} exceeded "
                  f"+{args.threshold * 100:.0f}% over baseline",
                  file=sys.stderr)
            return 2
        return 0
    if len(args.trace) != 1:
        p.error("exactly one trace (or use --compare A B)")
    spans = load_spans(args.trace[0])
    if not spans:
        print(f"{args.trace[0]}: no spans", file=sys.stderr)
        return 1
    print(f"{args.trace[0]}: {len(spans)} spans")
    print(render(spans, phases))
    return 0


if __name__ == "__main__":
    sys.exit(main())
