"""Render the metric catalog from `obs/names.py` as a Markdown table.

    python -m shockwave_tpu.obs.catalog

README's "Observability" section embeds this output; a test keeps the
two in sync (every declared metric name must appear in README.md), so
the catalog cannot silently drift from the docs.
"""
from __future__ import annotations

import sys

from . import names


def catalog_markdown() -> str:
    rows = [("metric", "kind", "labels", "description"),
            ("---", "---", "---", "---")]
    for spec in names.all_metric_specs():
        rows.append((f"`{spec.name}`", spec.kind,
                     ", ".join(spec.labels) or "—",
                     spec.help.replace("\n", " ")))
    return "\n".join("| " + " | ".join(r) + " |" for r in rows)


def main(argv=None) -> int:
    print(catalog_markdown())
    return 0


if __name__ == "__main__":
    sys.exit(main())
