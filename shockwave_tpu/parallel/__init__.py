from .mesh import (make_mesh, data_parallel_sharding, replicate,
                   shard_batch, local_batch_slice)

__all__ = ["make_mesh", "data_parallel_sharding", "replicate", "shard_batch",
           "local_batch_slice"]
