"""Test configuration.

- Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding tests
  run anywhere (real TPU tests live behind the `tpu` marker).
- Provides a loader for the read-only reference implementation so parity
  tests can cross-check behavior without depending on its solver stack.
"""
import importlib.util
import os
import sys
import types

# Force CPU with 8 virtual devices regardless of ambient accelerator env.
# The environment's sitecustomize may import jax and pin the platform list
# before we run, so the config update (not just the env var) is required.
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import faulthandler

import pytest

REFERENCE_DIR = "/root/reference/scheduler"


@pytest.fixture(autouse=True)
def _lock_sanitizer(request, monkeypatch):
    """Runtime concurrency sanitizer (analysis/sanitizer.py) for every
    `runtime`/`recovery`/`faults`-marked test: schedulers constructed
    during the test get instrumented locks (SWTPU_SANITIZE=1), and the
    test FAILS at teardown on any lock-order cycle or @requires_lock
    unowned-access report — so a concurrency regression in the round
    pipeline is named, not flaked around."""
    marked = any(request.node.get_closest_marker(m)
                 for m in ("runtime", "recovery", "faults"))
    if not marked:
        yield
        return
    from shockwave_tpu.analysis import sanitizer
    monkeypatch.setenv("SWTPU_SANITIZE", "1")
    sanitizer.monitor().reset()
    yield
    report = sanitizer.monitor().report()
    sanitizer.monitor().reset()
    assert not report["violations"], (
        "concurrency sanitizer reports for this test:\n  "
        + "\n  ".join(str(v) for v in report["violations"]))


@pytest.fixture(autouse=True)
def _hang_guard(request):
    """Per-test wall-clock guard for tests marked @pytest.mark.timeout(N).

    The loopback fault-injection tests exercise code whose historical
    failure mode is an `_end_round` hang; a regression must fail the run
    in seconds, not eat the tier-1 870 s budget. pytest-timeout is not
    in the image, so this uses faulthandler: on expiry it dumps every
    thread's traceback and hard-exits the process — a loud, attributable
    fast failure (the dump names the hung test).
    """
    marker = request.node.get_closest_marker("timeout")
    if marker is None:
        yield
        return
    seconds = marker.args[0] if marker.args else 120
    faulthandler.dump_traceback_later(seconds, exit=True)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()


def _install_stub(name, **attrs):
    """Install a minimal fake module so reference files import without solvers."""
    if name in sys.modules:
        return sys.modules[name]
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    sys.modules[name] = mod
    return mod


@pytest.fixture(scope="session")
def reference_utils():
    """Import the reference's utils module (pure-python parts only)."""
    if not os.path.isdir(REFERENCE_DIR):
        pytest.skip("reference implementation not available")
    cvxpy = _install_stub(
        "cvxpy",
        Variable=object, Problem=object, Maximize=object, Minimize=object,
        installed_solvers=lambda: [],
    )
    _install_stub("cvxpy.error", DCPError=Exception)
    cvxpy.error = sys.modules["cvxpy.error"]
    _install_stub("gurobipy")
    _install_stub("mosek")
    try:
        import psutil  # noqa: F401
    except ImportError:
        _install_stub("psutil")
    if REFERENCE_DIR not in sys.path:
        sys.path.insert(0, REFERENCE_DIR)
    spec = importlib.util.spec_from_file_location(
        "reference_utils", os.path.join(REFERENCE_DIR, "utils.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def ambient_accelerator_env(*extra_drop):
    """Subprocess env for children that should see the AMBIENT backend
    (real accelerator if present) rather than conftest's forced-CPU pin:
    drops JAX_PLATFORMS (and any extra keys) and prepends the repo root
    to PYTHONPATH. Shared by every test that shells out to hardware."""
    drop = {"JAX_PLATFORMS", *extra_drop}
    env = {k: v for k, v in os.environ.items() if k not in drop}
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def cpu_subprocess_env():
    """Subprocess env for children that must stay entirely OFF the
    accelerator relay: CPU backend pinned and the relay address dropped,
    so a wedged tunnel can never hang a CPU-only test (the site hook
    dials the relay at import when the address is present)."""
    env = ambient_accelerator_env("PALLAS_AXON_POOL_IPS")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def pytest_sessionfinish(session, exitstatus):
    """On a FAILED run with SWTPU_OBS_DUMP_DIR set (CI exports it),
    dump every live Observability's /metrics text and Chrome trace so
    the failure artifact carries a timeline — a distributed-test flake
    arrives with the round phases that led up to it, not just a
    traceback."""
    dump_dir = os.environ.get("SWTPU_OBS_DUMP_DIR")
    if not dump_dir or exitstatus == 0:
        return
    try:
        from shockwave_tpu.obs import dump_all
        written = dump_all(dump_dir)
        if written:
            print(f"\n[obs] dumped {len(written)} observability "
                  f"artifact(s) to {dump_dir}")
    except Exception as e:  # noqa: BLE001 - artifact dumping must never
        # mask the real test failure
        print(f"\n[obs] artifact dump failed: {type(e).__name__}: {e}")
