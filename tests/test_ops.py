"""Pallas op tests (run via the interpreter on the CPU test mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.ops import flash_attention


def ref_attn(q, k, v, causal=False, kpm=None):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -1e30)
    if kpm is not None:
        s = jnp.where(kpm[:, None, None, :], s, -1e30)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def rand_qkv(rng, b, t, h, d, tk=None):
    tk = tk or t
    return (jnp.asarray(rng.randn(b, t, h, d), jnp.float32),
            jnp.asarray(rng.randn(b, tk, h, d), jnp.float32),
            jnp.asarray(rng.randn(b, tk, h, d), jnp.float32))


class TestFlashAttention:
    @pytest.mark.parametrize("t,causal", [(128, False), (128, True),
                                          (32, True)])
    def test_forward_parity(self, t, causal):
        rng = np.random.RandomState(0)
        q, k, v = rand_qkv(rng, 2, t, 2, 64)
        out = flash_attention(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(out - ref_attn(q, k, v, causal))))
        assert err < 2e-5, err

    def test_key_padding_mask(self):
        rng = np.random.RandomState(1)
        q, k, v = rand_qkv(rng, 2, 128, 2, 64)
        kpm = jnp.asarray(rng.rand(2, 128) > 0.3)
        out = flash_attention(q, k, v, key_padding_mask=kpm)
        err = float(jnp.max(jnp.abs(out - ref_attn(q, k, v, kpm=kpm))))
        assert err < 2e-5, err

    def test_cross_attention_lengths(self):
        rng = np.random.RandomState(2)
        q, k, v = rand_qkv(rng, 1, 64, 2, 64, tk=128)
        out = flash_attention(q, k, v)
        err = float(jnp.max(jnp.abs(out - ref_attn(q, k, v))))
        assert err < 2e-5, err

    def test_gradients_match(self):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 1, 64, 2, 64)
        kpm = jnp.asarray(rng.rand(1, 64) > 0.2)

        def loss(f):
            return lambda q, k, v: (
                f(q, k, v, causal=True, key_padding_mask=kpm) ** 2).sum()

        g1 = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(lambda q, k, v, causal, key_padding_mask:
                           ref_attn(q, k, v, causal, key_padding_mask)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(a - b))) < 5e-4

    def test_fully_masked_row_leaks_no_gradient(self):
        """causal + key 0 padded => query row 0 sees NO valid key. Its
        backward contribution must be exactly zero: without the
        p = where(s <= NEG_INF/2, 0, ...) guard, s and lse both sit at
        the NEG_INF floor and exp(s - lse) injects O(1) garbage into
        valid keys' dk/dv (measured up to 2.2 at multi-block grids)."""
        rng = np.random.RandomState(5)
        t = 128
        q, k, v = rand_qkv(rng, 1, t, 2, 64)
        kpm = jnp.ones((1, t), bool).at[0, 0].set(False)
        row_ok = (jnp.arange(t) >= 1).astype(jnp.float32)

        def flash_loss(q, k, v):
            out = flash_attention(q, k, v, causal=True,
                                  key_padding_mask=kpm,
                                  block_q=32, block_k=32)
            return (out.astype(jnp.float32) ** 2).sum()

        def ref_loss_row0_excluded(q, k, v):
            out = ref_attn(q, k, v, True, kpm).astype(jnp.float32)
            return ((out * row_ok[None, :, None, None]) ** 2).sum()

        g1 = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(ref_loss_row0_excluded, argnums=(0, 1, 2))(q, k, v)
        # Row 0 contributes nothing anywhere; remaining grads match the
        # reference with row 0 excluded from the loss.
        assert float(jnp.max(jnp.abs(g1[0][0, 0]))) == 0.0
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(a - b))) < 5e-4

    def test_causal_cross_rejected(self):
        rng = np.random.RandomState(4)
        q, k, v = rand_qkv(rng, 1, 64, 2, 64, tk=128)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, causal=True)


class TestTransformerFlashPath:
    def test_flash_matches_einsum_path(self):
        from shockwave_tpu.models.transformer import Seq2SeqTransformer
        rng = np.random.RandomState(5)
        src = jnp.asarray(rng.randint(1, 64, (2, 32)), jnp.int32)
        tgt = jnp.asarray(rng.randint(1, 64, (2, 32)), jnp.int32)
        kwargs = dict(vocab_size=64, dim=64, num_heads=2, num_layers=1,
                      mlp_dim=64, max_len=32, dtype=jnp.float32)
        base = Seq2SeqTransformer(use_flash=False, **kwargs)
        flash = Seq2SeqTransformer(use_flash=True, **kwargs)
        params = base.init(jax.random.PRNGKey(0), src, tgt)["params"]
        out_base = base.apply({"params": params}, src, tgt)
        out_flash = flash.apply({"params": params}, src, tgt)
        err = float(jnp.max(jnp.abs(out_base - out_flash)))
        assert err < 1e-4, err


@pytest.mark.tpu
class TestFlashTPU:
    def test_hardware_parity(self):
        """Run the fwd+bwd flash-vs-einsum parity script on the REAL TPU
        backend, in a subprocess outside conftest's forced-CPU env."""
        import os
        import subprocess
        import sys

        from conftest import REPO_ROOT, ambient_accelerator_env

        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(REPO_ROOT, "tests/tpu_flash_parity.py")],
                capture_output=True, text=True, timeout=600,
                env=ambient_accelerator_env())
        except subprocess.TimeoutExpired:
            pytest.skip("TPU backend unreachable (wedged tunnel?)")
        if out.returncode == 75:
            pytest.skip("no TPU backend available")
        assert out.returncode == 0, out.stderr[-3000:]
        assert "ALL OK" in out.stdout
