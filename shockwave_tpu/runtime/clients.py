"""gRPC clients for all three control-plane directions
(reference: runtime/rpc/{scheduler_client,worker_client,iterator_client}.py)."""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import grpc

from .proto import control_pb2 as pb
from .rpc import Stub

logger = logging.getLogger("shockwave_tpu.runtime")


class SchedulerToWorkerClient:
    """Scheduler -> one worker daemon."""

    def __init__(self, addr: str, port: int):
        self.addr = addr
        self.port = port
        self._channel = grpc.insecure_channel(f"{addr}:{port}")
        self._stub = Stub(self._channel, "shockwave_tpu.SchedulerToWorker")

    def run_job(self, job_descriptions: Sequence[dict], worker_id: int,
                round_id: int) -> None:
        request = pb.RunJobRequest(
            jobs=[pb.JobDescription(**d) for d in job_descriptions],
            worker_id=worker_id, round_id=round_id)
        self._stub.RunJob(request)

    def kill_job(self, job_id: int) -> None:
        self._stub.KillJob(pb.KillJobRequest(job_id=job_id))

    def reset(self) -> None:
        self._stub.Reset(pb.Empty())

    def shutdown(self) -> None:
        try:
            self._stub.Shutdown(pb.Empty(), timeout=5)
        except grpc.RpcError:
            pass  # worker may exit before replying


class WorkerToSchedulerClient:
    """Worker daemon -> scheduler."""

    def __init__(self, sched_addr: str, sched_port: int):
        self._channel = grpc.insecure_channel(f"{sched_addr}:{sched_port}")
        self._stub = Stub(self._channel, "shockwave_tpu.WorkerToScheduler")

    def register_worker(self, worker_type: str, ip_addr: str, port: int,
                        num_chips: int) -> Tuple[List[int], float]:
        response = self._stub.RegisterWorker(pb.RegisterWorkerRequest(
            worker_type=worker_type, ip_addr=ip_addr, port=port,
            num_chips=num_chips))
        if not response.success:
            raise RuntimeError(response.error_message)
        return list(response.worker_ids), response.round_duration

    def notify_done(self, job_ids: Sequence[int], worker_id: int,
                    num_steps: Sequence[int], execution_times: Sequence[float],
                    iterator_logs: Optional[Sequence[str]] = None) -> None:
        self._stub.Done(pb.DoneRequest(
            job_ids=list(job_ids), worker_id=worker_id,
            num_steps=[int(s) for s in num_steps],
            execution_times=list(execution_times),
            iterator_logs=list(iterator_logs or [])))


class IteratorToSchedulerClient:
    """Training process (lease iterator) -> scheduler. A fresh channel per
    call keeps the client robust to scheduler restarts, as in the reference."""

    def __init__(self, job_id: int, worker_id: int, sched_addr: str,
                 sched_port: int):
        self._job_id = job_id
        self._worker_id = worker_id
        self._target = f"{sched_addr}:{sched_port}"

    def _stub(self, channel):
        return Stub(channel, "shockwave_tpu.IteratorToScheduler")

    def init(self) -> Tuple[int, float, float]:
        with grpc.insecure_channel(self._target) as channel:
            r = self._stub(channel).InitJob(pb.InitJobRequest(
                job_id=self._job_id, worker_id=self._worker_id))
            return r.max_steps, r.max_duration, r.extra_time

    def update_lease(self, steps: int, duration: float, max_steps: int,
                     max_duration: float) -> Tuple[int, float, float, float]:
        with grpc.insecure_channel(self._target) as channel:
            r = self._stub(channel).UpdateLease(pb.UpdateLeaseRequest(
                job_id=self._job_id, worker_id=self._worker_id,
                steps=int(steps), duration=duration, max_steps=int(max_steps),
                max_duration=max_duration))
            return r.max_steps, r.max_duration, r.run_time_so_far, r.deadline

    def update_resource_requirement(self, big_bs: bool, small_bs: bool) -> None:
        with grpc.insecure_channel(self._target) as channel:
            self._stub(channel).UpdateResourceRequirement(
                pb.UpdateResourceRequirementRequest(
                    job_id=self._job_id, worker_id=self._worker_id,
                    big_bs=big_bs, small_bs=small_bs))
