#!/usr/bin/env python3
"""CycleGAN monet2photo workload (trace: "CycleGAN").

CLI parity with the reference's cyclegan.py — the trace command is
`python3 cyclegan.py --dataset_path %s/monet2photo --decay_epoch 0` with
`--n_steps` appended by the dispatcher
(reference: workloads/pytorch/cyclegan/cyclegan.py).

GAN training needs two optimizers (generators vs discriminators), so this
workload drives the lease iterator directly instead of the shared Trainer:
one jit'd step updates G_AB/G_BA then D_A/D_B, batch sharded over the dp
mesh axis, params replicated (XLA all-reduces grads on ICI).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                *[".."] * 3))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.models import data
from shockwave_tpu.models.cyclegan import Discriminator, Generator
from shockwave_tpu.models.train_common import (checkpoint_path, common_parser,
                                               enable_compile_cache,
                                               load_checkpoint, parse_args,
                                               save_checkpoint_rank0)
from shockwave_tpu.parallel.mesh import data_parallel_sharding, make_mesh
from shockwave_tpu.runtime.iterator import LeaseIterator


def build_step(models, g_tx, d_tx, lambda_cyc=10.0, lambda_id=5.0):
    g_ab, g_ba, d_a, d_b = models

    def mse(x, target):
        return jnp.mean((x - target) ** 2)

    def g_loss_fn(g_params, d_params, real_a, real_b):
        fake_b = g_ab.apply({"params": g_params["g_ab"]}, real_a)
        fake_a = g_ba.apply({"params": g_params["g_ba"]}, real_b)
        rec_a = g_ba.apply({"params": g_params["g_ba"]}, fake_b)
        rec_b = g_ab.apply({"params": g_params["g_ab"]}, fake_a)
        id_a = g_ba.apply({"params": g_params["g_ba"]}, real_a)
        id_b = g_ab.apply({"params": g_params["g_ab"]}, real_b)
        adv = (mse(d_b.apply({"params": d_params["d_b"]}, fake_b), 1.0)
               + mse(d_a.apply({"params": d_params["d_a"]}, fake_a), 1.0))
        cyc = jnp.mean(jnp.abs(rec_a - real_a)) + jnp.mean(jnp.abs(rec_b - real_b))
        ident = jnp.mean(jnp.abs(id_a - real_a)) + jnp.mean(jnp.abs(id_b - real_b))
        loss = adv + lambda_cyc * cyc + lambda_id * ident
        return loss, (fake_a, fake_b)

    def d_loss_fn(d_params, real_a, real_b, fake_a, fake_b):
        loss_a = (mse(d_a.apply({"params": d_params["d_a"]}, real_a), 1.0)
                  + mse(d_a.apply({"params": d_params["d_a"]}, fake_a), 0.0))
        loss_b = (mse(d_b.apply({"params": d_params["d_b"]}, real_b), 1.0)
                  + mse(d_b.apply({"params": d_params["d_b"]}, fake_b), 0.0))
        return 0.5 * (loss_a + loss_b)

    def step(state, real_a, real_b):
        (g_loss, (fake_a, fake_b)), g_grads = jax.value_and_grad(
            g_loss_fn, has_aux=True)(state["g_params"], state["d_params"],
                                     real_a, real_b)
        g_updates, g_opt = g_tx.update(g_grads, state["g_opt"],
                                       state["g_params"])
        g_params = optax.apply_updates(state["g_params"], g_updates)

        fake_a = jax.lax.stop_gradient(fake_a)
        fake_b = jax.lax.stop_gradient(fake_b)
        d_loss, d_grads = jax.value_and_grad(d_loss_fn)(
            state["d_params"], real_a, real_b, fake_a, fake_b)
        d_updates, d_opt = d_tx.update(d_grads, state["d_opt"],
                                      state["d_params"])
        d_params = optax.apply_updates(state["d_params"], d_updates)
        new_state = dict(state, g_params=g_params, d_params=d_params,
                         g_opt=g_opt, d_opt=d_opt, step=state["step"] + 1)
        return new_state, {"g_loss": g_loss, "d_loss": d_loss}

    return jax.jit(step, donate_argnums=(0,))


def main():
    p = common_parser("CycleGAN monet2photo", steps_args=("--n_steps",))
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--img_size", type=int, default=128)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--decay_epoch", type=int, default=0)
    args = parse_args(p)
    enable_compile_cache()

    mesh = make_mesh(batch_size=args.batch_size)
    batch_sharding, repl_sharding = data_parallel_sharding(mesh)

    g_ab, g_ba = Generator(), Generator()
    d_a, d_b = Discriminator(), Discriminator()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, args.img_size, args.img_size, 3), jnp.float32)
    g_params = {"g_ab": g_ab.init(rng, sample)["params"],
                "g_ba": g_ba.init(rng, sample)["params"]}
    d_params = {"d_a": d_a.init(rng, sample)["params"],
                "d_b": d_b.init(rng, sample)["params"]}
    g_tx = optax.adam(args.lr, b1=0.5)
    d_tx = optax.adam(args.lr, b1=0.5)
    state = {"g_params": g_params, "d_params": d_params,
             "g_opt": g_tx.init(g_params), "d_opt": d_tx.init(d_params),
             "step": jnp.zeros((), jnp.int32)}
    state = jax.device_put(state, repl_sharding)
    step_fn = build_step((g_ab, g_ba, d_a, d_b), g_tx, d_tx)

    loader = data.monet2photo(args.batch_size, args.img_size,
                              data_dir=args.dataset_path)
    ckpt = checkpoint_path(args.checkpoint_dir)

    def load(path):
        return load_checkpoint(path, jax.device_get(state))

    if args.enable_lease_iterator:
        iterator = LeaseIterator(loader, args.checkpoint_dir,
                                 load_checkpoint_func=load,
                                 save_checkpoint_func=save_checkpoint_rank0,
                                 synthetic_data=args.synthetic_data)
        restored = iterator.load_checkpoint(ckpt)
    else:
        iterator = None
        restored = load(ckpt)
    if restored is not None:
        state = jax.device_put(restored, repl_sharding)
    start_step = int(state["step"])
    budget = args.num_steps

    steps_done, window_steps = 0, 0
    loss = None
    try:
        while True:
            for batch in (iterator if iterator is not None else loader):
                real_a, real_b = jax.device_put(batch, batch_sharding)
                state, metrics = step_fn(state, real_a, real_b)
                loss = metrics["g_loss"]
                if iterator is not None:
                    iterator.set_sync_ref(loss)
                steps_done += 1
                window_steps += 1
                if window_steps >= args.throughput_estimation_interval:
                    jax.block_until_ready(loss)
                    print(f"[THROUGHPUT_ESTIMATION]\t{time.time()}\t"
                          f"{start_step + steps_done}", flush=True)
                    window_steps = 0
                if budget is not None and start_step + steps_done >= budget:
                    if iterator is not None:
                        iterator.complete()
                    break
            budget_reached = (budget is not None
                              and start_step + steps_done >= budget)
            if iterator is not None and (iterator.done or budget_reached):
                break
            if iterator is None and (budget is None or budget_reached):
                break
    finally:
        if loss is not None:
            jax.block_until_ready(loss)
        if iterator is not None:
            iterator.save_checkpoint(ckpt, state)
        else:
            save_checkpoint_rank0(ckpt, state)
    print(f"TRAINED {steps_done} steps (cumulative {start_step + steps_done})",
          flush=True)


if __name__ == "__main__":
    main()
