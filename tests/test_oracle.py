"""Learned throughput oracle + heterogeneous multi-generation clusters.

Covers: seeded-fit determinism (byte-identical model saves), the
generation comm-scaling transfer, online residual convergence, the
profiled -> learned -> prior chain and its confidence gate, the
history-schema contract (`oracle.train` skip-and-warn, ring reload
validation, a record -> restart -> reload -> train round trip), the
planner's per-type capacity rows, scalar-vs-vectorized parity on a
mixed two-generation cluster (oracle on AND off), journal replay of a
mixed-cluster drive, serving mu priors, and the committed cold-start
study's byte-reproducibility + envelope gate.
"""
import copy
import json
import os
import pickle
import random
import subprocess
import sys

import numpy as np
import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.throughput_estimator import (
    CONSERVATIVE_PRIOR_STEPS_PER_S, PROVENANCE_LEARNED, PROVENANCE_PRIOR,
    PROVENANCE_PROFILED, OracleThroughputChain)
from shockwave_tpu.obs.history import (OBSERVATIONS_SCHEMA,
                                       TelemetryHistory, valid_observation)
from shockwave_tpu.obs.registry import MetricsRegistry
from shockwave_tpu.oracle import train as oracle_train
from shockwave_tpu.oracle.features import (family_bucket, family_of,
                                           generation_of)
from shockwave_tpu.oracle.model import ThroughputModel
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.sched.scheduler import DEFAULT_THROUGHPUT
from shockwave_tpu.shockwave.planner import PlanRequest, ShockwavePlanner
from shockwave_tpu.solver import get_policy

REPO = os.path.join(os.path.dirname(__file__), "..")
V5E = os.path.join(REPO, "data", "v5e_throughputs.json")
ORACLE_DIR = os.path.join(REPO, "reproduce", "oracle")
TRUTH = os.path.join(ORACLE_DIR, "truth_mixed.json")
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "oracle",
                       "history_fixture.json")
STUDY = os.path.join(REPO, "scripts", "drivers",
                     "oracle_coldstart_study.py")


class SteppingClock:
    def __init__(self, start=100.0, step=1.0):
        self.now = start
        self.step = step

    def __call__(self):
        t = self.now
        self.now += self.step
        return t


def synth_rows(v5_exponent=0.95, lite_exponent=0.8, families=3,
               noise=0.0, seed=0):
    """Training rows on an exact two-generation surface: the newer
    generation is 2.25x per chip AND keeps more scaling efficiency."""
    rng = random.Random(seed)
    rows = []
    fams = [("LM", 4.0), ("ResNet-18", 120.0), ("Transformer", 20.0),
            ("Recommendation", 900.0)][:families]
    for fam, base in fams:
        for bs in (16, 32, 64):
            for sf in (1, 2, 4):
                for wt, gain, exp in (("v5-lite", 1.0, lite_exponent),
                                      ("v5", 2.25, v5_exponent)):
                    rate = base * gain * (bs / 16.0) * sf ** exp
                    if noise:
                        rate *= rng.lognormvariate(0.0, noise)
                    rows.append((f"{fam} (batch size {bs})", bs, sf,
                                 wt, rate))
    return rows


class TestModel:
    def test_fit_deterministic_byte_identical_saves(self, tmp_path):
        rows = synth_rows(noise=0.05)
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        ThroughputModel.fit(rows, seed=3).save(str(a))
        ThroughputModel.fit(list(rows), seed=3).save(str(b))
        assert a.read_bytes() == b.read_bytes()

    def test_save_load_roundtrip_preserves_predictions(self, tmp_path):
        model = ThroughputModel.fit(synth_rows(noise=0.05), seed=0)
        model.observe("LM (batch size 32)", 32, 2, "v5", 123.0)
        path = str(tmp_path / "m.json")
        model.save(path)
        loaded = ThroughputModel.load(path)
        for query in (("LM (batch size 32)", 32, 2, "v5"),
                      ("Unseen (batch size 8)", 8, 4, "v5-lite")):
            got, want = loaded.predict(*query), model.predict(*query)
            # save() rounds weights/corrections to 12 decimals for
            # byte stability; predictions agree to that precision.
            assert got[0] == pytest.approx(want[0], rel=1e-9)
            assert got[1] == want[1]

    def test_load_rejects_foreign_schema(self, tmp_path):
        model = ThroughputModel.fit(synth_rows(), seed=0)
        path = str(tmp_path / "m.json")
        model.save(path)
        payload = json.loads(open(path).read())
        payload["schema"] = 99
        with open(path, "w") as f:
            json.dump(payload, f)
        with pytest.raises(ValueError):
            ThroughputModel.load(path)

    def test_generation_comm_scaling_transfers(self):
        """A family fit ONLY at scale factor 1 inherits the v5
        generation's flatter comm curve from the other families: its
        predicted v5/v5-lite speedup grows with scale factor."""
        rows = synth_rows(families=3)
        # The held-out family: single-chip rows on both generations.
        for wt, gain in (("v5-lite", 1.0), ("v5", 2.25)):
            rows.append(("ResNet-50 (batch size 32)", 32, 1, wt,
                         60.0 * gain))
        model = ThroughputModel.fit(rows, seed=0)

        def ratio(sf):
            v5, _ = model.predict("ResNet-50 (batch size 32)", 32, sf,
                                  "v5")
            lite, _ = model.predict("ResNet-50 (batch size 32)", 32, sf,
                                    "v5-lite")
            return v5 / lite

        assert ratio(4) > ratio(1) * 1.1

    def test_online_observation_converges_and_builds_confidence(self):
        model = ThroughputModel.fit(synth_rows(), seed=0)
        query = ("BrandNew (batch size 8)", 8, 1, "v5-lite")
        _, conf0 = model.predict(*query)
        assert conf0 == 0.0  # never seen: gate to the prior
        for _ in range(6):
            model.observe(*query, 50.0)
        rate, conf = model.predict(*query)
        assert abs(rate - 50.0) / 50.0 < 0.05
        assert conf > 0.5

    def test_family_hash_is_seeded_md5_not_pyhash(self):
        # Pinned values: a Python hash() would vary with
        # PYTHONHASHSEED across processes and break byte-stable fits.
        import hashlib
        for fam, seed in (("BrandNew", 0), ("BrandNew", 7), ("Zzz", 0)):
            digest = hashlib.md5(f"{seed}:{fam}".encode()).hexdigest()
            assert family_bucket(fam, seed) == int(digest, 16) % 4

    def test_family_and_generation_helpers(self):
        assert family_of("ResNet-50 (batch size 32)") == "ResNet-50"
        assert family_of("A3C") == "A3C"
        assert generation_of("v5-lite") == generation_of("v5e")
        assert generation_of("v5") != generation_of("v5-lite")
        assert generation_of("v100") == "gpu_volta"


class TestChain:
    def _chain(self, **kwargs):
        model = ThroughputModel.fit(synth_rows(noise=0.02), seed=0)
        profiled = {"v5-lite": {("LM (batch size 32)", 2): {"null": 9.5}}}
        return OracleThroughputChain(profiled=profiled, model=model,
                                     **kwargs)

    def test_fallback_chain_provenance(self):
        chain = self._chain()
        p = chain.predict("LM (batch size 32)", 32, 2, "v5-lite")
        assert (p.provenance, p.steps_per_s, p.confidence) == (
            PROVENANCE_PROFILED, 9.5, 1.0)
        p = chain.predict("LM (batch size 32)", 32, 2, "v5")
        assert p.provenance == PROVENANCE_LEARNED
        assert p.steps_per_s > 0 and 0 < p.confidence <= 1
        p = chain.predict("Unknown (batch size 4)", 4, 1, "v5")
        assert p.provenance == PROVENANCE_PRIOR
        assert p.steps_per_s == CONSERVATIVE_PRIOR_STEPS_PER_S
        assert p.confidence == 0.0

    def test_min_confidence_gates_learned_to_prior(self):
        chain = self._chain(min_confidence=1.01)
        p = chain.predict("LM (batch size 32)", 32, 2, "v5")
        assert p.provenance == PROVENANCE_PRIOR

    def test_prior_matches_scheduler_learn_online_seed(self):
        # Cross-module contract: the conservative prior must equal the
        # scheduler's DEFAULT_THROUGHPUT learn-online seed, so a
        # prior-provenance job behaves exactly like the pre-oracle
        # missing-entry path.
        assert CONSERVATIVE_PRIOR_STEPS_PER_S == DEFAULT_THROUGHPUT

    def test_observe_refines_prediction(self):
        chain = self._chain()
        before = chain.predict("LM (batch size 32)", 32, 2, "v5")
        for _ in range(4):
            chain.observe("LM (batch size 32)", 32, 2, "v5",
                          before.steps_per_s * 2.0)
        after = chain.predict("LM (batch size 32)", 32, 2, "v5")
        assert after.steps_per_s > before.steps_per_s * 1.5

    def test_serving_mu_zero_samples_is_none(self):
        chain = self._chain()
        assert chain.serving_mu("NeverSeen (batch size 1)", 1,
                                ["v5-lite", "v5"]) is None
        mu = chain.serving_mu("LM (batch size 16)", 16,
                              ["v5-lite", "v5"])
        assert mu is not None and mu > 0
        no_model = OracleThroughputChain(profiled=None, model=None)
        assert no_model.serving_mu("LM (batch size 16)", 16,
                                   ["v5-lite"]) is None


class TestHistorySchema:
    def test_valid_observation_contract(self):
        good = [3, "LM (batch size 10)", 10, 2, "v5-lite", 4.5]
        assert valid_observation(good)
        assert not valid_observation(good[:5])           # short row
        assert not valid_observation(good + [1])         # long row
        assert not valid_observation(["3"] + good[1:])   # str round
        assert not valid_observation(good[:5] + [True])  # bool rate
        assert not valid_observation(dict())             # wrong type

    def test_reload_drops_foreign_observations_schema(self, tmp_path):
        path = str(tmp_path / "history.json")
        with open(path, "w") as f:
            json.dump({"schema": 1, "observations_schema": 99,
                       "rounds": [],
                       "observations": [
                           [1, "LM (batch size 10)", 10, 1, "v5e", 4.0]],
                       "serving": [], "alerts": {}}, f)
        hist = TelemetryHistory(MetricsRegistry(), SteppingClock(), path)
        assert hist.payload()["observations"] == []

    def test_reload_keeps_valid_drops_malformed_rows(self, tmp_path):
        path = str(tmp_path / "history.json")
        with open(path, "w") as f:
            json.dump({"schema": 1, "observations_schema": 1,
                       "rounds": [],
                       "observations": [
                           [1, "LM (batch size 10)", 10, 1, "v5e", 4.0],
                           ["bad", "LM (batch size 10)", 10, 1, "v5e",
                            4.0]],
                       "serving": [], "alerts": {}}, f)
        hist = TelemetryHistory(MetricsRegistry(), SteppingClock(), path)
        assert hist.payload()["observations"] == [
            [1, "LM (batch size 10)", 10, 1, "v5e", 4.0]]


class TestTrainCLI:
    def test_fixture_skip_and_warn(self, tmp_path, capsys):
        out = str(tmp_path / "model.json")
        rc = oracle_train.main(["--history", FIXTURE, "--out", out])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip()
                             .splitlines()[-1])
        assert summary["rows"] == 14
        assert summary["skipped_rows"] == 5
        assert ThroughputModel.load(out).n_rows == 14

    def test_no_usable_rows_exits_nonzero(self, tmp_path, capsys):
        bad = str(tmp_path / "foreign.json")
        with open(bad, "w") as f:
            json.dump({"schema": 99, "observations": [[1, "x", 1, 1,
                                                       "v5e", 1.0]]}, f)
        rc = oracle_train.main(["--history", bad,
                                "--out", str(tmp_path / "m.json")])
        assert rc == 1
        assert "no usable training rows" in capsys.readouterr().out

    def test_from_history_roundtrip_across_restart(self, tmp_path):
        """record -> flush -> NEW TelemetryHistory on the same path
        (simulated restart) -> record more -> flush -> train."""
        path = str(tmp_path / "history.json")
        first = TelemetryHistory(MetricsRegistry(), SteppingClock(), path)
        for sf in (1, 2, 4):
            first.record_observation("LM (batch size 10)", 10, sf,
                                     "v5-lite", 4.0 * sf ** 0.8, sf)
        first.flush()

        second = TelemetryHistory(MetricsRegistry(), SteppingClock(),
                                  path)
        assert len(second.payload()["observations"]) == 3  # survived
        for sf in (1, 2, 4):
            second.record_observation("ResNet-18 (batch size 32)", 32,
                                      sf, "v5", 260.0 * sf ** 0.9,
                                      10 + sf)
        second.flush()

        rows, skipped = oracle_train.load_training_rows([path])
        assert len(rows) == 6 and skipped == 0
        model = ThroughputModel.fit(rows, seed=0)
        assert set(model.families) == {"LM", "ResNet-18"}
        assert set(model.worker_types) == {"v5", "v5-lite"}
        rate, conf = model.predict("LM (batch size 10)", 10, 2,
                                   "v5-lite")
        assert abs(rate - 4.0 * 2 ** 0.8) / rate < 0.2
        assert conf > 0.3


class _View:
    def __init__(self, nworkers, remaining):
        self.nworkers = nworkers
        self._remaining = remaining

    def dirichlet_posterior_remaining_runtime(self, progress=None):
        return self._remaining


class TestPlannerCapacityRows:
    def _planner(self, ngpus=4):
        return ShockwavePlanner(ngpus=ngpus, future_nrounds=2,
                                round_duration=120.0)

    def test_plan_request_capacity_rows_defaults_none(self):
        req = PlanRequest(round_ptr=0, job_ids=[], jobs=[],
                          share_series=[], generation=0)
        assert req.capacity_rows is None
        # Old pickles lack the field entirely; solve_prepared reads it
        # via getattr, so deleting it must be harmless.
        del req.capacity_rows
        assert getattr(req, "capacity_rows", None) is None

    def test_single_row_matches_scalar_backfill(self):
        planner = self._planner()
        jobs = [_View(2, 100.0), _View(1, 50.0), _View(1, 200.0)]
        x = np.array([[1, 0], [0, 1], [0, 0]], dtype=bool)
        scalar = planner._construct_schedules(x, [10, 11, 12], jobs, 0,
                                              ngpus=4)
        single = planner._construct_schedules(x, [10, 11, 12], jobs, 0,
                                              ngpus=4,
                                              capacity_rows={"v5": 4})
        assert scalar == single

    def test_hetero_rows_pack_per_generation(self):
        planner = self._planner()
        # Job 10 needs 4 chips: fits the scalar total (2+2) but no
        # single generation — it must be deferred, and the backfill
        # must fill each row independently.
        jobs = [_View(4, 300.0), _View(2, 200.0), _View(2, 100.0),
                _View(1, 50.0)]
        x = np.array([[1, 0], [0, 0], [0, 0], [0, 0]], dtype=bool)
        rows = {"v5-lite": 2, "v5": 2}
        schedules = planner._construct_schedules(
            x, [10, 11, 12, 13], jobs, 0, ngpus=4, capacity_rows=rows)
        assert 10 not in schedules[0]
        # Backfill by remaining runtime: 11 (200) and 12 (100) take one
        # row each; 13 no longer fits.
        assert schedules[0] == [11, 12]

    def test_fallback_schedule_respects_rows(self):
        planner = self._planner()
        planner.pipelined = True
        planner.capacity_rows = {"v5-lite": 2, "v5": 2}
        # The fallback path only reads nworkers and the posterior
        # remaining runtime, so the stub views stand in for metadata.
        for int_id, nworkers, remaining in ((1, 4, 900.0), (2, 2, 600.0),
                                            (3, 2, 300.0)):
            planner.metadata[int_id] = _View(nworkers, remaining)
        selected = planner._fallback_round_schedule()
        assert 1 not in selected
        assert sorted(selected) == [2, 3]


def _mixed_jobs(num_jobs=8, seed=0):
    truth = read_throughputs(TRUTH)["v5-lite"]
    keys = sorted(k for k, e in truth.items()
                  if e["null"] > 0 and k[1] in (1, 2))
    rng = random.Random(seed)
    jobs, arrivals, t = [], [], 0.0
    for _ in range(num_jobs):
        job_type, sf = rng.choice(keys)
        duration = float(round(rng.uniform(900.0, 2400.0)))
        steps = int(duration * truth[(job_type, sf)]["null"])
        jobs.append(Job(None, job_type, "python train.py 32",
                        total_steps=steps, duration=duration,
                        scale_factor=sf, mode="static"))
        arrivals.append(round(t, 2))
        t += rng.expovariate(1.0 / 150.0)
    return jobs, arrivals


def _run_mixed(vectorized, oracle_cfg=None, policy="max_min_fairness_perf"):
    jobs, arrivals = _mixed_jobs()
    sched = Scheduler(
        get_policy(policy, seed=0), simulate=True,
        throughputs_file=TRUTH,
        config=SchedulerConfig(time_per_iteration=120.0, seed=0,
                               oracle=oracle_cfg,
                               vectorized_sim=vectorized))
    makespan = sched.simulate({"v5-lite": 4, "v5": 4}, arrivals,
                              copy.deepcopy(jobs))
    return {
        "makespan": makespan,
        "jct": sched.get_average_jct(),
        "rounds": sched.rounds.num_completed_rounds,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "timelines": sched._job_timelines,
    }


class TestMixedClusterSim:
    def test_scalar_vectorized_parity_oracle_off(self):
        a = _run_mixed(vectorized=False)
        b = _run_mixed(vectorized=True)
        assert pickle.dumps(a) == pickle.dumps(b)

    def test_scalar_vectorized_parity_oracle_on(self):
        cfg = {"model": os.path.join(ORACLE_DIR, "model.json"),
               "min_confidence": 0.3, "truth_file": TRUTH}
        a = _run_mixed(vectorized=False, oracle_cfg=cfg)
        b = _run_mixed(vectorized=True, oracle_cfg=cfg)
        assert pickle.dumps(a) == pickle.dumps(b)

    def test_every_job_completes_on_mixed_spec(self):
        result = _run_mixed(vectorized=True)
        assert result["jct"] is not None
        assert len(result["jct"][3]) == 8


class TestMixedClusterJournalReplay:
    def _scheduler(self):
        return Scheduler(get_policy("max_min_fairness", seed=0),
                         throughputs_file=TRUTH)

    def test_mixed_drive_replays_identically(self, tmp_path):
        from shockwave_tpu.sched.journal import DurabilityLayer, load_state
        live = self._scheduler()
        layer = DurabilityLayer(str(tmp_path))
        live.attach_durability(layer)
        live.register_worker("v5-lite", 2)
        live.register_worker("v5", 2)
        j0 = live.add_job(Job(None, "ResNet-18 (batch size 32)",
                              "python train.py 32", total_steps=300,
                              duration=1000), timestamp=1.0)
        j1 = live.add_job(Job(None, "LM (batch size 10)",
                              "python train.py 10", total_steps=100,
                              duration=1000), timestamp=2.0)
        live._record_round({0: (0,), 1: (2,)})
        for jid, worker, steps, ts in ((j0, 0, 200, 5.0),
                                       (j1, 2, 100, 8.0)):
            live.rounds.current_assignments[jid] = (worker,)
            live._running_jobs.add(jid)
            live.acct.latest_timestamps[jid] = ts
            live.done_callback(jid, worker, [steps], [4.0])
            live.rounds.completed_in_round.discard(jid)
        layer.close()

        recovered = load_state(str(tmp_path))
        assert recovered.events
        replica = self._scheduler()
        replica.restore_from_durable_state(recovered)
        assert dict(replica.workers.cluster_spec) == {"v5-lite": 2,
                                                      "v5": 2}
        assert (dict(replica.acct.total_steps_run)
                == dict(live.acct.total_steps_run))
        assert (dict(replica.acct.completion_times)
                == dict(live.acct.completion_times))
        assert JobIdPair(j1.integer_job_id()) in replica._completed_jobs


class TestSchedulerOracleWiring:
    def test_default_config_is_inert(self):
        assert SchedulerConfig().oracle is None
        sched = Scheduler(get_policy("max_min_fairness", seed=0),
                          throughputs_file=TRUTH)
        assert sched._oracle is None
        assert sched._oracle_truth is None
        assert sched.oracle_serving_mu(
            Job(None, "LM (batch size 10)", "python train.py 10",
                total_steps=10, duration=10)) is None

    def test_oracle_serving_mu_prior(self, tmp_path):
        model_path = str(tmp_path / "model.json")
        ThroughputModel.fit(synth_rows(noise=0.02), seed=0).save(
            model_path)
        sched = Scheduler(
            get_policy("max_min_fairness", seed=0),
            throughputs_file=TRUTH,
            config=SchedulerConfig(oracle={"model": model_path,
                                           "min_confidence": 0.3}))
        sched.register_worker("v5-lite", 1)
        sched.register_worker("v5", 1)
        mu = sched.oracle_serving_mu(
            Job(None, "LM (batch size 16)", "python train.py 16",
                total_steps=10, duration=10))
        assert mu is not None and mu > 0
        # Zero family samples -> None: the tier falls back to the exact
        # configured rate and canonical serving replays stay identical.
        assert sched.oracle_serving_mu(
            Job(None, "NeverSeen (batch size 1)", "python train.py 1",
                total_steps=10, duration=10)) is None

    def test_serving_service_mu_prior_seeds_estimator(self):
        from shockwave_tpu.core.trace import make_serving_job
        from shockwave_tpu.serving.tier import (AutoscalerConfig,
                                                ServingService)
        job = make_serving_job(2.0, 4.0, 600.0, 8.0, 3600.0)
        prior = ServingService(0, job, {}, 0.0, AutoscalerConfig(),
                               mu_prior=5.5)
        assert prior.mu == 5.5
        assert prior.measured.mu_estimate() == pytest.approx(5.5)
        default = ServingService(1, job, {}, 0.0, AutoscalerConfig())
        assert default.mu == default.mu_analytic
        assert default.mu_oracle_prior is None


@pytest.mark.slow
class TestColdStartStudy:
    def test_committed_artifacts_reproduce_and_gate(self, tmp_path):
        """The full acceptance run: regenerate the study into a scratch
        dir, byte-compare every artifact against reproduce/oracle/, and
        require the cold-start envelope to hold."""
        from conftest import cpu_subprocess_env
        out = subprocess.run(
            [sys.executable, STUDY, "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=600, cwd=REPO,
            env=cpu_subprocess_env())
        assert out.returncode == 0, out.stderr[-2000:]
        line = json.loads(out.stdout.strip().splitlines()[-1])
        assert line["within_envelope"] is True
        for name in ("truth_mixed.json", "profiled_minus_cold.json",
                     "history_train.json", "model.json",
                     "coldstart_mixed_study.json"):
            regenerated = (tmp_path / name).read_bytes()
            committed = open(os.path.join(ORACLE_DIR, name),
                             "rb").read()
            assert regenerated == committed, f"{name} drifted"

    def test_cold_jobs_within_envelope_in_committed_artifact(self):
        with open(os.path.join(ORACLE_DIR,
                               "coldstart_mixed_study.json")) as f:
            doc = json.load(f)
        assert doc["cold_start"]["within_envelope"] is True
        assert doc["cold_start"]["max_rel_delta"] <= doc["meta"][
            "envelope"]
        cold = [j for j in doc["jobs"] if j["cold"]]
        assert len(cold) == 3
        assert all(j["rel_delta"] is not None
                   and j["rel_delta"] <= doc["meta"]["envelope"]
                   for j in cold)
        assert doc["oracle_counters"]["predictions_learned"] >= len(cold)
        assert doc["oracle_counters"]["predictions_prior"] == 0
