"""Input pipelines with deterministic synthetic fallbacks.

Real dataset loading is attempted when the data directory exists; in all
other cases (CI, benchmarks, dry runs) deterministic synthetic batches of
the right shapes are produced on host and sharded onto the mesh. The
reference's GavelIterator had the same synthetic-data escape hatch
(gavel_iterator.py:89-92); here it is the pipeline default so every
workload runs anywhere.
"""
from __future__ import annotations

import numpy as np


class SyntheticBatches:
    """A fixed-length epoch of host-generated batches."""

    def __init__(self, make_batch, batches_per_epoch: int, seed: int = 0):
        self._make_batch = make_batch
        self._len = max(1, batches_per_epoch)
        rng = np.random.RandomState(seed)
        # One real batch, reused; keeps host CPU out of the hot loop.
        self._batch = make_batch(rng)

    def __len__(self):
        return self._len

    def __iter__(self):
        for _ in range(self._len):
            yield self._batch


def cifar10(batch_size: int, dataset_size: int = 50000, seed: int = 0):
    def make(rng):
        return (rng.rand(batch_size, 32, 32, 3).astype(np.float32),
                rng.randint(0, 10, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def imagenet(batch_size: int, dataset_size: int = 100000, seed: int = 0):
    def make(rng):
        return (rng.rand(batch_size, 224, 224, 3).astype(np.float32),
                rng.randint(0, 1000, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def multi30k(batch_size: int, src_len: int = 32, tgt_len: int = 32,
             vocab: int = 9521, dataset_size: int = 10000, seed: int = 0):
    def make(rng):
        src = rng.randint(1, vocab, size=(batch_size, src_len)).astype(np.int32)
        tgt = rng.randint(1, vocab, size=(batch_size, tgt_len)).astype(np.int32)
        return src, tgt
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def wikitext2(batch_size: int, seq_len: int = 35, vocab: int = 33278,
              dataset_size: int = 59675, seed: int = 0):
    def make(rng):
        tokens = rng.randint(1, vocab, size=(batch_size, seq_len + 1)).astype(np.int32)
        return tokens[:, :-1], tokens[:, 1:]
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def monet2photo(batch_size: int, image_size: int = 128,
                dataset_size: int = 1193, seed: int = 0):
    """Unpaired image batches for CycleGAN (domains A=paintings, B=photos)."""
    def make(rng):
        a = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        b = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        return a.astype(np.float32), b.astype(np.float32)
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def ml20m(batch_size: int, num_items: int = 20108, dataset_size: int = 117907,
          seed: int = 0):
    def make(rng):
        # ~1% interaction density multi-hot rows.
        rows = (rng.rand(batch_size, num_items) < 0.01).astype(np.float32)
        return (rows,)
    return SyntheticBatches(make, dataset_size // batch_size, seed)
