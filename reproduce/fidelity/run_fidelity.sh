#!/bin/bash
# Physical-vs-simulation fidelity experiment on one real TPU chip
# (counterpart of the reference's reproduce/tacc_32gpus_comparison flow,
# analyze_fidelity.py:31-56, scaled to a single-chip loopback).
#
# Runs the 3-job trace through the REAL scheduler + worker daemon + job
# subprocesses on the attached chip, then the same trace in simulation
# against the measured v5e oracle, and checks the metrics agree.
#
# Tips: pre-warm the XLA compile cache by running each workload once for
# a few steps (first-dispatch compiles otherwise eat into round 0), and
# keep round_duration >= 120 s.
set -eu -o pipefail
cd "$(dirname "$0")/../.."
OUT=${1:-reproduce/fidelity/out}   # untracked by default; pass
                                   # reproduce/fidelity to refresh the
                                   # committed artifacts deliberately
PORT=${2:-50381}
ROUND=${ROUND:-120}
TRACE=${TRACE:-reproduce/fidelity/fidelity_3job.trace}
# No TPU attached? The same experiment runs on CPU (this produced the
# committed reproduce/fidelity/cpu_loopback artifacts):
#   JAX_PLATFORMS=cpu WORKER_TYPE=cpu ROUND=120 \
#   TOL=0.20 TRACE=reproduce/fidelity/fidelity_cpu_3job.trace \
#   ORACLE=reproduce/fidelity/cpu_throughputs.json \
#   reproduce/fidelity/run_fidelity.sh reproduce/fidelity/cpu_loopback
WORKER_TYPE=${WORKER_TYPE:-v5e}
ORACLE=${ORACLE:-data/v5e_throughputs.json}
TOL=${TOL:-0.15}
POLICY=${POLICY:-max_min_fairness}
TIMEOUT=${TIMEOUT:-3600}
# Chips on the (single) worker daemon; >1 enables gang (sf>1) traces.
NUM_CHIPS=${NUM_CHIPS:-1}
CKPT=$(mktemp -d /tmp/swtpu_fidelity.XXXX)
mkdir -p "$OUT"

python scripts/drivers/run_physical.py \
    --trace "$TRACE" --policy "$POLICY" \
    --throughputs "$ORACLE" \
    --expected_num_workers 1 --round_duration "$ROUND" --port "$PORT" \
    --timeout "$TIMEOUT" --timeline_dir "$OUT/timelines" \
    --output "$OUT/physical_${WORKER_TYPE}.pkl" --verbose &
SCHED_PID=$!
# The worker must die with the script, even if the scheduler fails.
WORKER_PID=""
trap '[ -n "$WORKER_PID" ] && kill "$WORKER_PID" 2>/dev/null || true' EXIT
sleep 5
python -m shockwave_tpu.runtime.worker --worker_type "$WORKER_TYPE" \
    --sched_addr 127.0.0.1 --sched_port "$PORT" --worker_port "$((PORT+1))" \
    --num_chips "$NUM_CHIPS" --data_dir /tmp/swtpu_data \
    --checkpoint_dir "$CKPT" &
WORKER_PID=$!

wait "$SCHED_PID"
kill "$WORKER_PID" 2>/dev/null || true

python scripts/drivers/simulate.py \
    --trace "$TRACE" --policy "$POLICY" \
    --throughputs "$ORACLE" \
    --cluster_spec "$WORKER_TYPE:$NUM_CHIPS" \
    --chips_per_server "$NUM_CHIPS" --round_duration "$ROUND" \
    --output "$OUT/simulated_${WORKER_TYPE}.pkl"

python reproduce/analyze_fidelity.py \
    "$OUT/physical_${WORKER_TYPE}.pkl" "$OUT/simulated_${WORKER_TYPE}.pkl" --tolerance "$TOL" \
    | tee "$OUT/fidelity_report.txt"
