#!/usr/bin/env python3
"""Measured-vs-analytic serving-latency calibration study.

Drives the ENTIRE measured-serving pipeline — seeded arrival clock ->
per-replica virtual queue (`serving/measured.ReplicaMeter`) -> sketch
deltas -> heartbeat wire encode/decode -> per-service merge -> quantile
readback -> online mu estimation — across a grid of load levels, and
tabulates measured p50/p99 against the analytic Erlang-C model the
autoscaler plans with (`serving/latency_model.py`).

Service times are drawn from a SEEDED exponential at the declared rate
``mu`` (the virtual-step stand-in for a decode wall), so the whole
study is a pure function of its seeds: two runs produce byte-identical
artifacts, which is what lets CI ``cmp`` them and commit the result as
``reproduce/serving/measured_calibration.json``. Every row also merges
its replica deltas in several seeded shuffles of arrival order and
asserts the merged sketch encodes byte-identically — the
order-independence contract of ``obs/quantiles.py``.

The headline calibration finding the table documents: at one replica
the measured p99 tracks Erlang-C within a few percent, but at higher
replica counts the round-robin request split (c independent queues)
measures markedly WORSE than the central-queue M/M/c idealization —
the analytic model is optimistic exactly where the autoscaler most
needs headroom, which is why measured p99 (not the model) is the
scaling signal once samples exist.

``--loopback`` appends a physical-loopback smoke: a REAL
PhysicalScheduler + stub worker daemon exchange measured deltas over
the live gRPC Done path, and the artifact records the (deterministic)
outcome booleans — measured samples reached the tier, measured p99 was
exported, the autoscaler's scale-up was driven by the measured breach
(the analytic model alone wanted fewer replicas), and mu was refined.

``--check`` gates: every row inside the calibration envelope, mu
recovered within tolerance, and (with --loopback) every outcome true.

The committed study:
    python scripts/drivers/serving_measured_calibration.py \
        --out reproduce/serving/measured_calibration.json --check
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

from shockwave_tpu.core.durable_io import write_text_atomic  # noqa: E402
from shockwave_tpu.obs.quantiles import QuantileSketch, merge_all  # noqa: E402
from shockwave_tpu.serving.latency_model import (p50_latency,  # noqa: E402
                                                 p99_latency)
from shockwave_tpu.serving.load import DiurnalLoad  # noqa: E402
from shockwave_tpu.serving.measured import (ArrivalClock,  # noqa: E402
                                            ReplicaMeter,
                                            ServiceMeasuredState)

ARTIFACT_SCHEMA = 1
#: Steps between delta takes inside one replica drive (exercises the
#: multi-delta merge path, not just one big sketch).
DELTA_EVERY_STEPS = 256
#: Merge-order shuffles per row (plus the sorted order).
MERGE_SHUFFLES = 3


def drive_replica(load, seed, horizon_s, replica_index, num_replicas,
                  mu, batch_size, tokens_per_request):
    """One replica's full measured pipeline at virtual speed: seeded
    exponential service walls stand in for decode-step timing. Returns
    the list of wire-encoded deltas the replica would heartbeat."""
    service_rng = np.random.RandomState(seed * 1009 + replica_index)
    meter = ReplicaMeter(
        ArrivalClock(load, seed, horizon_s, replica_index=replica_index,
                     num_replicas=num_replicas),
        batch_size=batch_size, tokens_per_request=tokens_per_request)
    deltas, steps = [], 0
    # Event-driven virtual drive: jump idle gaps (the driver owns the
    # timeline), then serve one batch per step with a service wall
    # ~ Exp(batch/mu) — length-proportional KV-cached decode at the
    # declared rate.
    while meter.idle_to_next_arrival():
        meter.step(float(service_rng.exponential(batch_size / mu)))
        steps += 1
        if steps % DELTA_EVERY_STEPS == 0:
            delta = meter.take_delta()
            if delta is not None:
                deltas.append(delta)
    final = meter.take_delta()
    if final is not None:
        deltas.append(final)
    return deltas


def merged_order_independent(deltas, seed):
    """Merge the deltas in sorted order plus seeded shuffles; return
    (merged sketch, True iff every order encoded byte-identically)."""
    sketches = [QuantileSketch.from_payload(d["sketch"]) for d in deltas]
    reference = merge_all(sketches).encode()
    rng = np.random.RandomState(seed)
    ok = True
    for _ in range(MERGE_SHUFFLES):
        order = list(rng.permutation(len(sketches)))
        ok = ok and merge_all([sketches[i] for i in order]
                              ).encode() == reference
    return QuantileSketch.decode(reference), ok


def calibration_row(rho, replicas, args):
    lam = rho * replicas * args.mu
    load = DiurnalLoad(base_rps=lam, peak_rps=lam, period_s=0.0)
    state = ServiceMeasuredState(args.mu, args.tokens_per_request,
                                 mu_prior_weight=args.mu_prior_weight)
    all_deltas = []
    for r in range(replicas):
        deltas = drive_replica(load, args.seed, args.horizon_s, r,
                               replicas, args.mu, args.batch_size,
                               args.tokens_per_request)
        all_deltas.extend(deltas)
        for delta in deltas:
            state.ingest(delta)
    merged, order_ok = merged_order_independent(all_deltas, args.seed)
    assert merged.count == state.requests_total
    analytic_p99 = p99_latency(lam, replicas, args.mu)
    analytic_p50 = p50_latency(lam, replicas, args.mu)
    measured_p99 = merged.quantile(0.99)
    measured_p50 = merged.quantile(0.5)
    return {
        "rho": rho,
        "replicas": replicas,
        "lambda_rps": round(lam, 4),
        "samples": merged.count,
        "deltas_merged": len(all_deltas),
        "merge_order_independent": order_ok,
        "measured_p50_s": round(measured_p50, 6),
        "measured_p99_s": round(measured_p99, 6),
        "analytic_p50_s": round(analytic_p50, 6),
        "analytic_p99_s": round(analytic_p99, 6),
        "p99_ratio": round(measured_p99 / analytic_p99, 4),
        "tokens_per_s_busy": round(state.measured_tokens_per_s(), 3),
        "mu_estimate": round(state.mu_estimate(), 4),
        "mu_declared": args.mu,
    }


# ----------------------------------------------------------------------
# Physical loopback: measured telemetry over the live gRPC Done path
# ----------------------------------------------------------------------

class LoopbackWorkerStub:
    """Stub worker daemon for the loopback: every dispatched replica
    inits its lease, then Done-reports with the prepared measured
    sketch blob on the iterator-log channel — the live gRPC path the
    real worker daemon uses."""

    def __init__(self, sched_port, worker_port, report_blob):
        import threading

        from shockwave_tpu.runtime.clients import WorkerToSchedulerClient
        from shockwave_tpu.runtime.servers import serve_worker
        self._threading = threading
        self._sched_port = sched_port
        self._report_blob = report_blob
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.server = serve_worker(worker_port, {
            "RunJob": self._run_job, "KillJob": self._kill_job,
            "Reset": self._reset, "Shutdown": self._reset,
        })
        self.worker_ids, _ = self._client.register_worker(
            "v5e", "127.0.0.1", worker_port, 4)

    def _kill_job(self, job_id):
        pass

    def _reset(self):
        pass

    def _run_job(self, jobs, worker_id, round_id):
        self._threading.Thread(target=self._execute,
                               args=(jobs, worker_id),
                               daemon=True).start()

    def _execute(self, jobs, worker_id):
        import time as _time

        from shockwave_tpu.runtime.clients import IteratorToSchedulerClient
        try:
            for j in jobs:
                it = IteratorToSchedulerClient(
                    j["job_id"], worker_id, "localhost", self._sched_port)
                it.init()
            _time.sleep(0.3)
            self._client.notify_done(
                [j["job_id"] for j in jobs], worker_id,
                [25] * len(jobs), [0.8] * len(jobs),
                iterator_logs=[self._report_blob] * len(jobs))
        except Exception as e:  # noqa: BLE001 - teardown race
            print(f"loopback stub report dropped: {e}", file=sys.stderr)

    def stop(self):
        self.server.stop(grace=0)


def run_loopback(args):
    """Real PhysicalScheduler + stub worker daemon: replica dispatches
    come back with measured sketch deltas on the Done heartbeat whose
    p99 breaches the SLO the analytic model says is safe — the
    autoscaler must scale up on the MEASURED evidence. Returns
    deterministic outcome booleans for the artifact."""
    import socket
    import threading
    import time as _time

    from shockwave_tpu.core.trace import make_serving_job
    from shockwave_tpu.obs import names as obs_names
    from shockwave_tpu.sched.physical import PhysicalScheduler
    from shockwave_tpu.sched.scheduler import SchedulerConfig
    from shockwave_tpu.serving.latency_model import replicas_for_slo
    from shockwave_tpu.serving.measured import encode_report
    from shockwave_tpu.solver import get_policy

    def free_port():
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    slo_p99_s = 0.5
    # Low offered load: the ANALYTIC model wants exactly one replica.
    base_rps, mu = 2.0, 25.0
    assert replicas_for_slo(base_rps * 1.15, mu, slo_p99_s, 4) == 1

    # Measured evidence of a breach: the replica actually serves at
    # HALF the declared rate (chip slower than the trace claims), so an
    # overloaded virtual queue produces a p99 well over the SLO and the
    # mu estimate must pull away from the analytic prior — both signals
    # the loopback asserts end to end (seeded, deterministic).
    hot = DiurnalLoad(40.0, 40.0, 0.0)
    rng = np.random.RandomState(args.seed)
    meter = ReplicaMeter(ArrivalClock(hot, args.seed, 30.0), 1, 64)
    while meter.idle_to_next_arrival():
        meter.step(float(rng.exponential(2.0 / mu)))
    breach_delta = meter.take_delta()
    breach_sketch = QuantileSketch.from_payload(breach_delta["sketch"])
    assert breach_sketch.quantile(0.99) > slo_p99_s
    report_blob = "\n".join([
        "[2026-01-01 00:00:00] [PROGRESS] [STEPS] 25",
        "[2026-01-01 00:00:00] [PROGRESS] [DURATION] 0.8",
        "[2026-01-01 00:00:00] [SERVING] [MEASURED] "
        + encode_report(breach_delta),
    ])

    sched_port, worker_port = free_port(), free_port()
    sched = PhysicalScheduler(
        get_policy("max_min_fairness"),
        throughputs_file=args.throughputs,
        config=SchedulerConfig(
            time_per_iteration=2.0, max_rounds=8,
            serving={"measured_min_samples": 1, "mu_prior_weight": 16.0}),
        expected_num_workers=4, port=sched_port)

    stub = LoopbackWorkerStub(sched_port, worker_port, report_blob)
    outcome = {"measured_samples_reported": False,
               "measured_p99_exported": False,
               "measured_drove_scale_up": False,
               "mu_refined": False,
               "analytic_only_target": 1}
    try:
        svc = make_serving_job(
            base_rps=base_rps, peak_rps=base_rps, period_s=0.0,
            lifetime_s=3600.0, slo_p99_s=slo_p99_s, tokens_per_request=64,
            decode_tokens_per_s=64 * mu, max_replicas=4)
        sched.add_job(svc)
        threading.Thread(target=sched.run, daemon=True).start()
        reg = sched.obs
        deadline = _time.time() + 40  # swtpu-check: ignore[determinism]
        while _time.time() < deadline:  # swtpu-check: ignore[determinism]
            with sched._lock:
                samples = reg.registry.value(
                    obs_names.SERVING_MEASURED_SAMPLES_TOTAL, service="0")
                target = reg.registry.value(
                    obs_names.SERVING_TARGET_REPLICAS, service="0")
            if samples > 0 and target >= 2:
                break
            _time.sleep(0.2)
        with sched._lock:
            registry = reg.registry
            samples = registry.value(
                obs_names.SERVING_MEASURED_SAMPLES_TOTAL, service="0")
            measured_p99 = registry.value(
                obs_names.SERVING_MEASURED_P99_SECONDS, service="0")
            target = registry.value(obs_names.SERVING_TARGET_REPLICAS,
                                    service="0")
            mu_est = registry.value(obs_names.SERVING_MU_ESTIMATE,
                                    service="0")
            tier_svc = (list(sched._serving_tier.services.values())[0]
                        if sched._serving_tier is not None else None)
        outcome["measured_samples_reported"] = samples > 0
        outcome["measured_p99_exported"] = measured_p99 > slo_p99_s
        outcome["measured_drove_scale_up"] = target >= 2
        outcome["mu_refined"] = (
            tier_svc is not None
            and abs(mu_est - tier_svc.mu_analytic) > 1e-9
            and abs(tier_svc.mu - mu_est) < 1e-9)
    finally:
        sched._done_event.set()
        stub.stop()
        sched._server.stop(grace=0)
    return outcome


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rhos", default="0.2,0.4,0.6,0.8,0.9",
                   help="offered-load levels (lambda / (c * mu))")
    p.add_argument("--replicas", default="1,2,4",
                   help="replica counts to calibrate at")
    p.add_argument("--mu", type=float, default=20.0,
                   help="declared per-replica service rate (req/s)")
    p.add_argument("--horizon_s", type=float, default=2000.0,
                   help="virtual drive length per (rho, replicas) cell")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--tokens_per_request", type=int, default=64)
    p.add_argument("--mu_prior_weight", type=float, default=64.0)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--envelope", default="0.7:6.0",
                   help="--check: measured/analytic p99 ratio bounds")
    p.add_argument("--mu_tolerance", type=float, default=0.05,
                   help="--check: |mu_estimate/mu - 1| bound")
    p.add_argument("--loopback", action="store_true",
                   help="append the physical gRPC loopback smoke")
    p.add_argument("--throughputs",
                   default=os.path.join(os.path.dirname(__file__), "..",
                                        "..", "data",
                                        "tacc_throughputs.json"))
    p.add_argument("--out", default="serving_measured_calibration.json")
    p.add_argument("--check", action="store_true",
                   help="exit nonzero outside the calibration envelope")
    args = p.parse_args(argv)

    rhos = [float(x) for x in args.rhos.split(",") if x]
    replica_counts = [int(x) for x in args.replicas.split(",") if x]
    rows = [calibration_row(rho, c, args)
            for c in replica_counts for rho in rhos]

    artifact = {
        "schema": ARTIFACT_SCHEMA,
        "study": "serving_measured_calibration",
        "config": {
            "rhos": rhos, "replicas": replica_counts, "mu": args.mu,
            "horizon_s": args.horizon_s, "batch_size": args.batch_size,
            "tokens_per_request": args.tokens_per_request,
            "mu_prior_weight": args.mu_prior_weight, "seed": args.seed,
        },
        "rows": rows,
        "merge_order_independent": all(r["merge_order_independent"]
                                       for r in rows),
        "measured_sample_coverage": sum(r["samples"] for r in rows),
    }
    if args.loopback:
        artifact["loopback"] = run_loopback(args)

    write_text_atomic(args.out,
                      json.dumps(artifact, sort_keys=True, indent=1)
                      + "\n")
    print(json.dumps({"out": args.out, "rows": len(rows),
                      "samples": artifact["measured_sample_coverage"],
                      "merge_order_independent":
                      artifact["merge_order_independent"]}))

    if not args.check:
        return 0
    lo, hi = (float(x) for x in args.envelope.split(":"))
    failures = []
    if artifact["measured_sample_coverage"] <= 0:
        failures.append("no measured samples at all")
    if not artifact["merge_order_independent"]:
        failures.append("sketch merge depended on delta order")
    for row in rows:
        if not lo <= row["p99_ratio"] <= hi:
            failures.append(
                f"rho={row['rho']} c={row['replicas']}: p99 ratio "
                f"{row['p99_ratio']} outside [{lo}, {hi}]")
        if abs(row["mu_estimate"] / args.mu - 1.0) > args.mu_tolerance:
            failures.append(
                f"rho={row['rho']} c={row['replicas']}: mu estimate "
                f"{row['mu_estimate']} off by more than "
                f"{args.mu_tolerance:.0%}")
    for key, value in artifact.get("loopback", {}).items():
        if value is False:
            failures.append(f"loopback outcome {key} is false")
    for failure in failures:
        print(f"CHECK FAIL: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
