"""Seeded violations for the thread-roots pass.

An unresolvable spawn target is a thread the race detector cannot see
behind; resolvable spawns (named function, bound method) must NOT be
flagged.
"""
import threading

HANDLERS = {"run": print}

# Module-level spawn (driver-script shape): discovery must look at
# top-level statements too, not just function bodies.
SPAWNED_AT_IMPORT = threading.Thread(target=HANDLERS["run"], daemon=True)  # SEEDED


def work():
    return 1


def spawn_resolvable():
    # Named module function: resolves, no finding.
    threading.Thread(target=work, daemon=True).start()


def spawn_opaque():
    threading.Thread(target=HANDLERS["run"], daemon=True).start()  # SEEDED


class Looper:
    def __init__(self, callbacks):
        self._callbacks = dict(callbacks)

    def start(self):
        # Bound method: resolves, no finding.
        threading.Thread(target=self._loop, daemon=True).start()
        # Dynamic callable out of a runtime dict: opaque.
        threading.Timer(1.0, self._callbacks["tick"]).start()  # SEEDED

    def _loop(self):
        return self._callbacks
