"""Real-process stub worker for fault-injection tests.

Registers with the scheduler and simulates job execution at a fixed
throughput (like test_runtime.StubWorkerDaemon) but as a genuine OS
process, so tests can SIGKILL it and exercise the scheduler's worker
liveness machinery against a genuinely dead daemon. Deliberately jax-free: it
imports only the runtime control plane.

`--freeze_after_round N` makes every RunJob with round_id > N a silent
no-op (accepted, never executed, never reported) — the deterministic
"worker wedged mid-round" hook, so tests never depend on racing a
SIGKILL against the stub's execution sleep.

Gray failures: ``degrade`` rules in $SWTPU_FAULTS (method "execute")
scale the stub's simulated step rate per RunJob — the worker keeps
answering Ping and renewing leases while computing at a fraction of its
speed, which is exactly the straggler the scheduler's health layer must
catch and quarantine.
"""
import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from shockwave_tpu.runtime import faults  # noqa: E402
from shockwave_tpu.runtime.clients import (IteratorToSchedulerClient,  # noqa: E402
                                           WorkerToSchedulerClient)
from shockwave_tpu.runtime.resilience import EpochFence  # noqa: E402
from shockwave_tpu.runtime.servers import serve_worker  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sched_port", type=int, required=True)
    p.add_argument("--worker_port", type=int, required=True)
    p.add_argument("--num_chips", type=int, default=1)
    p.add_argument("--throughput", type=float, default=100.0)
    p.add_argument("--exec_time", type=float, default=0.3)
    p.add_argument("--freeze_after_round", type=int, default=None)
    p.add_argument("--state_file", required=True,
                   help="JSON file the parent polls for worker ids/pid")
    args = p.parse_args()

    client = WorkerToSchedulerClient("localhost", args.sched_port)
    shutdown = threading.Event()
    box = {}

    def run_job(jobs, worker_id, round_id):
        if (args.freeze_after_round is not None
                and round_id > args.freeze_after_round):
            print(f"FROZEN worker={worker_id} round={round_id}", flush=True)
            return

        def execute():
            max_steps = 10**9
            for j in jobs:
                it = IteratorToSchedulerClient(j["job_id"], worker_id,
                                               "localhost", args.sched_port)
                max_steps, _, _ = it.init()
            # Gray-failure hook: a degrade rule scales the simulated
            # step rate — liveness (this RPC traffic) is untouched.
            slowdown = faults.get_injector().slowdown("execute")
            time.sleep(args.exec_time)
            steps = [min(int(args.throughput * slowdown
                             * box["round_duration"]),
                         j["num_steps"], int(max_steps)) for j in jobs]
            client.notify_done([j["job_id"] for j in jobs], worker_id, steps,
                               [args.exec_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    # Same epoch fence as the real daemon (runtime/worker.py): a
    # deposed leader's dispatches — and its parting Shutdown — are
    # rejected, and an advanced epoch re-points the report channel at
    # the promoted leader (HA failover drills lean on both).
    fence = EpochFence()
    server = serve_worker(args.worker_port, {
        "RunJob": run_job, "KillJob": lambda j: None,
        "Reset": lambda: None, "Shutdown": shutdown.set,
    }, fence=fence,
        on_epoch_advance=lambda epoch: client.refresh_endpoint())
    worker_ids, round_duration = client.register_worker(
        "v5e", "127.0.0.1", args.worker_port, args.num_chips)
    box["round_duration"] = round_duration
    with open(args.state_file, "w") as f:
        json.dump({"worker_ids": worker_ids, "pid": os.getpid()}, f)
    shutdown.wait()
    server.stop(grace=0)


if __name__ == "__main__":
    main()
