from .metadata import JobMetadata
from .planner import ShockwavePlanner

__all__ = ["JobMetadata", "ShockwavePlanner"]
