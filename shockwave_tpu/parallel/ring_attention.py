"""Ring attention: sequence-parallel exact attention over the `sp` axis.

Each device holds one sequence shard of Q, K, V. K/V blocks rotate around
the ring with `lax.ppermute` while every device accumulates its queries'
attention over all blocks using the numerically-stable online-softmax
(flash-attention) update. Communication overlaps the per-block compute,
FLOPs stay on the MXU, and per-device memory is O(seq/sp).

This is the long-context capability the reference lacks entirely
(SURVEY.md §5: no sequence parallelism anywhere); here it is first-class
so workloads can scale past single-chip sequence-length limits.
References: Liu et al., "Ring Attention with Blockwise Transformers"
(arXiv:2310.01889); the public scaling-book collective patterns.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .compat import to_varying

NEG_INF = -1e30


def _block_attention(q, k, v, m_prev, l_prev, o_prev, causal_mask=None):
    """One online-softmax accumulation step.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D)
    m_prev/l_prev: (B, H, Tq) running max / normalizer
    o_prev: (B, Tq, H, D) running (unnormalized) output
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    scores = scores.astype(jnp.float32)
    if causal_mask is not None:
        scores = jnp.where(causal_mask, scores, NEG_INF)
    m_new = jnp.maximum(m_prev, scores.max(axis=-1))
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l_prev * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o_prev * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-shard body: rotate K/V around the ring, accumulate attention."""
    axis_size = lax.psum(1, axis_name)
    axis_index = lax.axis_index(axis_name)
    batch, q_len, num_heads, head_dim = q.shape

    m = jnp.full((batch, num_heads, q_len), NEG_INF, jnp.float32)
    l = jnp.zeros((batch, num_heads, q_len), jnp.float32)
    o = jnp.zeros(q.shape, jnp.float32)
    # Mark the accumulators as device-varying along the ring axis so the
    # scan carry types line up with the shard-resident outputs
    # (identity on jax versions without shard_map variance typing).
    m, l, o = jax.tree.map(lambda x: to_varying(x, axis_name), (m, l, o))

    def make_mask(step):
        if not causal:
            return None
        # After `step` rotations this device holds the KV block that
        # originated on device (axis_index - step) mod axis_size.
        kv_index = jnp.mod(axis_index - step, axis_size)
        q_pos = axis_index * q_len + jnp.arange(q_len)
        k_pos = kv_index * q_len + jnp.arange(q_len)
        return (q_pos[:, None] >= k_pos[None, :])[None, None]

    def body(carry, step):
        k_blk, v_blk, m, l, o = carry
        m, l, o = _block_attention(q, k_blk, v_blk, m, l, o, make_mask(step))
        # Pass KV to the next device in the ring (overlaps next compute).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (k, v, m, l, o), _ = lax.scan(
        body, (k, v, m, l, o), jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False):
    """Exact attention with Q/K/V sharded along sequence over `axis_name`.

    Args:
      q, k, v: (batch, seq, heads, head_dim), seq sharded over axis_name.
    Returns: attention output with the same sharding as q.
    """
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Unsharded attention for correctness checks."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    scores = scores.astype(jnp.float32)
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((q_len, k_len), bool))[None, None]
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v).astype(q.dtype)
