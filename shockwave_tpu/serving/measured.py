"""Measured serving telemetry: the replica-side request clock and the
service-side merge state that closes the autoscaler loop.

The serving tier's quality accounting was purely analytic (M/M/c over a
configured ``mu``); this module puts real request-level measurements on
the same deterministic load curve:

- **ArrivalClock** — a seeded Poisson arrival stream drawn from the
  SAME ``serving/load.DiurnalLoad`` curve the simulator and autoscaler
  plan with (Lewis-Shedler thinning against a static rate bound), split
  round-robin across ``num_replicas`` so each replica serves its
  deterministic share. Pure function of (load spec, seed): no wall
  clocks, no unseeded RNG — the determinism analyzer pass covers this
  module.
- **ReplicaMeter** — the per-replica virtual queue: each physical
  decode step contributes its *measured* wall duration; the meter
  admits pending synthetic arrivals (up to the batch size), stamps each
  request's admission->last-token latency on the virtual service clock,
  and accumulates samples into a mergeable ``obs/quantiles``
  QuantileSketch plus tokens/requests/busy counters. ``take_delta()``
  yields the compact payload a replica ships on its Done heartbeat.
- **ServiceMeasuredState** — the scheduler-side fold: per-service
  merged sketches (cumulative + per-round window), measured tokens/s,
  and online ``mu`` re-estimation — measured service rate blended with
  the analytic prior by sample count, so the analytic value is the
  cold-start fallback and measurement takes over as evidence
  accumulates. With zero samples every readback equals the analytic
  input exactly, which is what keeps simulation replays bit-identical.

Report lines ride the lease-renewal heartbeat
(``UpdateLeaseRequest.measured_reports`` — a sticky replica holds one
extended lease for its whole life, so renewals are its per-round
channel), with unsent deltas flushed to the iterator log at exit and
arriving with Done; deltas carry a (round, seq) stamp so the tier
dedupes double delivery. ``encode_report`` / ``find_reports`` define
the line format, marked by ``MEASURED_REPORT_MARKER``.
"""
from __future__ import annotations

import json
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..obs.quantiles import QuantileSketch
from .load import DiurnalLoad

#: Wire version of the Done-heartbeat measured payload.
REPORT_VERSION = 1
#: Substring marking a measured-telemetry line in the iterator log
#: (the scheduler's log fold routes these to the serving tier instead
#: of the job timeline).
MEASURED_REPORT_MARKER = "SWTPU-SERVING-MEASURED "


def derive_arrival_seed(spike_seed: Optional[int],
                        replica_index: int) -> int:
    """Deterministic per-replica arrival seed from the service's spike
    seed (0 when the trace carries none) and the replica index — every
    dispatch of replica k replays the same synthetic request stream."""
    base = int(spike_seed or 0)
    return (base * 1000003 + int(replica_index) * 7919) % (2 ** 31 - 1)


def _max_rate_bound(load: DiurnalLoad) -> float:
    """A static upper bound on load.rate(t): day-curve peak times the
    worst concurrent spike-multiplier product (spike intervals swept at
    their boundary points)."""
    day_max = max(load.peak_rps, load.base_rps)
    if not load.spikes:
        return day_max
    bounds = sorted({s.start for s in load.spikes}
                    | {s.start + s.duration for s in load.spikes})
    worst = 1.0
    for t in bounds:
        mult = 1.0
        for s in load.spikes:
            if s.active(t):
                mult *= s.multiplier
        worst = max(worst, mult)
    return day_max * worst


class ArrivalClock:
    """Seeded Poisson arrivals over a DiurnalLoad, filtered to one
    replica's round-robin share. Yields service-relative arrival times
    in increasing order; exhausts at ``horizon_s``."""

    def __init__(self, load: DiurnalLoad, seed: int, horizon_s: float,
                 replica_index: int = 0, num_replicas: int = 1,
                 phase_s: float = 0.0):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        self.load = load
        self.horizon_s = float(horizon_s)
        self.replica_index = int(replica_index) % int(num_replicas)
        self.num_replicas = int(num_replicas)
        self.phase_s = float(phase_s)
        # One shared stream per service seed: every replica draws the
        # SAME global arrival sequence (thinning consumes RNG draws in
        # lockstep), then keeps the indices assigned to it — so the
        # union over replicas is exactly the service's Poisson stream.
        self._rng = np.random.RandomState(int(seed))
        self._rate_bound = max(_max_rate_bound(load), 1e-9)
        self._t = 0.0
        self._global_index = 0

    def __iter__(self) -> Iterator[float]:
        return self

    def __next__(self) -> float:
        while True:
            self._t += float(self._rng.exponential(1.0 / self._rate_bound))
            if self._t >= self.horizon_s:
                raise StopIteration
            accept = (float(self._rng.random_sample()) * self._rate_bound
                      < self.load.rate(self._t + self.phase_s))
            if not accept:
                continue
            index = self._global_index
            self._global_index += 1
            if index % self.num_replicas == self.replica_index:
                return self._t


class ReplicaMeter:
    """Virtual request queue driven by measured decode-step durations.

    The meter keeps TWO clocks on one timeline: ``wall``, the measured
    time the replica has actually spent (every step advances it by the
    step's duration), and ``clock``, the service clock (the completion
    stamp of the last served batch). A step picks up to ``batch_size``
    requests that have arrived by its service start, runs for the
    measured duration, and completes them all at the step's end — the
    admission->last-token latency of request i is ``completion -
    arrival_i``. Crucially the service clock can never outrun the
    wall: a chip faster than the arrival rate IDLES (the step serves
    nothing) instead of consuming future arrivals early — otherwise a
    fast replica would "serve" hours of the request stream in seconds
    and report fictitious zero-latency samples."""

    def __init__(self, arrivals: Iterator[float], batch_size: int,
                 tokens_per_request: int):
        self._arrivals = iter(arrivals)
        self.batch_size = max(int(batch_size), 1)
        self.tokens_per_request = max(int(tokens_per_request), 1)
        self.wall = 0.0          # measured replica time spent
        self.clock = 0.0         # service clock (last batch completion)
        self._pending: List[float] = []
        self._stream_done = False
        self._span_start = 0.0   # wall at the last take_delta
        self._delta_sketch = QuantileSketch()
        self._delta_requests = 0
        self._delta_tokens = 0
        self._delta_busy_s = 0.0
        self._delta_span_s = 0.0

    def _pull_arrivals(self, until: float) -> None:
        """Keep at most one lookahead arrival beyond `until` buffered."""
        while not self._stream_done and (not self._pending
                                         or self._pending[-1] <= until):
            try:
                self._pending.append(next(self._arrivals))
            except StopIteration:
                self._stream_done = True
                return

    @property
    def exhausted(self) -> bool:
        """The arrival stream is drained and nothing is queued."""
        self._pull_arrivals(self.wall)
        return self._stream_done and not self._pending

    def idle_to_next_arrival(self) -> bool:
        """Virtual-time callers ONLY (the calibration driver owns its
        timeline): jump the wall forward to the next pending arrival
        instead of polling through the idle gap step by step. Returns
        False when the stream is drained. The physical replica never
        calls this — its wall is real time."""
        self._pull_arrivals(self.wall)
        if self._stream_done and not self._pending:
            return False
        if self._pending and self._pending[0] > self.wall:
            self.wall = self._pending[0]
        return True

    def step(self, duration_s: float) -> int:
        """Account one measured decode step; returns requests completed
        (0 for an idle step — nothing had arrived by the measured
        wall — or a drained stream)."""
        duration_s = max(float(duration_s), 0.0)
        self.wall += duration_s
        self._delta_span_s = self.wall - self._span_start
        self._pull_arrivals(self.wall)
        if not self._pending or self._pending[0] > self.wall:
            return 0                 # idle (or drained): nothing to serve
        start = max(self.clock, self._pending[0])
        ready = 0
        while (ready < len(self._pending) and ready < self.batch_size
               and self._pending[ready] <= start):
            ready += 1
        admitted = self._pending[:ready]
        del self._pending[:ready]
        completion = start + duration_s
        self.clock = completion
        for arrival in admitted:
            self._delta_sketch.add(completion - arrival)
        self._delta_requests += len(admitted)
        self._delta_tokens += len(admitted) * self.tokens_per_request
        self._delta_busy_s += duration_s
        return len(admitted)

    @property
    def pending_delta_requests(self) -> int:
        return self._delta_requests

    def take_delta(self) -> Optional[dict]:
        """The compact heartbeat payload since the last take (None when
        nothing was measured)."""
        if self._delta_requests == 0:
            return None
        delta = {
            "v": REPORT_VERSION,
            "sketch": self._delta_sketch.to_payload(),
            "requests": self._delta_requests,
            "tokens": self._delta_tokens,
            "busy_s": round(self._delta_busy_s, 6),
            "span_s": round(self._delta_span_s, 6),
        }
        self._span_start = self.wall
        self._delta_sketch = QuantileSketch()
        self._delta_requests = 0
        self._delta_tokens = 0
        self._delta_busy_s = 0.0
        self._delta_span_s = 0.0
        return delta


# ----------------------------------------------------------------------
# Heartbeat line format (iterator log -> Done RPC -> scheduler fold)
# ----------------------------------------------------------------------

def encode_report(delta: dict) -> str:
    """One measured-telemetry log line (canonical JSON after the
    marker, so identical deltas encode byte-identically)."""
    return MEASURED_REPORT_MARKER + json.dumps(
        delta, sort_keys=True, separators=(",", ":"))


def find_reports(lines: "list[str] | str") -> List[dict]:
    """Extract every measured payload from iterator-log content;
    malformed payloads are skipped (telemetry must never fail the
    Done path)."""
    if isinstance(lines, str):
        lines = lines.splitlines()
    out: List[dict] = []
    for line in lines:
        marker = line.find(MEASURED_REPORT_MARKER)
        if marker < 0:
            continue
        try:
            payload = json.loads(line[marker
                                      + len(MEASURED_REPORT_MARKER):])
        except ValueError:
            continue
        if isinstance(payload, dict) and payload.get("v") == REPORT_VERSION:
            out.append(payload)
    return out


# ----------------------------------------------------------------------
# Service-side merge + online mu estimation
# ----------------------------------------------------------------------

class ServiceMeasuredState:
    """Per-service fold of replica deltas; owned by ServingService and
    mutated only under the scheduler lock (the tier's synchronization
    domain)."""

    def __init__(self, mu_analytic: float, tokens_per_request: int,
                 mu_prior_weight: float = 64.0):
        self.mu_analytic = float(mu_analytic)
        self.tokens_per_request = max(int(tokens_per_request), 1)
        #: Pseudo-sample weight of the analytic prior in the blend.
        self.mu_prior_weight = float(mu_prior_weight)
        self.sketch_total = QuantileSketch()
        self.requests_total = 0
        self.tokens_total = 0
        self.busy_s_total = 0.0
        # Window accumulators, drained by the tier at each round
        # accounting point.
        self._window_sketch = QuantileSketch()
        self._window_requests = 0
        self._window_tokens = 0
        self._window_span_s = 0.0

    def ingest(self, delta: dict) -> None:
        sketch = QuantileSketch.from_payload(delta["sketch"])
        self.sketch_total.merge(sketch)
        self._window_sketch.merge(sketch)
        requests = int(delta.get("requests", 0))
        tokens = int(delta.get("tokens", 0))
        self.requests_total += requests
        self.tokens_total += tokens
        self.busy_s_total += float(delta.get("busy_s", 0.0))
        self._window_requests += requests
        self._window_tokens += tokens
        self._window_span_s += float(delta.get("span_s", 0.0))

    @property
    def has_samples(self) -> bool:
        return self.requests_total > 0

    def mu_estimate(self) -> float:
        """Service rate (requests/s per replica): measured tokens/s /
        tokens_per_request (latency_model.mu_from_tokens_per_s) blended
        with the analytic prior by sample count. Exactly the analytic
        value with zero samples (the sim-mode fallback)."""
        from .latency_model import mu_from_tokens_per_s
        measured = mu_from_tokens_per_s(self.measured_tokens_per_s(),
                                        self.tokens_per_request)
        if self.requests_total <= 0 or measured <= 0.0:
            return self.mu_analytic
        n = float(self.requests_total)
        w = self.mu_prior_weight
        return (w * self.mu_analytic + n * measured) / (w + n)

    def measured_tokens_per_s(self) -> float:
        """Cumulative measured decode throughput (tokens per busy
        second) — the mu-estimation numerator."""
        if self.busy_s_total <= 0.0:
            return 0.0
        return self.tokens_total / self.busy_s_total

    def take_window(self) -> Optional[dict]:
        """Drain the per-round window: quantiles + rates of the samples
        ingested since the last call (None when no fresh samples)."""
        if self._window_requests == 0:
            return None
        sketch = self._window_sketch
        window = {
            "requests": self._window_requests,
            "tokens": self._window_tokens,
            "span_s": round(self._window_span_s, 6),
            "p50_s": sketch.quantile(0.5),
            "p99_s": sketch.quantile(0.99),
            "mean_s": sketch.mean(),
        }
        self._window_sketch = QuantileSketch()
        self._window_requests = 0
        self._window_tokens = 0
        self._window_span_s = 0.0
        return window


__all__ = ["ArrivalClock", "ReplicaMeter", "ServiceMeasuredState",
           "derive_arrival_seed", "encode_report", "find_reports",
           "MEASURED_REPORT_MARKER", "REPORT_VERSION"]
