"""Durable scheduler state: write-ahead journal + compacting snapshots.

The physical scheduler is a single long-lived process driving round-based
leases; on preemptible capacity its own death is routine, not exceptional.
This module gives it the standard durability recipe:

- **Write-ahead journal**: an append-only file of CRC-framed JSON records
  (job lifecycle, worker membership, round bookkeeping, micro-task
  progress, planner sync, solve outcomes). Every append is flushed and
  fsync'd before the mutation is considered durable. A torn tail — the
  partial record a crash mid-append leaves behind — is detected by the
  length/CRC frame and discarded on the next open, never fatal.

- **Compacting snapshots**: a pickle of the scheduler's durable state,
  written atomically (tmp + fsync + rename + directory fsync) with the
  previous snapshot retained as a fallback. Each snapshot records the
  journal sequence it covers; segments the PREVIOUS snapshot no longer
  needs are deleted — the `.prev` fallback must keep its replay tail —
  so the journal's size is bounded by two snapshot intervals of events.

- **Recovery**: `load_state` returns the newest loadable snapshot plus
  every journal event after it, in order. The scheduler rebuilds itself
  by restoring the snapshot and replaying the events
  (`Scheduler.restore_from_durable_state`).

State-dir layout:

    <state_dir>/
      snapshot.pkl           # latest snapshot (atomic replace)
      snapshot.pkl.prev      # previous snapshot (corruption fallback)
      journal.<seq12>.log    # CRC-framed segments; <seq12> = first seq

Record frame: ``<u32 payload_len> <u32 crc32(payload)> <payload>`` where
payload is UTF-8 JSON ``{"seq": n, "type": str, "t": wall, "data": {...}}``.
Files start with an 8-byte magic so an unrelated file is rejected loudly
rather than replayed.

**Leader epochs (control-plane HA):** when the scheduler runs under the
HA controller (`sched/ha.py`), every record additionally carries the
writer's fenced leader epoch (``"epoch": n``). Along the sequence chain
epochs are non-decreasing in any correct history — a record whose epoch
is LOWER than one already seen at an earlier-or-equal sequence was
written by a deposed leader that had not yet noticed its fencing (the
wedged-but-alive gray case). `filter_epoch_chain` deterministically
discards those stale-writer orphans; `load_state` applies it so a
promoted standby never replays a zombie's writes, and each HA
incarnation opens a FRESH segment (`rotate_on_open`) so a zombie's
leftover file descriptor can only ever append to a segment the new
leader no longer writes.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.durable_io import (FOOTER_OK, fsync_dir as _fsync_dir,
                               verify_footer, write_durable)

logger = logging.getLogger("shockwave_tpu.sched.journal")

JOURNAL_MAGIC = b"SWTPUJ1\n"
SNAPSHOT_MAGIC = b"SWTPUS1\n"
_FRAME = struct.Struct("<II")
_SEGMENT_RE = re.compile(r"^journal\.(\d{12})\.log$")

SNAPSHOT_NAME = "snapshot.pkl"

#: Tail status of a journal read.
TAIL_CLEAN = "clean"      # file ends exactly at a record boundary
TAIL_TORN = "torn"        # trailing partial/corrupt record discarded


class JournalError(Exception):
    """Unrecoverable journal problem (bad magic, unreadable file)."""


def _scan_records(data: bytes) -> Tuple[List[dict], int, str]:
    """Parse framed records out of `data` (magic already stripped).

    Returns (records, valid_byte_length, tail_status). Parsing stops at
    the first bad frame — a crash mid-append leaves exactly one torn
    record at the tail, and anything after a bad frame is unframed
    garbage by construction.
    """
    records: List[dict] = []
    off = 0
    n = len(data)
    while off < n:
        if n - off < _FRAME.size:
            return records, off, TAIL_TORN
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + length
        if length == 0 or end > n:
            return records, off, TAIL_TORN
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            return records, off, TAIL_TORN
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return records, off, TAIL_TORN
        records.append(rec)
        off = end
    return records, off, TAIL_CLEAN


def read_journal(path: str, strict: bool = False) -> Tuple[List[dict], str]:
    """Read one journal segment. Returns (records, tail_status).

    A torn tail (partial last record from a crash mid-append) is
    discarded; with `strict`, it raises instead (fsck uses strict to
    report, recovery never does).
    """
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(JOURNAL_MAGIC):
        raise JournalError(f"{path}: bad journal magic")
    records, _, status = _scan_records(blob[len(JOURNAL_MAGIC):])
    if status != TAIL_CLEAN:
        if strict:
            raise JournalError(f"{path}: torn tail after {len(records)} "
                               "records")
        logger.warning("journal %s has a torn tail; %d valid records kept",
                       path, len(records))
    return records, status


class JournalWriter:
    """Append-only CRC-framed record writer with per-append fsync.

    Opening an existing segment first truncates any torn tail so new
    appends land at a record boundary (otherwise everything after the
    crash leftover would be unreadable).
    """

    def __init__(self, path: str):
        self.path = path
        if os.path.exists(path):
            with open(path, "rb") as f:
                blob = f.read()
            if not blob.startswith(JOURNAL_MAGIC):
                raise JournalError(f"{path}: bad journal magic")
            _, valid, status = _scan_records(blob[len(JOURNAL_MAGIC):])
            self._f = open(path, "r+b")
            if status != TAIL_CLEAN:
                logger.warning("truncating torn tail of %s at byte %d",
                               path, len(JOURNAL_MAGIC) + valid)
                self._f.truncate(len(JOURNAL_MAGIC) + valid)
            self._f.seek(len(JOURNAL_MAGIC) + valid)
        else:
            self._f = open(path, "w+b")
            self._f.write(JOURNAL_MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
            _fsync_dir(os.path.dirname(path) or ".")

    def append(self, record: dict, sync: bool = True) -> int:
        """Append one framed record; returns the framed byte count.
        With `sync` (the default) the record is fsync'd before return —
        required for write-ahead semantics. Audit-only records may pass
        sync=False: they ride to disk with the next durable append, and
        losing the tail of them in a crash costs nothing (their replay
        handlers are no-ops)."""
        payload = json.dumps(record, separators=(",", ":"),
                             default=str).encode("utf-8")
        self._f.write(_FRAME.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        return _FRAME.size + len(payload)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:  # already closed / fs went away
            pass


# ----------------------------------------------------------------------
# Snapshots
# ----------------------------------------------------------------------

def write_snapshot(state_dir: str, payload: dict) -> str:
    """Atomically persist `payload`: tmp + fsync + rename + dir fsync,
    retaining the previous snapshot as `.prev` for corruption fallback."""
    return write_durable(
        os.path.join(state_dir, SNAPSHOT_NAME),
        pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL),
        SNAPSHOT_MAGIC)


def _read_snapshot_file(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    status, body = verify_footer(blob, SNAPSHOT_MAGIC)
    if status != FOOTER_OK:
        # Unlike trainer checkpoints there is no legacy footer-less
        # snapshot format, so "missing" is corruption too.
        logger.warning("snapshot %s integrity check failed (%s); "
                       "rejecting", path, status)
        return None
    try:
        payload = pickle.loads(body)
    except Exception:  # noqa: BLE001 - any unpickle failure means corrupt
        logger.exception("snapshot %s unreadable despite valid CRC", path)
        return None
    return payload if isinstance(payload, dict) else None


def load_snapshot(state_dir: str) -> Optional[dict]:
    """Newest loadable snapshot: current first, `.prev` fallback."""
    path = os.path.join(state_dir, SNAPSHOT_NAME)
    payload = _read_snapshot_file(path)
    if payload is not None:
        return payload
    prev = _read_snapshot_file(path + ".prev")
    if prev is not None:
        logger.warning("snapshot %s unusable; recovered from previous "
                       "snapshot", path)
    return prev


# ----------------------------------------------------------------------
# Segments / recovery
# ----------------------------------------------------------------------

def list_segments(state_dir: str) -> List[str]:
    """Journal segment paths in sequence order."""
    try:
        names = os.listdir(state_dir)
    except OSError:
        return []
    segs = [(int(m.group(1)), os.path.join(state_dir, name))
            for name in names
            for m in (_SEGMENT_RE.match(name),) if m]
    return [path for _, path in sorted(segs)]


def _segment_path(state_dir: str, start_seq: int) -> str:
    return os.path.join(state_dir, f"journal.{start_seq:012d}.log")


def has_state(state_dir: str) -> bool:
    """Whether `state_dir` holds any prior scheduler state — judged by
    what recovery would actually use (load_snapshot consults the .prev
    fallback, so a dir whose current snapshot is corrupt but whose
    previous one loads still counts as stateful)."""
    if load_snapshot(state_dir):
        return True
    for path in list_segments(state_dir):
        try:
            records, _ = read_journal(path)
        except JournalError:
            return True  # unreadable state still counts as "present"
        if records:
            return True
    return False


def filter_epoch_chain(events: List[dict]) -> Tuple[List[dict], List[dict]]:
    """Drop stale-writer orphans from a seq-sorted event list.

    Invariant of a correct single-writer-per-epoch history: walking the
    records in sequence order, the leader epoch never decreases, and no
    sequence number is written twice. A deposed leader that keeps
    appending (frozen across its own fencing) violates both — its
    records carry an epoch LOWER than the chain's high-water mark, or
    duplicate a sequence the new leader already claimed. Rule, applied
    deterministically:

    - for duplicate seqs, the record with the HIGHEST epoch wins
      (epoch-less duplicates lose to any epoch-tagged record);
    - a record whose epoch is below the high-water epoch of the kept
      chain so far is discarded;
    - records with no epoch field (pre-HA journals, HA disabled) are
      never discarded on epoch grounds.

    Returns ``(kept, orphans)``; input must already be sorted by seq.
    """
    kept: List[dict] = []
    orphans: List[dict] = []
    max_epoch: Optional[int] = None
    i, n = 0, len(events)
    while i < n:
        j = i
        seq = int(events[i].get("seq", 0))
        while j < n and int(events[j].get("seq", 0)) == seq:
            j += 1
        group = events[i:j]
        winner = max(
            group, key=lambda r: -1 if r.get("epoch") is None
            else int(r["epoch"]))
        orphans.extend(r for r in group if r is not winner)
        epoch = winner.get("epoch")
        if (epoch is not None and max_epoch is not None
                and int(epoch) < max_epoch):
            orphans.append(winner)
        else:
            kept.append(winner)
            if epoch is not None:
                max_epoch = max(max_epoch or 0, int(epoch))
        i = j
    return kept, orphans


@dataclass
class RecoveredState:
    """Everything recovery needs: newest snapshot (or None) plus every
    journal event after it, in sequence order."""
    snapshot: Optional[dict] = None
    events: List[dict] = field(default_factory=list)
    tail_status: str = TAIL_CLEAN
    segments: List[str] = field(default_factory=list)
    #: Stale-writer records discarded by `filter_epoch_chain` (writes a
    #: deposed leader landed after its fencing; see module docstring).
    stale_orphans: List[dict] = field(default_factory=list)

    @property
    def last_seq(self) -> int:
        if self.events:
            return int(self.events[-1].get("seq", 0))
        if self.snapshot is not None:
            return int(self.snapshot.get("last_seq", 0))
        return 0


def load_state(state_dir: str) -> RecoveredState:
    """Load snapshot + post-snapshot journal events from `state_dir`.

    Raises JournalError when no snapshot loads but the surviving
    journal provably does not start at the beginning (seq 1): the
    missing head was compacted away on the strength of snapshots that
    are now unreadable, and replaying the truncated tail onto an empty
    scheduler would misnumber every job and silently drop accounting.
    Refusing loudly beats resuming with garbage."""
    snapshot = load_snapshot(state_dir)
    min_seq = int(snapshot.get("last_seq", 0)) if snapshot else 0
    events: List[dict] = []
    tail = TAIL_CLEAN
    segments = list_segments(state_dir)
    for path in segments:
        records, status = read_journal(path)
        if status != TAIL_CLEAN:
            tail = status
        events.extend(r for r in records if int(r.get("seq", 0)) > min_seq)
    events.sort(key=lambda r: int(r.get("seq", 0)))
    events, orphans = filter_epoch_chain(events)
    if orphans:
        logger.warning(
            "discarded %d stale-writer journal record(s) superseded by a "
            "higher leader epoch (a deposed leader wrote past its "
            "fencing); seqs %s", len(orphans),
            sorted({int(r.get("seq", 0)) for r in orphans})[:10])
    if snapshot is None and events and int(events[0].get("seq", 0)) > 1:
        raise JournalError(
            f"{state_dir}: no readable snapshot, and the journal starts "
            f"at seq {events[0].get('seq')} (events 1.."
            f"{int(events[0].get('seq', 1)) - 1} were compacted into the "
            "now-unreadable snapshots) — state is unrecoverable; run "
            "scripts/utils/fsck_journal.py for details")
    return RecoveredState(snapshot=snapshot, events=events,
                          tail_status=tail, segments=segments,
                          stale_orphans=orphans)


# ----------------------------------------------------------------------
# Streaming follower (hot standby / fsck --follow)
# ----------------------------------------------------------------------

#: Follower poll outcomes beyond the shared tail statuses.
FOLLOW_WAIT = "wait"        # torn/partial tail right now: poll again
FOLLOW_BEHIND = "behind"    # compaction outran us: reload from snapshot


class JournalFollower:
    """Incremental reader that tails a LIVE journal while the leader is
    still appending to it — the standby's replication feed and fsck's
    ``--follow`` mode.

    Unlike `read_journal`, a partial frame at end-of-file is WAIT (the
    writer is mid-append, or its fsync has not landed), never
    corruption: the follower keeps its offset at the last whole record
    and re-reads the tail on the next poll. If a crash later truncates
    that torn tail, re-reading from the valid offset parses the
    replacement bytes cleanly. Epoch fencing is applied on the fly with
    the same supersede rule recovery uses (`filter_epoch_chain`), so a
    deposed leader's post-fencing appends never reach the twin.

    The follower also detects falling behind compaction: when a new
    snapshot's horizon passes the last delivered sequence while the
    covering segments are already deleted, `poll` returns FOLLOW_BEHIND
    and the caller must rebuild from `load_state` (then resume with a
    fresh follower seeded at the new sequence).
    """

    def __init__(self, state_dir: str, start_after_seq: int = 0):
        self.state_dir = state_dir
        self.last_seq = int(start_after_seq)
        self.last_record_walltime: Optional[float] = None
        self.max_epoch: Optional[int] = None
        self.stale_dropped = 0
        self.records_delivered = 0
        # path -> byte offset just past the last WHOLE record parsed
        # (magic included).
        self._offsets: dict = {}
        # path -> highest epoch ever read from that segment: a torn
        # tail on a SUPERSEDED writer's segment (a dead/deposed
        # leader's never-reopened file) is ignorable debris, not a
        # pending write — see poll().
        self._seg_epoch: dict = {}
        # (mtime_ns, size) -> horizon cache: the behind-compaction
        # probe runs on every idle poll, and unpickling a fleet-sized
        # snapshot each 100ms would dominate the standby's CPU.
        self._snap_stat = None
        self._snap_horizon = 0

    def snapshot_horizon(self) -> int:
        """last_seq of the current on-disk snapshot (0 when none) —
        the staleness probe for the behind-compaction check. Cached by
        the snapshot file's (mtime, size); only a rewritten snapshot is
        re-read."""
        try:
            st = os.stat(os.path.join(self.state_dir, SNAPSHOT_NAME))
            stat_key = (st.st_mtime_ns, st.st_size)
        except OSError:
            stat_key = None
        if stat_key != self._snap_stat or self._snap_stat is None:
            snapshot = load_snapshot(self.state_dir)
            self._snap_horizon = (int(snapshot.get("last_seq", 0))
                                  if snapshot else 0)
            self._snap_stat = stat_key
        return self._snap_horizon

    def _poll_segment(self, path: str) -> Tuple[List[dict], str]:
        """New whole records of one segment since the last poll."""
        start = self._offsets.get(path, len(JOURNAL_MAGIC))
        try:
            with open(path, "rb") as f:
                if start == len(JOURNAL_MAGIC):
                    magic = f.read(len(JOURNAL_MAGIC))
                    if magic != JOURNAL_MAGIC:
                        raise JournalError(f"{path}: bad journal magic")
                else:
                    f.seek(start)
                blob = f.read()
        except FileNotFoundError:
            # Compacted away under us; anything unread is judged by the
            # behind-compaction check in poll().
            return [], TAIL_CLEAN
        records, valid, status = _scan_records(blob)
        self._offsets[path] = start + valid
        return records, status

    def poll(self) -> Tuple[List[dict], str]:
        """Read every record appended since the last poll, fenced and
        deduplicated, in sequence order.

        Returns ``(events, status)`` where status is TAIL_CLEAN (caught
        up at a record boundary), FOLLOW_WAIT (a torn tail is pending —
        poll again) or FOLLOW_BEHIND (compaction deleted events this
        follower never read; rebuild from `load_state`).
        """
        raw: List[dict] = []
        torn_paths: List[str] = []
        for path in list_segments(self.state_dir):
            records, seg_status = self._poll_segment(path)
            raw.extend(records)
            epochs = [int(r["epoch"]) for r in records
                      if r.get("epoch") is not None]
            if epochs:
                self._seg_epoch[path] = max(
                    self._seg_epoch.get(path, 0), max(epochs))
            if seg_status != TAIL_CLEAN:
                torn_paths.append(path)
        raw.sort(key=lambda r: int(r.get("seq", 0)))
        # A zombie's append can DUPLICATE a sequence already delivered
        # (its stale write landed after the winner's was shipped): the
        # seq cursor filters it out of the feed, but it still counts as
        # a fenced stale record for the lag/diagnostic surfaces.
        if self.max_epoch is not None:
            self.stale_dropped += sum(
                1 for r in raw
                if int(r.get("seq", 0)) <= self.last_seq
                and r.get("epoch") is not None
                and int(r["epoch"]) < self.max_epoch)
        fresh, orphans = filter_epoch_chain(
            [r for r in raw if int(r.get("seq", 0)) > self.last_seq])
        # Fencing is STATEFUL across polls: a stale writer's records
        # must lose to a higher epoch delivered on an earlier poll too.
        if self.max_epoch is not None:
            still = [r for r in fresh
                     if r.get("epoch") is None
                     or int(r["epoch"]) >= self.max_epoch]
            orphans.extend(r for r in fresh if r not in still)
            fresh = still
        self.stale_dropped += len(orphans)
        out: List[dict] = []
        for rec in fresh:
            seq = int(rec.get("seq", 0))
            if seq != self.last_seq + 1:
                # A gap inside the live stream: either compaction
                # outran us (judged below) or events were lost; stop at
                # the gap so the caller decides with a clean cursor.
                break
            out.append(rec)
            self.last_seq = seq
            if rec.get("epoch") is not None:
                self.max_epoch = max(self.max_epoch or 0,
                                     int(rec["epoch"]))
            if rec.get("t") is not None:
                self.last_record_walltime = float(rec["t"])
        self.records_delivered += len(out)
        # Tail status, decided AFTER this poll's epochs are folded in:
        # a torn tail on a segment whose writer is superseded (its
        # highest epoch is below the chain's) can never complete — the
        # dead leader's file is never reopened — so it is ignorable
        # debris, not a pending write to WAIT for.
        status = TAIL_CLEAN
        for path in torn_paths:
            seg_epoch = self._seg_epoch.get(path)
            superseded = (self.max_epoch is not None
                          and seg_epoch is not None
                          and seg_epoch < self.max_epoch)
            if not superseded:
                status = FOLLOW_WAIT
        if (not out and status == TAIL_CLEAN
                and self.snapshot_horizon() > self.last_seq):
            return [], FOLLOW_BEHIND
        return out, status


class DurabilityLayer:
    """The scheduler's durable-state sink: sequenced journal appends plus
    compacting snapshots. Thread-safe (RPC callbacks, watchdog timers and
    the round loop all emit)."""

    #: Sanctioned blocking-under-lock sites (hold-discipline pass,
    #: analysis/lockflow.py): write-ahead journaling IS fsync under
    #: this layer's serialization lock — `record` must assign the
    #: sequence number and reach disk atomically with respect to other
    #: emitters (two racing appends with swapped seq/disk order would
    #: corrupt the recovery chain), and `snapshot` must write the
    #: compaction point that matches the sequence it claims. The
    #: non-critical audit stream opts out via ``sync=False`` instead.
    _HOLD_DISCIPLINE_JUSTIFIED = frozenset({
        "record:fsync", "snapshot:fsync",
    })

    def __init__(self, state_dir: str,
                 snapshot_interval_rounds: int = 10, obs=None,
                 epoch: Optional[int] = None,
                 rotate_on_open: bool = False):
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self.snapshot_interval_rounds = snapshot_interval_rounds
        # Fenced leader epoch (control-plane HA): stamped on every
        # record so recovery and fsck can discard a deposed leader's
        # post-fencing writes (filter_epoch_chain). None = HA disabled,
        # records stay untagged.
        self._epoch = None if epoch is None else int(epoch)
        # Observability: append/fsync latency histograms, byte counters
        # and journal-fsync spans. The owning scheduler injects its
        # bundle; standalone layers (tests, fsck) fall back to the
        # process-global wall-clock one. The registry/tracer locks are
        # leaves, so recording under this layer's lock (itself under
        # the scheduler lock) cannot invert any watched order.
        if obs is None:
            from ..obs import get_observability
            obs = get_observability()
        self._obs = obs
        # Instrumented under SWTPU_SANITIZE=1: the scheduler emits under
        # its own lock, so scheduler-lock -> journal-lock is an order
        # edge the sanitizer watches for inversions.
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "DurabilityLayer._lock")

        last_seq = 0
        snapshot = load_snapshot(state_dir)
        if snapshot is not None:
            last_seq = int(snapshot.get("last_seq", 0))
        # The horizon of the CURRENT on-disk snapshot (what the next
        # compaction may delete up to: segments older than this are only
        # needed by a snapshot generation that no longer exists).
        self._snap_seq = last_seq
        segments = list_segments(state_dir)
        if rotate_on_open:
            # HA incarnation: resume numbering after the newest
            # SURVIVING record. All segments are scanned (bounded to
            # ~2 snapshot intervals) through the epoch supersede rule,
            # so a deposed leader's stale tail records can never
            # inflate the sequence this incarnation continues from.
            all_records: List[dict] = []
            for path in segments:
                records, _ = read_journal(path)
                all_records.extend(records)
            all_records.sort(key=lambda r: int(r.get("seq", 0)))
            kept, _ = filter_epoch_chain(all_records)
            if kept:
                last_seq = max(last_seq, int(kept[-1].get("seq", 0)))
        else:
            # Single-writer history: the newest non-empty segment's
            # last record is authoritative (no stale-writer records
            # can exist to supersede).
            for path in reversed(segments):
                records, _ = read_journal(path)
                if records:
                    last_seq = max(last_seq,
                                   int(records[-1].get("seq", 0)))
                    break
        self._seq = last_seq
        if rotate_on_open or not segments:
            # HA incarnations NEVER continue an inherited segment: a
            # deposed-but-alive predecessor may still hold an open file
            # descriptor into it, and two writers interleaving appends
            # in one file is unframeable corruption. A fresh segment
            # confines the zombie to files this incarnation only reads.
            path = _segment_path(state_dir, last_seq + 1)
            bump = last_seq + 1
            while os.path.exists(path):
                # Extremely rare: the predecessor rotated to this very
                # start seq and crashed before appending. The filename
                # seq only orders segments, and every record here will
                # carry seq > last_seq, so bumping the name is safe.
                bump += 1
                path = _segment_path(state_dir, bump)
        else:
            # Continue the newest segment (its torn tail, if any, is
            # truncated by JournalWriter).
            path = segments[-1]
        self._writer: Optional[JournalWriter] = JournalWriter(path)

    @property
    def last_seq(self) -> int:
        # Read under the lock: /healthz probes this from the exporter's
        # request thread while gRPC handlers append (race-detector).
        with self._lock:
            return self._seq

    @property
    def epoch(self) -> Optional[int]:
        return self._epoch

    def record(self, etype: str, data: dict, sync: bool = True) -> int:
        """Append one event; returns its sequence number. sync=False is
        for audit-only events (see JournalWriter.append)."""
        from ..obs import names as obs_names
        with self._lock:
            if self._writer is None:
                raise JournalError("durability layer is closed")
            # Claim the sequence number only once the append succeeded:
            # a failed append (ENOSPC, ...) is swallowed by the emitter,
            # and burning the number would leave a permanent gap that
            # fsck_journal flags as lost events.
            seq = self._seq + 1
            rec = {"seq": seq, "type": etype, "t": time.time(),
                   "data": data}
            if self._epoch is not None:
                rec["epoch"] = self._epoch
            t0 = self._obs.clock()
            if sync:
                with self._obs.span(obs_names.SPAN_JOURNAL_FSYNC,
                                    etype=etype):
                    nbytes = self._writer.append(rec, sync=True)
            else:
                nbytes = self._writer.append(rec, sync=False)
            sync_label = "true" if sync else "false"
            self._obs.observe(obs_names.JOURNAL_APPEND_SECONDS,
                              max(self._obs.clock() - t0, 0.0),
                              sync=sync_label)
            self._obs.inc(obs_names.JOURNAL_RECORDS_TOTAL,
                          sync=sync_label)
            self._obs.inc(obs_names.JOURNAL_BYTES_TOTAL, amount=nbytes)
            self._seq = seq
            self._obs.set_gauge(obs_names.JOURNAL_LAG_EVENTS,
                                self._seq - self._snap_seq)
            return seq

    @property
    def pending_events(self) -> int:
        """Events appended since the last compacting snapshot (the
        journal lag the /healthz endpoint reports)."""
        with self._lock:
            return self._seq - self._snap_seq

    def snapshot(self, payload: dict) -> None:
        """Write a compacting snapshot covering every event so far, then
        rotate. Only segments the OUTGOING snapshot (now `.prev`) no
        longer needs are deleted: if the new snapshot.pkl is later
        unreadable and recovery falls back to `.prev`, the events
        between the two snapshot horizons must still exist to replay.
        Journal size is therefore bounded by TWO snapshot intervals.
        Crash-safe at every step — recovery filters replay by
        `last_seq`, so a crash between the snapshot rename and the
        segment deletion only leaves already-covered (skipped) events
        behind."""
        from ..obs import names as obs_names
        with self._lock:
            if self._writer is None:
                raise JournalError("durability layer is closed")
            payload = dict(payload)
            payload["last_seq"] = self._seq
            payload.setdefault("time", time.time())
            if self._epoch is not None:
                payload["epoch"] = self._epoch
            with self._obs.span(obs_names.SPAN_SNAPSHOT, seq=self._seq), \
                    self._obs.timed(obs_names.SNAPSHOT_WRITE_SECONDS):
                write_snapshot(self.state_dir, payload)
            self._obs.inc(obs_names.JOURNAL_COMPACTIONS_TOTAL)
            prev_horizon = self._snap_seq  # the snapshot now at .prev
            self._snap_seq = self._seq
            self._obs.set_gauge(obs_names.JOURNAL_LAG_EVENTS, 0)
            old_segment = self._writer.path
            self._writer.close()
            for path in list_segments(self.state_dir):
                # Deletable iff every record is at or below the .prev
                # horizon. Judged by the segment's actual LAST record —
                # not its filename start seq — because a crash between
                # write_snapshot and rotation leaves a segment SPANNING
                # a snapshot horizon, and a name-based rule would delete
                # events the .prev fallback still needs. Segments are
                # bounded (~2 intervals), so the read is cheap.
                try:
                    records, _ = read_journal(path)
                except JournalError:
                    logger.warning("unreadable segment %s left in place",
                                   path)
                    continue
                if records and int(records[-1].get("seq", 0)) > prev_horizon:
                    continue
                try:
                    os.remove(path)
                except OSError:
                    logger.warning("could not remove compacted segment %s",
                                   path)
            _fsync_dir(self.state_dir)
            try:
                self._writer = JournalWriter(
                    _segment_path(self.state_dir, self._seq + 1))
            except Exception:  # noqa: BLE001 - rotation failed (ENOSPC,
                # EACCES, ...): the layer must NOT be left holding the
                # closed writer, where every later append would fail
                # silently per-event and a crash would lose a whole
                # interval. Fall back to the previous segment; if even
                # that fails, go loudly closed.
                logger.exception("journal rotation failed; reopening "
                                 "previous segment %s", old_segment)
                try:
                    self._writer = JournalWriter(old_segment)
                except Exception:
                    self._writer = None
                    raise

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


# ----------------------------------------------------------------------
# Job-key codec (JobIdPair <-> JSON-safe key)
# ----------------------------------------------------------------------

def encode_job_key(job_id) -> object:
    """JobIdPair -> JSON-safe key: bare int for singles, [lo, hi] pairs."""
    if job_id.is_pair():
        return [job_id[0], job_id[1]]
    return job_id.integer_job_id()


def decode_job_key(key):
    from ..core.job import JobIdPair
    if isinstance(key, (list, tuple)):
        return JobIdPair(int(key[0]), int(key[1]))
    return JobIdPair(int(key))
