"""Round-based scheduler core + discrete-event simulator.

One scheduling core serves two execution modes (the reference's fidelity
claim, EXPERIMENTS.md:24):

- **Simulation**: `simulate()` replaces workers with an oracle-throughput
  event loop (reference: scheduler.py:1728-2268).
- **Physical**: a round loop drives real workers over gRPC; jobs hold
  leases and report via done callbacks (wired up in runtime/).

The round mechanism: every `time_per_iteration` seconds each scheduled job
runs a micro-task; the policy's allocation is turned into per-round worker
assignments greedily by (priority, deficit, allocation), with sticky
placement so unchanged assignments can become lease extensions.
"""
from __future__ import annotations

import collections
import heapq
import logging
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import constants
from ..core import trace as trace_mod
from ..core.job import Job, JobIdPair
from ..core.oracle import read_oracle
from ..obs import Observability
from ..obs import names as obs_names
from . import simcore
from .journal import decode_job_key, encode_job_key
from .state import JobAccounting, RoundState, WorkerState

logger = logging.getLogger("shockwave_tpu.sched")

class SchedulerClockAdapter(logging.LoggerAdapter):
    """Prefixes every message with the scheduler clock — simulated seconds
    in simulation, wall-clock offset in physical mode (reference:
    scheduler/custom_logging.py SchedulerAdapter)."""

    def process(self, msg, kwargs):
        sched = self.extra["scheduler"]
        try:
            # Rebase physical wall-clock to run start; simulation time
            # already starts at zero.
            ts = (sched.get_current_timestamp()
                  - getattr(sched, "_start_time", 0.0))
        except Exception:  # noqa: BLE001 - never let logging raise
            ts = 0.0
        return f"[{ts:11.2f}] {msg}", kwargs



INFINITY = int(1e9)
#: First integer job id of the serving-replica id space (disjoint from
#: trace-job ids, which count up from 0 in trace position).
SERVING_REPLICA_ID_BASE = 1_000_000_000
DEFAULT_THROUGHPUT = 1.0
EMA_ALPHA = 0.5
MAX_FAILED_ATTEMPTS = 5
# Checkpoint + restore overhead injected when a simulated job was preempted
# in the previous round (reference: scheduler.py:1936-1968).
PREEMPTION_OVERHEAD_S = 20.0
# A job running over 1.5x its expected duration is force-completed.
DEADLINE_SLACK = 1.5
REOPT_ROUNDS = 8


@dataclass
class SchedulerConfig:
    time_per_iteration: float = 360.0
    seed: int = 0
    minimum_time_between_allocation_resets: float = 1000.0
    max_rounds: Optional[int] = None
    # Shockwave planner hyperparameters (configs/*.json).
    shockwave: Optional[dict] = None
    # Per-worker-type $/hour, for cost-normalized policies.
    per_worker_type_prices: Optional[Dict[str, float]] = None
    # Measured per-dispatch process startup (spawn -> first completed
    # step) per worker type, in seconds. When set — explicitly or via
    # the oracle file's __meta__.dispatch_overhead_s — the simulator
    # charges it on every COLD dispatch (first dispatch and redispatch
    # after preemption) instead of the reference-parity flat
    # PREEMPTION_OVERHEAD_S drain-time charge, closing the
    # physical-vs-sim fidelity gap on platforms where startup dominates
    # (reproduce/fidelity/). None preserves reference behavior exactly.
    dispatch_overhead_s: Optional[Dict[str, float]] = None
    # Physical-mode deadlock watchdog: dump all thread tracebacks every
    # N seconds (reference: faulthandler at scheduler.py:451-455).
    watchdog_interval: Optional[float] = None
    # Physical mode: how long past the round end a dispatched job may run
    # before the unresponsive-kill watchdog fires (None = the default
    # JOB_COMPLETION_BUFFER_TIME). Raise on platforms with slow dispatch.
    job_completion_buffer_s: Optional[float] = None
    # Physical mode: a job that has NEVER reached its first RPC (InitJob/
    # UpdateLease/Done) is granted this long from dispatch before the
    # unresponsive-kill watchdog may kill it. Cold dispatch through a
    # relayed TPU legitimately spends minutes in backend init waiting for
    # the chip grant, and killing the waiter wedges the relay so every
    # subsequent dispatch hangs too (observed live on the v5e tunnel —
    # the kill->wedge->kill livelock). 0 disables the grace.
    first_init_grace_s: float = 300.0
    # Fidelity-analysis hook: per-job measured throughput overrides
    # ({integer_job_id: steps_per_s}) replacing the oracle rate for
    # those jobs on every worker type. Used by the schedule-replay
    # methodology (reproduce/fidelity/) to feed the simulator the rates
    # a physical run actually experienced, isolating rate-model error
    # from decision divergence. None = oracle rates (default).
    rate_override: Optional[Dict[int, float]] = None
    # ---- fault tolerance (physical mode; see configs/fault_tolerance
    # .json for the recorded defaults and README "Failure model") ----
    # Worker-liveness monitor cadence. Heartbeats piggyback on every
    # Done / UpdateLease RPC; a worker silent for worker_timeout_s is
    # actively probed (Ping), and after worker_probe_failures
    # consecutive failed probes its chips are marked dead, its in-round
    # jobs are failed-in-round + requeued, and the allocation re-plans
    # over the survivors. 0 disables the monitor.
    heartbeat_interval_s: float = 10.0
    worker_timeout_s: float = 30.0
    worker_probe_deadline_s: float = 5.0
    worker_probe_failures: int = 2
    # How long _kill_job waits for the worker to confirm a kill before
    # synthesizing a zero-step completion (liveness floor for the
    # round; the reference hardcoded 30 s).
    kill_wait_s: float = 30.0
    # A job whose latest heartbeat is younger than this is not killed
    # as unresponsive; the kill timer re-arms instead (it may be mid
    # lease-expiry checkpoint). None = KILL_HEARTBEAT_FRESHNESS_S.
    kill_heartbeat_freshness_s: Optional[float] = None
    # Cap on consecutive freshness re-arms per dispatch: a job that
    # keeps heartbeating but never honors lease expiry is killed after
    # this many deferrals, so _end_round cannot be held hostage by a
    # perpetually-"fresh" job (ADVICE round 5).
    max_kill_rearms: int = 3
    # ---- durability (physical mode; see configs/durability.json and
    # README "Scheduler crash recovery") ----
    # Directory for the write-ahead journal + compacting snapshots. None
    # disables durability entirely (state dies with the process).
    state_dir: Optional[str] = None
    # Rebuild the scheduler from state_dir (snapshot + journal replay)
    # instead of starting empty. A non-empty state_dir with resume=False
    # is an error — never silently clobber a crashed run's state.
    resume: bool = False
    # Rounds between compacting snapshots; each snapshot compacts the
    # journal, bounding its size to two intervals of events (the
    # retained interval is the previous snapshot's replay tail). 0
    # disables snapshots (journal grows without bound).
    snapshot_interval_rounds: int = 10
    # ---- planner pipelining (physical mode; see README "Planner
    # performance") ----
    # Run the Shockwave MILP on a background solve thread, kicked at
    # round start for the round's re-solve point, so the solve wall
    # overlaps round execution instead of blocking `_mid_round` under
    # the scheduler lock. With pipelining on, physical mode no longer
    # clamps `solver_budget_cap_rounds` to 0.5 — the solver gets its
    # full `timeout x njobs/120` budget (bounded by the config cap,
    # default 2.0 rounds) and a solve that misses the re-solve round
    # falls back to the cached schedule + work-conserving backfill
    # (planner._fallback_round_schedule) instead of stalling the round.
    # Simulation ignores this flag entirely (solves stay inline and
    # bit-identical).
    pipelined_planning: bool = True
    # ---- observability (physical mode; see README "Observability") ----
    # HTTP port serving /metrics (Prometheus text) + /healthz (JSON).
    # 0 binds an ephemeral port (read PhysicalScheduler.obs_port);
    # None disables the endpoint entirely.
    obs_port: Optional[int] = None
    # Chrome-trace JSON path the span tracer exports to at shutdown
    # (view in Perfetto, or summarize with
    # `python -m shockwave_tpu.obs.report`). None skips the export.
    obs_trace_path: Optional[str] = None
    # Fleet-trace directory: the scheduler writes its own span shard
    # here at shutdown and merges every shard present (worker daemons
    # and trainers write theirs when pointed at the same directory via
    # --trace_dir / $SWTPU_SPAN_SHARD_DIR) into ONE Perfetto trace —
    # a round's solve->dispatch->launch->trainer chain connected by
    # propagated span context. None disables propagation entirely
    # (physical-mode only; simulation never constructs contexts).
    obs_trace_dir: Optional[str] = None
    # Telemetry history (obs/history.py): a crash-safe ring sampling
    # every registered metric each round plus per-microtask observed
    # steps/s by (job_type, bs, sf, worker_type) — served as
    # /history.json and feeding the swtpu_alert burn-rate checks.
    # A dict of TelemetryHistory.from_config overrides ({} for
    # defaults); None (the default) keeps history off — simulation
    # stays bit-identical and history-free.
    history: Optional[dict] = None
    # ---- simulation performance (see README "Fleet-scale simulation")
    # Vectorized sim-core passes (sched/simcore.py): priority recompute,
    # round-queue sort, schedule-membership bookkeeping, batched
    # micro-task completion, O(1) GNS oracle. Bit-identical to the
    # retained scalar path (the regression suite pins every policy);
    # False — or env SWTPU_SCALAR_SIM=1 — selects the scalar reference
    # oracle. Packing policies fall back to the scalar PRIORITY pass
    # (pair [a, b] throughput entries); the other vectorized passes
    # handle pair keys and stay active.
    vectorized_sim: bool = True
    # ---- serving tier (both modes; see README "Serving tier" and
    # configs/serving_mixed.json) ----
    # Autoscaler options for latency-SLO serving jobs
    # (serving.AutoscalerConfig fields: headroom, scale_down_patience,
    # min_requests_per_round, max_cluster_fraction). None uses the
    # defaults; the tier itself only exists once a serving job arrives,
    # so training-only traces never touch this path.
    serving: Optional[dict] = None
    # ---- gray-failure resilience (physical mode; see README "Gray
    # failures & chaos testing") ----
    # Per-host health scoring + quarantine of degraded-but-alive
    # workers (thermal throttling, flaky interconnect, slow disk): a
    # worker that answers Ping while running at a fraction of its speed
    # is classified healthy -> suspect -> degraded by an EWMA +
    # hysteresis score over telemetry obs already collects, quarantined
    # out of assignable capacity (journaled, so quarantine survives
    # --resume), probed while out, and released on probation after a
    # backoff. False disables scoring and quarantine entirely.
    worker_health_enabled: bool = True
    # runtime/resilience.HealthConfig field overrides (ewma_alpha,
    # suspect_below, degraded_below, recover_above, min_samples,
    # degraded_consecutive, recover_consecutive,
    # dispatch_latency_ref_s, rate_ref_decay, quarantine_backoff_s,
    # quarantine_backoff_max_s). None = the recorded defaults.
    worker_health: Optional[dict] = None
    # ---- online what-if control plane (both modes; see README
    # "What-if control plane") ----
    # whatif.WhatIfConfig field overrides: Monte-Carlo admission
    # control (admission="gate"), knob auto-tuning (tune_knob=...),
    # rollout forecasts and the twin shadow-chaos validator. None (the
    # default) constructs no plane at all — zero code on the canonical
    # replay path; a config with the default admission="always_admit"
    # keeps every admission decision identical too.
    whatif: Optional[dict] = None
    # ---- control-plane HA (physical mode; see README "Control-plane
    # HA" and configs/ha.json) ----
    # sched/ha.HAConfig field overrides (lease_interval_s, lease_ttl_s,
    # standby_poll_interval_s, failover_budget_s, advertise_addr).
    # Enables the leader-side HA controller: a fenced epoch is claimed
    # in state_dir, every journal record and scheduler->worker RPC
    # carries it, a liveness lease is renewed for hot standbys to
    # watch, and the process self-fences when a standby promotes over
    # it. Requires state_dir. None (the default) constructs nothing —
    # canonical replays and non-HA physical runs are bit-identical.
    ha: Optional[dict] = None
    # ---- learned throughput oracle (both modes; see README "Learned
    # throughput oracle" and shockwave_tpu/oracle/) ----
    # Keys: "model" (path to a `python -m shockwave_tpu.oracle.train`
    # artifact), "min_confidence" (trust gate below which a learned
    # prediction is demoted to the conservative prior),
    # "online_alpha" (residual EMA weight), and — simulation only —
    # "truth_file" (an oracle-format json of TRUE rates: jobs whose
    # initial rate came from the chain execute at the truth rate while
    # the planner's view converges online — the cold-start acceptance
    # methodology, reproduce/oracle/). None (the default) constructs
    # no chain at all: missing profiled entries raise/learn exactly as
    # before and every canonical replay is bit-identical.
    oracle: Optional[dict] = None


class Scheduler:
    """The scheduling core. Construct with a policy, then either call
    `simulate(...)` or drive it with worker callbacks (physical mode)."""

    #: Documented for the race detector (analysis/races.py):
    #: `_current_timestamp` is the simulator's virtual clock, advanced
    #: only by the single-threaded sim event loop (the physical
    #: subclass overrides get_current_timestamp with the wall clock and
    #: never touches it); `_replaying` is flipped only during recovery/
    #: journal replay, which runs before any worker thread exists (or
    #: on a single-threaded standby twin); `_journal` is bound once by
    #: attach_durability during construction (under the physical lock)
    #: and read-only afterwards. The scheduling-core maps in the second
    #: group are mutated by THESE base-class methods from add_job /
    #: register_worker / round-loop paths whose physical callers all
    #: hold PhysicalScheduler._lock (and whose sim callers are the
    #: single-threaded event loop) — externally synchronized by the
    #: subclass's lock, which a per-class lexical check cannot see; the
    #: physical-side helpers touching them are @requires_lock
    #: (sanitizer-verified). Fields whose access sites live in
    #: physical.py itself belong in PhysicalScheduler._LOCK_PROTECTED
    #: instead, where the lock-discipline pass genuinely checks them.
    _EXTERNALLY_SYNCHRONIZED = frozenset({
        "_current_timestamp", "_replaying", "_journal",
        "_throughputs", "_priorities", "_deficits", "_last_reset_time",
        "_scheduled_jobs_in_prev_round", "_scheduled_jobs_in_current_round",
        "_rounds_since_reopt", "_shockwave_job_completed",
        # Oracle-managed throughput bookkeeping: written by
        # _set_initial_throughput and read by _update_throughput /
        # _oracle_step_throughput — the same add_job / Done-report /
        # round-loop paths as the maps above, so the same external
        # synchronization (physical lock / single-threaded sim loop).
        "_oracle_predicted",
    })

    def __init__(self, policy, simulate: bool = False,
                 throughputs_file: Optional[str] = None,
                 profiles: Optional[List[dict]] = None,
                 config: Optional[SchedulerConfig] = None):
        self._policy = policy
        self._simulate = simulate
        self.log = SchedulerClockAdapter(logger, {"scheduler": self})
        self._job_packing = "Packing" in policy.name
        self._config = config or SchedulerConfig()
        self._time_per_iteration = self._config.time_per_iteration
        # Vectorized sim-core passes (sched/simcore.py); the env var is
        # the kill switch the regression suite and bench_sim_round.py
        # flip to reach the retained scalar oracle without config
        # plumbing.
        import os as _os
        self._vectorized = (bool(self._config.vectorized_sim)
                            and _os.environ.get("SWTPU_SCALAR_SIM") != "1")

        self._current_timestamp: float = 0.0
        self._job_id_counter = 0

        # Observability: registry + tracer driven by THIS scheduler's
        # clock — the simulator's virtual clock here, wall time in the
        # physical subclass (get_current_timestamp is overridden), so
        # the same metric names exist in both modes and recording never
        # feeds back into scheduling (bit-identical replay preserved).
        self._obs = Observability(clock=self.get_current_timestamp)

        self.workers = WorkerState()
        self.acct = JobAccounting()
        self.rounds = RoundState()

        # Allocation machinery.
        self._allocation: Dict[JobIdPair, Dict[str, float]] = {}
        self._priorities: Dict[str, Dict[JobIdPair, float]] = {}
        self._deficits: Dict[str, Dict[JobIdPair, float]] = {}
        self._need_to_update_allocation = False
        self._last_reset_time = 0.0

        # Throughputs: measured/estimated per job, plus the offline oracle.
        self._throughputs: Dict[JobIdPair, Dict[str, float]] = {}
        self._oracle_throughputs, oracle_meta = (
            read_oracle(throughputs_file) if throughputs_file
            else (None, {}))
        # Calibrated cold-dispatch overhead: explicit config wins, else
        # the oracle file's measured metadata, else the reference-parity
        # flat post-preemption charge (PREEMPTION_OVERHEAD_S).
        self._dispatch_overhead = self._config.dispatch_overhead_s
        if self._dispatch_overhead is None:
            self._dispatch_overhead = oracle_meta.get("dispatch_overhead_s")
        # Optional per-job-type refinement: startup varies by family
        # (model import + checkpoint size + compile), e.g. 23 s for
        # ResNet vs 7 s for Recommendation on the CPU loopback host.
        # {worker_type: {job_type: seconds}}; unlisted types fall back
        # to the per-worker-type scalar.
        self._dispatch_overhead_by_type = oracle_meta.get(
            "dispatch_overhead_s_by_type", {})
        # Deployed-conditions in-lease shortfall (round minus mean
        # in-lease duration), measured through the real runtime by
        # scripts/profiling/measure_deployed.py. Distinct key from the
        # solo spawn->exit proxy above so the two calibration methods
        # can't clobber each other's scalars (they have different
        # semantics); the deployed measurement is the more faithful
        # step-budget charge, so it takes precedence when present.
        self._lease_shortfall = oracle_meta.get("lease_shortfall_s", {})
        self._shortfall_by_type = oracle_meta.get(
            "lease_shortfall_s_by_type", {})
        # Measured per-cycle dead time OUTSIDE the lease (exit +
        # progress scrape + done RPC + round rollover + unhidden next
        # startup): physically every preemption cycle runs
        # round_duration + drain, so the simulator shifts each cold
        # dispatch's finish time by it without shrinking the step
        # budget ({worker_type: seconds}, measured by
        # scripts/profiling/measure_deployed.py).
        self._round_drain = oracle_meta.get("round_drain_s", {})
        # Optional per-job-type drain ({worker_type: {job_type: s}}):
        # the dead time is dominated by the incoming job's startup, so
        # it varies by family like the dispatch overhead does.
        self._round_drain_by_type = oracle_meta.get(
            "round_drain_s_by_type", {})
        # Optional per-scale-factor drain ({worker_type: {"2": s}}):
        # gang preemption cycles (multi-process exit + rendezvous +
        # redispatch) cost measurably more than sf=1 ones, and must not
        # clobber the sf=1 calibration — measured by
        # measure_deployed.py --scale_factor N.
        self._round_drain_by_sf = oracle_meta.get(
            "round_drain_s_by_sf", {})
        # Deployment-faithful mode (any calibration present): the
        # physical round mechanism wall-clocks rounds — a job completing
        # mid-round leaves its worker idle until the boundary — so the
        # simulator floors each round at the full round duration instead
        # of rolling at the last completion. Default (uncalibrated) DES
        # keeps the reference's completion-rolled rounds for replay
        # parity.
        self._deployment_faithful = bool(
            self._dispatch_overhead or self._dispatch_overhead_by_type
            or self._lease_shortfall or self._shortfall_by_type
            or self._round_drain or self._round_drain_by_type
            or self._round_drain_by_sf)
        self._sim_round_start: Optional[float] = None
        # Simulated gray failures: worker_id -> multiplicative speed
        # factor, installed/cleared by `simulate(fault_events=...)`
        # degrade/restore events. Empty on every canonical replay path
        # (the fast-path guard keeps the float math untouched).
        self._sim_degraded: Dict[int, float] = {}
        self._throughput_timeline: Dict[int, "collections.OrderedDict"] = {}

        # Cost / SLO / timeline observability.
        self._job_cost_so_far: Dict[JobIdPair, float] = {}
        self._slo_deadlines: Dict[JobIdPair, float] = {}
        self._job_timelines: Dict[int, List[str]] = {}
        # Per-round iterator logs shipped back in Done RPCs, buffered per
        # job until the round's micro-task aggregates (reference folds
        # these into job timelines, scheduler.py:4341-4715).
        self._iterator_log_buffers: Dict[JobIdPair, list] = {}

        self._completed_jobs: Set[JobIdPair] = set()
        self._last_completion_time = 0.0
        self._running_jobs: Set[JobIdPair] = set()
        self._in_progress_updates: Dict[JobIdPair, list] = {}
        self._steps_run_in_current_lease: Dict[JobIdPair, int] = {}
        self._num_jobs_in_trace = 0

        # Dynamic adaptation (accordion/GNS) request flags.
        self._bs_flags: Dict[JobIdPair, Dict[str, bool]] = {}

        # Serving tier (shockwave_tpu/serving/): constructed lazily on
        # the first serving job, None for training-only traces — every
        # serving hook below is guarded on it, so the canonical replay
        # never executes serving code. _serving_job_ids holds every
        # REPLICA job id ever admitted (kept after removal: metrics
        # filters read it), never service anchors. Replicas draw ids
        # from their OWN counter so trace-position invariants survive:
        # profiles stay positionally indexable by int_id for training
        # jobs arriving after a scale-up, and num_jobs_submitted stays
        # a valid trace-resume cursor.
        self._serving_tier = None
        self._serving_job_ids: Set[JobIdPair] = set()
        self._serving_replica_id_counter = SERVING_REPLICA_ID_BASE

        # Profiles indexed by integer job id (Shockwave solver input).
        self._profiles = profiles
        # int job id -> trace position, for runs where admission ORDER
        # diverges from trace order (what-if admission deferral): ids
        # are assigned at admission, so a deferred job's id no longer
        # equals its trace position and the positional profile lookup
        # must go through this map. Identity (empty) on every
        # non-deferring path — canonical replays never populate it.
        self._profile_map: Dict[int, int] = {}

        # Knob values committed by the what-if auto-tuner: mirrored
        # here (and into every snapshot) because the tuned state
        # itself may live OUTSIDE the snapshot fields (planner opts,
        # health config) and the whatif_knob journal event can be
        # compacted away — restore_state re-applies these.
        self._whatif_knob_values: Dict[str, float] = {}

        self._rng = np.random.RandomState(self._config.seed)
        import random as _random
        self._worker_type_shuffler = _random.Random(self._config.seed + 5)

        # Durability: a journal.DurabilityLayer once attached (physical
        # mode with state_dir; tests attach directly). While _replaying,
        # emission is suppressed so recovery never re-journals the
        # events it is consuming.
        self._journal = None
        self._replaying = False
        # Driver-recorded run metadata (trace path, wall start time);
        # survives restarts via the journal/snapshot so a resumed driver
        # can rebase arrival offsets and makespan onto the original run.
        self._run_meta: dict = {}

        # Shockwave planner.
        self._shockwave_planner = None
        if policy.name == "shockwave":
            from ..shockwave.planner import ShockwavePlanner
            sw = dict(self._config.shockwave or {})
            sw.setdefault("time_per_iteration", self._time_per_iteration)
            if not simulate:
                # Physical-mode solve budget. With pipelined planning
                # (default) the solve runs on a background thread and a
                # late result degrades to the cached-schedule fallback,
                # so a hard instance can never stall the round loop —
                # the solver gets its full budget (default cap 2.0
                # rounds, the setting that eliminated greedy fallbacks
                # at 256 chips in EXPERIMENTS.md). With pipelining
                # DISABLED the solve blocks `_mid_round` under the
                # scheduler lock, so the historical half-round clamp
                # applies regardless of what the config ships. A config
                # shipping null means "use the mode default"; anything
                # non-numeric is a config error, reported as such rather
                # than a bare TypeError out of the comparison below.
                pipelined = self._config.pipelined_planning
                cap = sw.get("solver_budget_cap_rounds",
                             2.0 if pipelined else 0.5)
                if cap is None:
                    cap = 2.0 if pipelined else 0.5
                try:
                    cap = float(cap)
                except (TypeError, ValueError):
                    raise ValueError(
                        "config error: solver_budget_cap_rounds must be a "
                        f"number (rounds) or null, got {cap!r}") from None
                if not pipelined and cap > 0.5:
                    self.log.warning(
                        "clamping solver_budget_cap_rounds %.2f -> 0.5 "
                        "(physical mode without pipelined planning)", cap)
                    cap = 0.5
                sw["solver_budget_cap_rounds"] = cap
            self._shockwave_planner = ShockwavePlanner.from_config(sw)
            # Planner-side observability: spans/histograms ride this
            # scheduler's injected clock (virtual in simulation).
            self._shockwave_planner.obs = self._obs
            # Planner-side durability hook: mark_progress /
            # add_waiting_delay / increment_round / solve outcomes are
            # journaled at their source so replay reproduces the
            # planner's estimate state exactly.
            self._shockwave_planner.journal = self._emit_event
        self._scheduled_jobs_in_current_round: Optional[List[int]] = None
        self._scheduled_jobs_in_prev_round: Optional[List[int]] = None
        self._shockwave_job_completed = False
        self._rounds_since_reopt = 0

        # Online what-if control plane (shockwave_tpu/whatif/): forks
        # this scheduler's journal-snapshot state into in-memory twin
        # rollouts for admission control, knob tuning and forecasts.
        # None (the default) means not even the hook sites execute —
        # the canonical replay path is untouched. Twins themselves are
        # built with whatif=None, so forks never recurse.
        self._whatif = None
        if self._config.whatif is not None:
            from ..whatif.plane import WhatIfPlane
            self._whatif = WhatIfPlane(self, self._config.whatif)

        # Learned throughput oracle (shockwave_tpu/oracle/): the
        # profiled-table -> learned-model -> conservative-prior chain
        # behind core/throughput_estimator.py. None means not even the
        # hook sites execute — the canonical replay path is untouched.
        # _oracle_predicted maps (int job id, worker_type) of every
        # entry the chain seeded (vs. the profiled table) to its
        # provenance: those entries are "oracle-managed" — in
        # simulation they execute at the truth-file rate while the
        # planning view EMA-converges from observed completions.
        self._oracle = None
        self._oracle_truth = None
        self._oracle_predicted: Dict[Tuple[int, str], str] = {}
        if self._config.oracle is not None:
            from ..core.throughput_estimator import OracleThroughputChain
            self._oracle = OracleThroughputChain.from_config(
                self._config.oracle, self._oracle_throughputs)
            truth_file = self._config.oracle.get("truth_file")
            if truth_file:
                self._oracle_truth, _ = read_oracle(truth_file)

    # ------------------------------------------------------------------
    # Time
    # ------------------------------------------------------------------

    def get_current_timestamp(self) -> float:
        return self._current_timestamp

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    @property
    def obs(self) -> Observability:
        """This scheduler's observability bundle (registry + tracer on
        the scheduler clock)."""
        return self._obs

    def _obs_update_round_gauges(self) -> None:
        """Refresh the round-state gauges. Called at every round
        boundary in both execution modes (the physical caller holds the
        scheduler lock; the simulator is single-threaded)."""
        self._obs.set_gauge(obs_names.CURRENT_ROUND,
                            self.rounds.num_completed_rounds)
        self._obs.set_gauge(obs_names.ACTIVE_JOBS, len(self.acct.jobs))
        self._obs.set_gauge(obs_names.LIVE_WORKERS,
                            len(self.workers.worker_ids))

    # ------------------------------------------------------------------
    # Durability (write-ahead journal + snapshot/restore)
    # ------------------------------------------------------------------

    #: Fields a compacting snapshot captures. Everything here must be
    #: picklable; in-flight round plumbing (threads, RPC clients,
    #: per-dispatch protocol state) is deliberately excluded — recovery
    #: re-adopts in-flight rounds conservatively instead.
    _SNAPSHOT_FIELDS = (
        "_current_timestamp", "_job_id_counter", "acct", "rounds",
        "workers", "_allocation", "_priorities", "_deficits",
        "_need_to_update_allocation", "_last_reset_time", "_throughputs",
        "_throughput_timeline", "_job_cost_so_far", "_slo_deadlines",
        "_job_timelines", "_completed_jobs", "_last_completion_time",
        "_num_jobs_in_trace", "_bs_flags", "_steps_run_in_current_lease",
        "_scheduled_jobs_in_current_round", "_scheduled_jobs_in_prev_round",
        "_shockwave_job_completed", "_rounds_since_reopt", "_rng",
        "_worker_type_shuffler", "_run_meta", "_profile_map",
        "_whatif_knob_values",
        "_serving_tier", "_serving_job_ids", "_serving_replica_id_counter",
        "_oracle_predicted",
    )
    _PLANNER_SNAPSHOT_FIELDS = (
        "metadata", "completed", "schedules", "round_ptr", "share_series",
        "solve_stats", "_resolve", "_reestimate_share",
    )

    def attach_durability(self, layer) -> None:
        """Start journaling state mutations into a DurabilityLayer."""
        self._journal = layer

    def _emit(self, etype: str, **data) -> None:
        self._emit_event(etype, data)

    def _emit_audit(self, etype: str, **data) -> None:
        """Journal an audit-only event (replay no-op) WITHOUT paying a
        per-record fsync — it persists with the next durable append."""
        self._emit_event(etype, data, sync=False)

    def _emit_event(self, etype: str, data: dict, sync: bool = True) -> None:
        if self._journal is None or self._replaying:
            return
        try:
            self._journal.record(etype, data, sync=sync)
        except Exception:  # noqa: BLE001 - never let the WAL kill a round
            self.log.exception("journal append failed for %s", etype)

    def record_run_meta(self, **meta) -> None:
        """Driver-level run metadata, journaled so a resumed run can
        rebase its clock and job submission cursor."""
        self._run_meta = dict(meta)
        self._emit("run_meta", **meta)

    @property
    def run_meta(self) -> dict:
        return dict(self._run_meta)

    @property
    def num_jobs_submitted(self) -> int:
        """Jobs ever admitted (the resume cursor into a trace)."""
        return self._job_id_counter

    def snapshot_state(self) -> dict:
        """Picklable durable-state dict (one object, so structure shared
        between the scheduler and planner — e.g. the per-job throughput
        timelines the planner calibrates against — stays shared on
        restore)."""
        state = {f: getattr(self, f) for f in self._SNAPSHOT_FIELDS}
        if self._shockwave_planner is not None:
            state["planner"] = {
                f: getattr(self._shockwave_planner, f)
                for f in self._PLANNER_SNAPSHOT_FIELDS}
        return state

    def restore_state(self, state: dict) -> None:
        for f in self._SNAPSHOT_FIELDS:
            if f in state:
                setattr(self, f, state[f])
        if not hasattr(self.workers, "quarantined"):
            # Snapshot written before the gray-failure layer existed:
            # the pickled WorkerState lacks the field.
            self.workers.quarantined = set()
        if self._serving_tier is not None:
            # The tier pickles without its scheduler reference.
            self._serving_tier.bind(self)
        planner_state = state.get("planner")
        if planner_state is not None:
            if self._shockwave_planner is None:
                self.log.warning("snapshot carries planner state but this "
                                 "scheduler has no shockwave planner; "
                                 "dropping it")
            else:
                for f in self._PLANNER_SNAPSHOT_FIELDS:
                    if f in planner_state:
                        setattr(self._shockwave_planner, f,
                                planner_state[f])
        # Re-apply what-if-tuned knob values AFTER the planner/tier are
        # in place: the tuned state may live outside the snapshot
        # fields (planner opts, health config) and the whatif_knob
        # journal event may have been compacted behind this snapshot.
        for name, value in getattr(self, "_whatif_knob_values",
                                   {}).items():
            try:
                from ..whatif.knobs import get_knob
                knob = get_knob(name)
            except ValueError:
                self.log.warning("snapshot carries tuned knob %r unknown "
                                 "to this build; ignoring", name)
                continue
            if knob.applicable(self):
                knob.set(self, float(value))

    def restore_from_durable_state(self, recovered) -> None:
        """Rebuild from a journal.RecoveredState: restore the snapshot,
        then replay every event after it. Emission is suspended for the
        duration so recovery never re-journals its own input."""
        self._replaying = True
        try:
            if recovered.snapshot is not None:
                self.restore_state(recovered.snapshot.get("state", {}))
            for event in recovered.events:
                self._apply_journal_event(event.get("type", "?"),
                                          event.get("data", {}))
        finally:
            self._replaying = False
        self.log.info(
            "recovered scheduler state: snapshot=%s, %d journal events "
            "replayed, %d active jobs, %d completed, round %d",
            "yes" if recovered.snapshot is not None else "no",
            len(recovered.events), len(self.acct.jobs),
            len(self._completed_jobs), self.rounds.num_completed_rounds)

    def _apply_journal_event(self, etype: str, data: dict) -> None:
        """Replay one journaled event. A single malformed event is
        logged and skipped — recovery of everything else must not hinge
        on it."""
        try:
            handler = getattr(self, f"_replay_{etype}", None)
            if handler is None:
                self.log.warning("unknown journal event %r; skipping",
                                 etype)
                return
            handler(data)
        except Exception:  # noqa: BLE001 - degrade, don't abort recovery
            self.log.exception("replay of journal event %r failed; "
                               "skipping", etype)

    # -- replay handlers (one per journaled event type) -----------------

    def _replay_run_meta(self, data: dict) -> None:
        self._run_meta = dict(data)

    def _replay_job_added(self, data: dict) -> None:
        spec = dict(data["job"])
        slo = spec.get("SLO")
        job = Job(
            job_id=None, job_type=spec["job_type"], command=spec["command"],
            working_directory=spec.get("working_directory", ""),
            num_steps_arg=spec.get("num_steps_arg", "--num_steps"),
            total_steps=spec.get("total_steps", 0),
            duration=spec.get("duration", 0),
            scale_factor=spec.get("scale_factor", 1),
            mode=spec.get("mode", "static"),
            priority_weight=spec.get("priority_weight", 1.0),
            SLO=None if slo is None else float(slo),
            needs_data_dir=spec.get("needs_data_dir", False))
        if "trace_position" in spec:
            job.trace_position = int(spec["trace_position"])
        job_id = self.add_job(job, timestamp=data.get("ts"))
        if job_id.integer_job_id() != data["int_id"]:
            self.log.warning("replayed job id %s != journaled %s (journal "
                             "out of order?)", job_id, data["int_id"])

    def _replay_job_removed(self, data: dict) -> None:
        job_id = JobIdPair(int(data["int_id"]))
        if job_id not in self.acct.jobs:
            return  # already removed via a replayed micro-task completion
        if data.get("ts") is not None:
            self.acct.latest_timestamps[job_id] = data["ts"]
        self._remove_job(job_id)

    def _replay_worker_registered(self, data: dict) -> None:
        ids, _ = self.register_worker(data["worker_type"],
                                      data.get("num_chips", 1))
        if list(ids) != list(data.get("worker_ids", ids)):
            self.log.warning("replayed worker ids %s != journaled %s",
                             ids, data.get("worker_ids"))

    def _replay_workers_retired(self, data: dict) -> None:
        self.deregister_workers([int(i) for i in data["worker_ids"]])

    def _replay_workers_revived(self, data: dict) -> None:
        self.revive_workers([int(i) for i in data["worker_ids"]],
                            data["worker_type"])

    def _replay_round_recorded(self, data: dict) -> None:
        assignments = {}
        staged: "collections.OrderedDict" = collections.OrderedDict()
        for key, ids in data["assignments"]:
            chip_ids = tuple(int(i) for i in ids)
            if isinstance(key, (list, tuple)):
                key = tuple(int(k) for k in key)
                staged[JobIdPair(*key)] = chip_ids
            else:
                key = int(key)
                staged[JobIdPair(key)] = chip_ids
            assignments[key] = chip_ids
        self._record_round(assignments)
        # Track the latest planned round as the current assignments:
        # recovery's conservative requeue reads these to attribute
        # abandoned leases to the jobs actually dispatched at the
        # crash, not to whichever job last completed a micro-task.
        self.rounds.current_assignments = staged

    def _replay_round_ended(self, data: dict) -> None:
        self.rounds.num_completed_rounds = int(data["round"])
        self.rounds.completed_in_round = set()

    def _replay_microtask_done(self, data: dict) -> None:
        job_id = decode_job_key(data["key"])
        if not any(m in self.acct.jobs for m in job_id.singletons()):
            return
        updates = data["updates"]
        worker_ids = tuple(int(u[0]) for u in updates)
        # Stage the round context the done path aggregates against, then
        # drive the REAL completion code (core class explicitly — the
        # physical subclass's wrapper adds live-RPC plumbing that must
        # not run during replay).
        self.rounds.current_assignments[job_id] = worker_ids
        self.rounds.completed_in_round.discard(job_id)
        self._in_progress_updates[job_id] = []
        latest = data.get("latest", {})
        for m in job_id.singletons():
            if m in self.acct.jobs:
                self._running_jobs.add(m)
                stamp = latest.get(str(m.integer_job_id()),
                                   latest.get(m.integer_job_id(),
                                              data.get("ts")))
                if stamp is not None:
                    self.acct.latest_timestamps[m] = stamp
        for worker_id, num_steps, times in updates:
            Scheduler.done_callback(self, job_id, int(worker_id),
                                    [int(s) for s in num_steps],
                                    [float(t) for t in times])

    def _replay_failure_comp(self, data: dict) -> None:
        job_id = JobIdPair(int(data["int_id"]))
        if job_id in self.acct.failures:
            self.acct.failures[job_id] -= 1

    def _replay_bs_flag(self, data: dict) -> None:
        flags = self._bs_flags.get(JobIdPair(int(data["int_id"])))
        if flags is not None:
            if data.get("big"):
                flags["big_bs"] = True
            if data.get("small"):
                flags["small_bs"] = True

    def _replay_lease_granted(self, data: dict) -> None:
        pass  # audit record: lease terms are re-derived on redispatch

    def _replay_planner_progress(self, data: dict) -> None:
        if self._shockwave_planner is not None:
            self._shockwave_planner.mark_progress(int(data["int_id"]),
                                                  int(data["epoch"]))

    def _replay_planner_waiting(self, data: dict) -> None:
        if self._shockwave_planner is not None:
            self._shockwave_planner.add_waiting_delay(int(data["int_id"]),
                                                      float(data["delay"]))

    def _replay_planner_round(self, data: dict) -> None:
        if self._shockwave_planner is not None:
            self._shockwave_planner.increment_round()

    def _replay_solve_outcome(self, data: dict) -> None:
        if self._shockwave_planner is not None:
            from ..shockwave.milp import SolveStats
            known = {f for f in SolveStats.__dataclass_fields__}
            self._shockwave_planner.solve_stats.append(
                SolveStats(**{k: v for k, v in data.items() if k in known}))

    def _replay_serving_retired(self, data: dict) -> None:
        if self._serving_tier is not None:
            self._serving_tier.force_retire(int(data["int_id"]),
                                            float(data["ts"]))

    def _emit_whatif_knob(self, knob: str, value: float, round: int,
                          sweep: list) -> None:
        """Journal a committed what-if knob value (called by the plane;
        the emit lives here so the journal-coverage invariant sees the
        emit/replay pair side by side). The value is also mirrored into
        _whatif_knob_values so snapshots carry it past journal
        compaction (restore_state re-applies it)."""
        self._whatif_knob_values[knob] = float(value)
        self._emit("whatif_knob", knob=knob, value=value, round=round,
                   sweep=sweep)

    def _emit_whatif_admission(self, record: dict) -> None:
        """Journal one admission verdict (audit-only; decision evidence
        for operators — the admission itself rides job_added)."""
        self._emit_audit("whatif_admission", **record)

    def _replay_whatif_knob(self, data: dict) -> None:
        """Re-apply a what-if-tuned knob value: the tuning decision is
        durable scheduler state (an operator-visible config change), so
        a resumed scheduler must come back with the tuned value, not
        the config default."""
        from ..whatif.knobs import get_knob
        try:
            knob = get_knob(data["knob"])
        except ValueError:
            self.log.warning("journaled what-if knob %r unknown to this "
                             "build; keeping the configured value",
                             data.get("knob"))
            return
        self._whatif_knob_values[data["knob"]] = float(data["value"])
        if knob.applicable(self):
            knob.set(self, float(data["value"]))

    def _replay_whatif_admission(self, data: dict) -> None:
        pass  # audit record: the decision's effect (the admission
        # itself / the deferred arrival time) is journaled via the
        # ordinary job_added events

    def _emit_serving_retired(self, int_id: int, ts: float) -> None:
        """Journal a service retirement (called by the serving tier; the
        emit lives here so the journal-coverage invariant sees the
        emit/replay pair side by side)."""
        self._emit("serving_retired", int_id=int_id, ts=ts)

    # ------------------------------------------------------------------
    # Serving tier
    # ------------------------------------------------------------------

    def _ensure_serving_tier(self):
        if self._serving_tier is None:
            from ..serving.tier import ServingTier
            self._serving_tier = ServingTier(self, self._config.serving)
        return self._serving_tier

    def _serving_live(self) -> bool:
        """Whether any serving service is still within its lifetime —
        the scheduler must keep rolling rounds for it even with no
        training jobs (and no replicas: scale-to-zero troughs still
        need the autoscaler consulted every round)."""
        return (self._serving_tier is not None
                and self._serving_tier.has_live_services())

    def serving_summary(self) -> Optional[dict]:
        """SLO-attainment summary across all serving services, or None
        for training-only traces (drivers put this in their metrics)."""
        if self._serving_tier is None:
            return None
        return self._serving_tier.summary()

    def oracle_serving_mu(self, job: Job) -> Optional[float]:
        """Learned decode-rate prior for a serving service's per-replica
        mu (requests/s), or None — None means "use the exact configured
        rate", and the chain returns None whenever the learned model
        has ZERO samples for this family, so canonical serving replays
        stay bit-identical (the tier calls this at registration)."""
        if self._oracle is None:
            return None
        try:
            batch_size = job.batch_size
        except ValueError:
            batch_size = 1
        return self._oracle.serving_mu(
            job.job_type, batch_size, sorted(self.workers.worker_types))

    def _admit_serving_service(self, job: Job, timestamp: Optional[float],
                               params: dict) -> JobIdPair:
        """Admit a serving SERVICE (the trace anchor). The service never
        enters the training books (acct.jobs / priorities / planner) —
        the tier expands it into autoscaled replica jobs, which do."""
        job_id = JobIdPair(self._job_id_counter)
        self._job_id_counter += 1
        job.job_id = job_id
        int_id = job_id.integer_job_id()
        pos = getattr(job, "trace_position", None)
        if pos is not None and pos != int_id:
            # Admission-order remap (see _profile_map): without this, a
            # reordered service's id would positionally alias a TRAINING
            # job's profile; mapped, _profile_for resolves to the
            # service's own (None) profile slot.
            self._profile_map[int_id] = int(pos)
        self._num_jobs_in_trace += 1
        ts = (timestamp if timestamp is not None
              else self.get_current_timestamp())
        self._ensure_serving_tier().register_service(int_id, job, params, ts)
        self._job_timelines[int_id] = [
            f"t={ts:.1f} SUBMITTED {job.job_type} serving service "
            f"slo_p99={job.SLO}s lifetime={float(job._duration):.0f}s"]
        self._obs.inc(obs_names.JOBS_SUBMITTED_TOTAL)
        self._emit("job_added", int_id=int_id, ts=ts, job=dict(
            job_type=job.job_type, command=job.command,
            working_directory=job.working_directory,
            num_steps_arg=job.num_steps_arg, total_steps=job.total_steps,
            duration=float(job._duration), scale_factor=job.scale_factor,
            mode=job.mode, priority_weight=job.priority_weight,
            SLO=job.SLO, needs_data_dir=job.needs_data_dir))
        self.log.info("[Serving service admitted] job %s (%s, slo_p99=%ss)",
                      job_id, job.job_type, job.SLO)
        return job_id

    # ------------------------------------------------------------------
    # Job lifecycle
    # ------------------------------------------------------------------

    def add_job(self, job: Job, timestamp: Optional[float] = None) -> JobIdPair:
        serving_params = None
        if trace_mod.is_serving_job(job):
            serving_params = trace_mod.parse_serving_command(job.command)
            if "replica_of" not in serving_params:
                # A serving SERVICE (trace anchor): tier-owned, not a
                # schedulable job. Replicas (--replica_of) fall through
                # to the normal path below with serving-aware guards.
                return self._admit_serving_service(job, timestamp,
                                                   serving_params)
        if serving_params is not None:
            # Replica ids come from their own space (see __init__).
            job_id = JobIdPair(self._serving_replica_id_counter)
            self._serving_replica_id_counter += 1
        else:
            job_id = JobIdPair(self._job_id_counter)
            self._job_id_counter += 1
        job.job_id = job_id
        a = self.acct
        a.jobs[job_id] = job
        a.steps_run[job_id] = {wt: 0 for wt in self.workers.worker_types}
        a.total_steps_run[job_id] = 0
        a.run_time_per_worker[job_id] = {}
        a.job_time[job_id] = {
            wt: self._time_per_iteration / 2.0 for wt in self.workers.worker_types}
        a.failures[job_id] = 0
        a.original_bs[job_id] = job.batch_size
        a.original_num_steps[job_id] = job.total_steps
        a.original_job_type[job_id] = job.job_type
        if serving_params is None:
            # Replicas are autoscaling artifacts, not trace jobs: they
            # must not inflate the FTF static contention factor.
            self._num_jobs_in_trace += 1

        self._throughputs[job_id] = {}
        for wt in self.workers.worker_types:
            self._set_initial_throughput(job_id, wt)
        override = (self._config.rate_override or {}).get(
            job_id.integer_job_id())
        if override is not None:
            # Fidelity-analysis hook (see SchedulerConfig.rate_override):
            # both the timing model and the planner/policy read
            # _throughputs, so the measured rate drives everything.
            for wt in self.workers.worker_types:
                self._throughputs[job_id][wt] = override
        if self._job_packing and serving_params is None:
            self._populate_pair_throughputs(job_id)

        ts = timestamp if timestamp is not None else self.get_current_timestamp()
        a.start_timestamps[job_id] = ts
        a.latest_timestamps[job_id] = None
        if serving_params is None:
            # Serving replicas are scheduled by reservation (tier.
            # plan_round), never by policy priority.
            self._add_to_priorities(job_id)
        self._need_to_update_allocation = True
        self._bs_flags[job_id] = {"big_bs": False, "small_bs": False}
        self._steps_run_in_current_lease[job_id] = 0

        self._job_cost_so_far[job_id] = 0.0
        if job.SLO is not None and job.duration and serving_params is None:
            # SLO is a multiplier on the job's isolated duration; the
            # deadline is an absolute timestamp (reference: scheduler.py:724-730).
            # Serving reinterprets SLO as a p99 latency target — the
            # completion-deadline machinery does not apply.
            self._slo_deadlines[job_id] = job.SLO * job.duration + ts

        int_id = job_id.integer_job_id()
        self._job_timelines[int_id] = [
            f"t={ts:.1f} SUBMITTED {job.job_type} sf={job.scale_factor} "
            f"mode={job.mode}"]
        self.rounds.num_scheduled_rounds[int_id] = 0
        self.rounds.num_queued_rounds[int_id] = 0
        self.rounds.job_start_round[int_id] = self.rounds.num_completed_rounds

        pos = getattr(job, "trace_position", None)
        if pos is not None and serving_params is None and pos != int_id:
            self._profile_map[int_id] = int(pos)

        if self._shockwave_planner is not None and serving_params is None:
            from ..shockwave.metadata import JobMetadata
            profile = self._profile_for(int_id)
            meta = JobMetadata(int_id, profile)
            meta.register_submit(ts)
            self._throughput_timeline[int_id] = collections.OrderedDict()
            meta.attach_throughput_measurements(
                self._throughput_timeline[int_id], self._time_per_iteration)
            self._shockwave_planner.add_job(int_id, meta)
        else:
            # LP policies, and serving replicas under any policy (the
            # planner never sees them; there is no epoch profile).
            self._throughput_timeline[job_id.integer_job_id()] = collections.OrderedDict()

        if serving_params is not None:
            self._serving_job_ids.add(job_id)
            self._ensure_serving_tier().adopt_replica(job_id, job,
                                                      serving_params)

        self._obs.inc(obs_names.JOBS_SUBMITTED_TOTAL)
        self._emit("job_added", int_id=int_id, ts=ts, job=dict(
            job_type=job.job_type, command=job.command,
            working_directory=job.working_directory,
            num_steps_arg=job.num_steps_arg, total_steps=job.total_steps,
            duration=float(job._duration), scale_factor=job.scale_factor,
            mode=job.mode, priority_weight=job.priority_weight,
            SLO=job.SLO, needs_data_dir=job.needs_data_dir,
            **({"trace_position": int(pos)} if pos is not None
               and pos != int_id else {})))
        self.log.info("[Job dispatched] job %s (%s, sf=%d, mode=%s)",
                    job_id, job.job_type, job.scale_factor, job.mode)
        return job_id

    def _remove_job(self, job_id: JobIdPair) -> None:
        a = self.acct
        self._completed_jobs.add(job_id)
        duration = a.latest_timestamps[job_id] - a.start_timestamps[job_id]
        a.completion_times[job_id] = duration
        self._last_completion_time = max(self._last_completion_time,
                                         a.latest_timestamps[job_id])
        a.priority_weights_archive[job_id] = a.jobs[job_id].priority_weight
        int_id = job_id.integer_job_id()
        self._job_timelines.setdefault(int_id, []).append(
            f"t={a.latest_timestamps[job_id]:.1f} COMPLETED jct={duration:.1f}")
        self.rounds.job_end_round[int_id] = self.rounds.num_completed_rounds
        del a.jobs[job_id]
        del a.steps_run[job_id]
        del a.job_time[job_id]
        del self._throughputs[job_id]
        del a.failures[job_id]
        if self._job_packing:
            for merged in [k for k in self._throughputs
                           if k.is_pair() and job_id.overlaps_with(k)]:
                del self._throughputs[merged]
                a.job_time.pop(merged, None)
        self._in_progress_updates.pop(job_id, None)
        self._iterator_log_buffers.pop(job_id, None)
        self._steps_run_in_current_lease.pop(job_id, None)
        self.rounds.extended_leases.discard(job_id)
        if self._shockwave_planner is not None:
            planner = self._shockwave_planner
            if int_id in planner.metadata:
                planner.mark_progress(int_id, planner.metadata[int_id].epochs)
                planner.remove_job(int_id)
            self._shockwave_job_completed = True
        if self._serving_tier is not None and job_id in self._serving_job_ids:
            self._serving_tier.on_replica_removed(job_id)
        self._remove_from_priorities(job_id)
        self._need_to_update_allocation = True
        self._obs.inc(obs_names.JOBS_COMPLETED_TOTAL)
        self._emit("job_removed", int_id=int_id,
                   ts=a.latest_timestamps[job_id])
        self.log.info("[Job completed] job %s after %.1fs (%d active)",
                    job_id, duration, len(a.jobs))

    # ------------------------------------------------------------------
    # Workers
    # ------------------------------------------------------------------

    def register_worker(self, worker_type: str, num_chips: int = 1):
        """Register one worker host exposing `num_chips` accelerator chips."""
        w = self.workers
        if worker_type not in w.type_to_server_ids:
            w.type_to_server_ids[worker_type] = []
            self._priorities[worker_type] = {}
            self._deficits[worker_type] = {}
            self.acct.worker_type_time.setdefault(worker_type, 0.0)
            for job_id in self.acct.jobs:
                self.acct.steps_run[job_id][worker_type] = 0
                self.acct.job_time[job_id][worker_type] = self._time_per_iteration / 2.0
                self._set_initial_throughput(job_id, worker_type)
                if job_id in self._serving_job_ids:
                    continue  # replicas stay out of priorities/packing
                if self._job_packing:
                    # Extend existing pair entries with the new worker type.
                    self._populate_pair_throughputs(job_id)
                self._add_to_priorities(job_id, worker_type)
        server_ids = []
        for _ in range(num_chips):
            worker_id = w.next_worker_id
            w.next_worker_id += 1
            server_ids.append(worker_id)
            w.worker_ids.append(worker_id)
            w.worker_types.add(worker_type)
            w.id_to_type[worker_id] = worker_type
            w.cumulative_time[worker_id] = 0.0
            w.start_times[worker_id] = self.get_current_timestamp()
            w.cluster_spec[worker_type] = w.cluster_spec.get(worker_type, 0) + 1
        # Store a copy: deregister_workers prunes dead ids from these
        # server lists in place, and the returned list must stay the
        # caller's stable record of its chip ids.
        w.type_to_server_ids[worker_type].append(list(server_ids))
        self._need_to_update_allocation = True
        self._emit("worker_registered", worker_type=worker_type,
                   num_chips=num_chips, worker_ids=list(server_ids))
        return server_ids, self._time_per_iteration

    def deregister_workers(self, worker_ids: Sequence[int]) -> None:
        """Remove chips from schedulable capacity (worker presumed dead).

        `id_to_type` and the cumulative-time books are retained so past
        accounting stays resolvable, and the ids are remembered in
        `workers.dead` so a rejoining daemon can revive them
        (`revive_workers`). Allocation is flagged for re-planning over
        the surviving capacity.
        """
        w = self.workers
        ids = [i for i in worker_ids if i not in w.dead and i in w.id_to_type]
        if not ids:
            return
        emptied_types = set()
        for worker_id in ids:
            w.dead.add(worker_id)
            w.last_seen.pop(worker_id, None)
            wt = w.id_to_type[worker_id]
            emptied_types.add(wt)
            w.cluster_spec[wt] = max(w.cluster_spec.get(wt, 0) - 1, 0)
            if worker_id in w.worker_ids:
                w.worker_ids.remove(worker_id)
            for server in w.type_to_server_ids.get(wt, []):
                if worker_id in server:
                    server.remove(worker_id)
        for wt in emptied_types:
            # Prune emptied server groups: revive appends a fresh group,
            # and under routine churn the empties would otherwise grow
            # (and be deep-copied by every round's assignment pass)
            # without bound.
            w.type_to_server_ids[wt] = [
                s for s in w.type_to_server_ids.get(wt, []) if s]
        self._need_to_update_allocation = True
        self._emit("workers_retired", worker_ids=list(ids))
        self.log.warning("[Workers lost] chips %s removed from capacity "
                         "(%s left)", ids, dict(w.cluster_spec))

    def revive_workers(self, worker_ids: Sequence[int],
                       worker_type: str) -> None:
        """Return previously-dead chips to capacity (worker rejoined).

        The ids keep their identity — accounting history and any stale
        references in old rounds stay valid — and come back as one
        server list (they live on one host, like at registration).
        """
        w = self.workers
        ids = [i for i in worker_ids if i in w.dead]
        if not ids:
            return
        for worker_id in ids:
            w.dead.discard(worker_id)
            # Revived => assignable => by definition not quarantined
            # (quarantine release and daemon re-registration both come
            # through here; replay of `workers_revived` reproduces the
            # same clearing, keeping recovery consistent).
            w.quarantined.discard(worker_id)
            if worker_id not in w.worker_ids:
                w.worker_ids.append(worker_id)
            w.cluster_spec[worker_type] = (
                w.cluster_spec.get(worker_type, 0) + 1)
        w.type_to_server_ids.setdefault(worker_type, []).append(list(ids))
        self._need_to_update_allocation = True
        self._emit("workers_revived", worker_ids=list(ids),
                   worker_type=worker_type)
        self.log.info("[Workers rejoined] chips %s restored to capacity "
                      "(%s)", ids, dict(w.cluster_spec))

    def suspect_worker_ids(self) -> frozenset:
        """Chips on hosts the gray-failure layer currently distrusts
        (suspect or degraded) — consumers that can choose placement
        (serving replica assignment) prefer other chips. The base
        scheduler has no health layer, so simulation always returns the
        empty set and replays stay bit-identical."""
        return frozenset()

    # ------------------------------------------------------------------
    # Throughputs
    # ------------------------------------------------------------------

    def _set_initial_throughput(self, job_id: JobIdPair, worker_type: str):
        job = self.acct.jobs[job_id]
        if trace_mod.is_serving_job(job):
            # A serving replica's "steps" are requests served: seed from
            # the command's decode-rate parameters (the same mu the
            # latency model plans with); physical mode EMA-refines it.
            self._throughputs[job_id][worker_type] = (
                trace_mod.serving_service_rate(job.command))
            return
        key = (job.job_type, job.scale_factor)
        oracle = (self._oracle_throughputs or {}).get(worker_type)
        if (oracle is not None and key in oracle
                and oracle[key]["null"] > 0.0):
            self._throughputs[job_id][worker_type] = oracle[key]["null"]
            if self._oracle is not None:
                self._obs.inc(obs_names.ORACLE_PREDICTIONS_TOTAL,
                              provenance="profiled")
        elif oracle is not None and key in oracle:
            # A zeroed oracle entry (the reference ships 0.0 for A3C /
            # CycleGAN) would starve the job in every throughput-driven
            # policy — and in simulation it previously raised a misleading
            # "no oracle throughput" KeyError even though the key exists.
            # Seed from the trace's expected rate; in physical mode the
            # EMA then learns the real value.
            nominal = job.total_steps / max(float(job.duration), 1.0)
            self.log.warning("zero oracle throughput for %s on %s; seeding "
                           "%.4f steps/s from expected duration", key,
                           worker_type, nominal)
            self._throughputs[job_id][worker_type] = nominal
        elif self._oracle is not None:
            # Learned-oracle chain (core/throughput_estimator.py): no
            # profiled entry, so consult the learned model, else the
            # conservative prior. The provenance record marks this
            # entry oracle-managed: in simulation it executes at the
            # truth-file rate (_oracle_step_throughput) while this
            # planning view converges online (_update_throughput).
            pred = self._oracle.predict(job.job_type, job.batch_size,
                                        job.scale_factor, worker_type)
            self._throughputs[job_id][worker_type] = pred.steps_per_s
            self._oracle_predicted[
                (job_id.integer_job_id(), worker_type)] = pred.provenance
            self._obs.inc(obs_names.ORACLE_PREDICTIONS_TOTAL,
                          provenance=pred.provenance)
            self.log.info(
                "oracle %s throughput for %s on %s: %.4f steps/s "
                "(confidence %.2f)", pred.provenance, key, worker_type,
                pred.steps_per_s, pred.confidence)
        elif (self._simulate and not self._replaying
                and self._oracle_throughputs is not None):
            # Simulation has no measured path to recover from a missing
            # oracle entry; fail loudly rather than fabricate throughput.
            # EXCEPT during journal replay: a sim-mode twin rebuilding a
            # PHYSICAL run's history (hot standby, whatif load_twin)
            # must tolerate whatever the physical side learned online —
            # the default-and-learn path below mirrors it.
            raise KeyError(
                f"no oracle throughput for {key} on {worker_type!r}")
        else:
            # Unprofiled hardware (e.g. a TPU worker against a GPU-profiled
            # oracle): start from the default and let the EMA learn it.
            self.log.warning("no profiled throughput for %s on %s; starting "
                           "from default and learning online", key, worker_type)
            self._throughputs[job_id][worker_type] = DEFAULT_THROUGHPUT

    def _populate_pair_throughputs(self, job_id: JobIdPair):
        """Record co-located throughputs for every same-scale-factor partner
        of `job_id` (packing policies only; reference: scheduler.py:3404-3483)."""
        job = self.acct.jobs[job_id]
        key = (job.job_type, job.scale_factor)
        for other_id, other in list(self.acct.jobs.items()):
            if other_id == job_id or other.scale_factor != job.scale_factor:
                continue
            other_key = (other.job_type, other.scale_factor)
            merged = JobIdPair(job_id[0], other_id[0])
            self._throughputs.setdefault(merged, {})
            self.acct.job_time.setdefault(merged, {})
            for wt in self.workers.worker_types:
                self.acct.job_time[merged].setdefault(wt, 0.0)
                oracle = (self._oracle_throughputs or {}).get(wt, {})
                if key in oracle and other_key in oracle[key]:
                    pair = oracle[key][other_key]
                    # Throughputs stored in sorted-member order.
                    ordered = pair if job_id[0] == merged[0] else pair[::-1]
                    self._throughputs[merged][wt] = list(ordered)
                else:
                    self._throughputs[merged][wt] = [0.0, 0.0]

    def _update_throughput(self, job_id: JobIdPair, worker_type: str,
                           all_num_steps: Sequence[int],
                           all_execution_times: Sequence[float]):
        if job_id not in self._throughputs:
            return
        members = job_id.singletons()
        for i, m in enumerate(members):
            if m not in self.acct.jobs:
                continue
            timeline = self._throughput_timeline.setdefault(
                m.integer_job_id(), collections.OrderedDict())
            exec_time = all_execution_times[i]
            tput = 0.0 if exec_time <= 0 else all_num_steps[i] / exec_time
            timeline[self.rounds.num_completed_rounds] = (
                tput, self.acct.jobs[m].batch_size)
            if not self._simulate and exec_time > 0:
                if job_id.is_pair():
                    old = self._throughputs[job_id][worker_type][i]
                    self._throughputs[job_id][worker_type][i] = (
                        EMA_ALPHA * tput + (1 - EMA_ALPHA) * old)
                else:
                    old = self._throughputs[job_id][worker_type]
                    if old != INFINITY:
                        tput = EMA_ALPHA * tput + (1 - EMA_ALPHA) * old
                    self._throughputs[job_id][worker_type] = tput
                if (self._oracle is not None and not job_id.is_pair()
                        and tput > 0):
                    # Physical mode feeds every measured rate to the
                    # learned model's online corrections too (the EMA
                    # above is per-job state; the model generalizes).
                    self._oracle.observe(
                        self.acct.jobs[m].job_type,
                        self.acct.jobs[m].batch_size,
                        self.acct.jobs[m].scale_factor, worker_type,
                        all_num_steps[i] / exec_time)
                    self._obs.inc(obs_names.ORACLE_ONLINE_UPDATES_TOTAL)
            elif (self._simulate and exec_time > 0
                    and not job_id.is_pair()
                    and self._oracle is not None
                    and (m.integer_job_id(), worker_type)
                    in self._oracle_predicted):
                # Oracle-managed entry in simulation: the micro-task
                # executed at the truth-file rate, so the observed
                # steps/s is a genuine measurement — EMA the planning
                # view toward it and feed the residual learner, exactly
                # as physical mode does for measured rates. Entries
                # seeded from the profiled table never take this path,
                # keeping oracle-off replays' rates untouched.
                old = self._throughputs[job_id][worker_type]
                if old != INFINITY and tput > 0:
                    self._obs.observe(
                        obs_names.ORACLE_PREDICTION_REL_ERROR,
                        abs(tput - old) / tput)
                    self._throughputs[job_id][worker_type] = (
                        EMA_ALPHA * tput + (1 - EMA_ALPHA) * old)
                    job = self.acct.jobs[m]
                    self._oracle.observe(job.job_type, job.batch_size,
                                         job.scale_factor, worker_type,
                                         tput)
                    self._obs.inc(obs_names.ORACLE_ONLINE_UPDATES_TOTAL)

    # ------------------------------------------------------------------
    # Priorities / deficits (Gavel machinery)
    # ------------------------------------------------------------------

    def _add_to_priorities(self, job_id: JobIdPair, worker_type: Optional[str] = None):
        for wt in ([worker_type] if worker_type else self.workers.worker_types):
            self._priorities[wt][job_id] = 0.0
            self._deficits[wt][job_id] = 0.0
            for other in self._throughputs:
                if other.is_pair() and job_id.overlaps_with(other):
                    self._priorities[wt][other] = 0.0
                    self._deficits[wt][other] = 0.0

    def _remove_from_priorities(self, job_id: JobIdPair):
        for wt in self.workers.worker_types:
            for other in list(self._priorities[wt]):
                if job_id.overlaps_with(other) if not job_id.is_pair() else job_id == other:
                    del self._priorities[wt][other]
                    del self._deficits[wt][other]

    def _reset_time_run_so_far(self):
        current_time = self.get_current_timestamp()
        elapsed = current_time - self._last_reset_time
        for wt in self.workers.worker_types:
            self.acct.worker_type_time[wt] = 0.0
            for job_id in self.acct.job_time:
                if job_id in self._serving_job_ids:
                    # Serving replicas run by reservation, outside the
                    # fair-share books: their time must not dilute the
                    # training jobs' received fractions.
                    continue
                received = self.acct.job_time[job_id].get(wt, 0.0) - (
                    self._time_per_iteration / 2.0)
                if job_id in self._allocation:
                    owed = self._allocation[job_id][wt] * elapsed
                else:
                    owed = 0.0
                self._deficits[wt].setdefault(job_id, 0.0)
                self._deficits[wt][job_id] += owed - received
                self.acct.job_time[job_id][wt] = self._time_per_iteration / 2.0
                self.acct.worker_type_time[wt] += self.acct.job_time[job_id][wt]
        self._last_reset_time = current_time

    def _inflight_elapsed_times(self, current_time: float):
        """(per-job, per-worker-type) time of microtasks still running.

        Simulation charges time at done-callbacks only, so this is empty;
        the physical scheduler overrides it. Without the in-flight term a
        job holding an extended lease never reports a Done, its received
        fraction never grows, and sticky placement re-extends it forever
        while the other jobs starve (reference: scheduler.py:3640-3666
        adds exactly this elapsed-time correction in physical mode)."""
        return {}, {}

    def _update_priorities(self):
        current_time = self.get_current_timestamp()
        reset_elapsed = (current_time - self._last_reset_time
                         >= self._config.minimum_time_between_allocation_resets)
        need_reset = (reset_elapsed or self._last_reset_time == 0)
        if self._simulate:
            need_reset = self._need_to_update_allocation and need_reset
        if need_reset:
            self._reset_time_run_so_far()
            if self._simulate:
                self._allocation = self._compute_allocation()
                self._need_to_update_allocation = False

        inflight_job, inflight_worker = self._inflight_elapsed_times(
            current_time)
        if self._vectorized and not self._job_packing:
            # Packing policies carry [a, b] pair throughput entries the
            # scalar zero-throughput guard compares directly; the
            # vectorized pass handles scalar rates only.
            simcore.update_priorities(self, inflight_job, inflight_worker)
            return
        for wt in self.workers.worker_types:
            worker_time = (self.acct.worker_type_time.get(wt, 0.0)
                           + inflight_worker.get(wt, 0.0))
            for job_id in self._priorities[wt]:
                if job_id not in self._allocation:
                    self._priorities[wt][job_id] = 0.0
                    continue
                alloc = self._allocation[job_id][wt]
                if alloc == 0.0 or self._throughputs[job_id][wt] == 0:
                    self._priorities[wt][job_id] = 0.0
                    continue
                if worker_time > 0 and wt in self.acct.job_time.get(job_id, {}):
                    job_time = (self.acct.job_time[job_id][wt]
                                + inflight_job.get(job_id, {}).get(wt, 0.0))
                    fraction = job_time / worker_time
                else:
                    fraction = 0.0
                if fraction > 0.0:
                    self._priorities[wt][job_id] = alloc / fraction
                else:
                    # Newly added job: run it according to its allocation.
                    self._priorities[wt][job_id] = alloc * 1e9

    # ------------------------------------------------------------------
    # Allocation
    # ------------------------------------------------------------------

    def _allocation_state(self) -> dict:
        a = self.acct
        now = self.get_current_timestamp()
        # Serving replicas are scheduled by reservation ahead of the
        # policy — exclude them from the LP's job set, and shrink the
        # cluster it divides by the chips serving currently holds.
        # (Both filters are identity for training-only traces.)
        serving = self._serving_job_ids
        job_ids = [j for j in a.jobs if j not in serving]
        cluster_spec = dict(self.workers.cluster_spec)
        if self._serving_tier is not None:
            for wt, n in self._serving_tier.last_reserved.items():
                cluster_spec[wt] = max(cluster_spec.get(wt, 0) - n, 0)
        num_steps_remaining = {}
        for job_id in job_ids:
            remaining = self._get_remaining_steps(job_id)
            remaining -= self._steps_run_in_current_lease[job_id]
            num_steps_remaining[job_id] = remaining
        return {
            "scale_factors": {j: a.jobs[j].scale_factor for j in job_ids},
            "priority_weights": {j: a.jobs[j].priority_weight
                                 for j in job_ids},
            "num_steps_remaining": num_steps_remaining,
            "times_since_start": {
                j: now - a.start_timestamps[j] for j in job_ids},
            # Explicit two-level copy (pair entries hold [a, b] lists the
            # EMA mutates in place) instead of deepcopy: this snapshot is
            # rebuilt every allocation solve and deepcopy's memo
            # machinery dominated it at scale. JobIdPair keys and the
            # scalar rates are immutable and safely shared.
            "throughputs": {
                job_id: {wt: (list(v) if isinstance(v, list) else v)
                         for wt, v in per_wt.items()}
                for job_id, per_wt in self._throughputs.items()
                if job_id not in serving},
            "per_round_schedule": list(self.rounds.per_round_schedule),
            "cluster_spec": cluster_spec,
            "instance_costs": self._config.per_worker_type_prices,
        }

    def _compute_allocation(self, state: Optional[dict] = None) -> dict:
        if state is None:
            state = self._allocation_state()
        name = self._policy.name
        # No schedulable capacity (every worker retired — routine on
        # preemptible fleets): there is nothing to allocate, and the LP
        # policies divide by cluster size (nan coefficients crash
        # linprog). Jobs re-plan when a worker registers or revives.
        if sum(state["cluster_spec"].values()) <= 0:
            return {}
        with self._obs.timed(obs_names.ALLOCATION_SOLVE_SECONDS,
                             policy=name):
            return self._policy_allocation(state, name)

    def _policy_allocation(self, state: dict, name: str) -> dict:
        throughputs = state["throughputs"]
        sf = state["scale_factors"]
        cluster = state["cluster_spec"]
        if name == "shockwave":
            return {}
        if name == "AlloX_Perf":
            allocation = self._policy.get_allocation(
                throughputs, sf, state["times_since_start"],
                state["num_steps_remaining"], state["per_round_schedule"], cluster)
        elif name.startswith("FinishTimeFairness"):
            allocation = self._policy.get_allocation(
                throughputs, sf, state["priority_weights"],
                state["times_since_start"], state["num_steps_remaining"], cluster)
        elif name.startswith("Isolated"):
            allocation = self._policy.get_allocation(throughputs, sf, cluster)
        elif name.startswith("MaxMinFairness"):
            allocation = self._policy.get_allocation(
                throughputs, sf, state["priority_weights"], cluster)
        elif name.startswith("MinTotalDuration"):
            allocation = self._policy.get_allocation(
                throughputs, sf, state["num_steps_remaining"], cluster)
        elif name == "Proportional":
            allocation = self._policy.get_allocation(throughputs, cluster)
        elif name == "ThroughputNormalizedByCostSum_Perf":
            allocation = self._policy.get_allocation(
                throughputs, sf, cluster, state.get("instance_costs"))
        else:
            allocation = self._policy.get_allocation(throughputs, sf, cluster)
        return allocation or {}

    # ------------------------------------------------------------------
    # Round scheduling
    # ------------------------------------------------------------------

    def _get_remaining_steps(self, job_id: JobIdPair) -> int:
        return self.acct.jobs[job_id].total_steps - self.acct.total_steps_run[job_id]

    def _profile_for(self, int_id: int):
        """The epoch profile for an integer job id, honoring the
        admission-order remap (see _profile_map). None when no profile
        exists (serving lines, out-of-range ids)."""
        if self._profiles is None:
            return None
        idx = self._profile_map.get(int_id, int_id)
        if 0 <= idx < len(self._profiles):
            return self._profiles[idx]
        return None

    def _select_jobs_for_round(self, worker_types: List[str],
                               reserved: Optional[Dict[str, int]] = None
                               ) -> dict:
        """Pick (job_id, scale_factor) lists per worker type for next
        round. `reserved` (worker_type -> chips) is what the serving
        tier already claimed this round; training selection budgets over
        the remainder."""
        reserved = reserved or {}
        if self._policy.name == "shockwave":
            # Keep the planner's per-type capacity rows current (mixed
            # clusters only: a single row keeps the scalar backfill
            # path and its bit-identical canonical replays).
            self._shockwave_planner.capacity_rows = (
                {wt: self.workers.cluster_spec[wt] - reserved.get(wt, 0)
                 for wt in worker_types}
                if len(worker_types) > 1 else None)
            job_ids = self._shockwave_planner.round_schedule()
            self._scheduled_jobs_in_prev_round = self._scheduled_jobs_in_current_round
            self._scheduled_jobs_in_current_round = job_ids
            scheduled = {wt: [] for wt in worker_types}
            # The planner budgets against total chips; spread the selected
            # jobs across worker types by remaining capacity.
            capacity = {wt: self.workers.cluster_spec[wt]
                        - reserved.get(wt, 0) for wt in worker_types}
            for int_id in job_ids:
                job_id = JobIdPair(int_id)
                if job_id not in self.acct.jobs:
                    self.log.warning("job %s in round schedule but completed", int_id)
                    continue
                sf = self.acct.jobs[job_id].scale_factor
                order = worker_types
                if self._oracle is not None and len(worker_types) > 1:
                    # Heterogeneous placement: try the worker type the
                    # oracle's current estimate ranks fastest for THIS
                    # job first (stable sort: rate ties keep the
                    # round's type order). Gated on the chain so
                    # oracle-off mixed-cluster runs keep first-fit.
                    rates = self._throughputs.get(job_id, {})
                    order = sorted(
                        worker_types,
                        key=lambda wt: (-float(rates.get(wt, 0.0)),
                                        worker_types.index(wt)))
                for wt in order:
                    if capacity[wt] >= sf:
                        scheduled[wt].append((job_id, sf))
                        capacity[wt] -= sf
                        break
                else:
                    self.log.warning("no capacity for planned job %s (sf=%d)",
                                   int_id, sf)
            return scheduled

        if self._vectorized:
            return simcore.select_jobs_for_round(self, worker_types,
                                                 reserved)

        scheduled = {wt: [] for wt in worker_types}
        workers_left = {wt: self.workers.cluster_spec[wt]
                        - reserved.get(wt, 0) for wt in worker_types}
        already: Set[JobIdPair] = set()

        queue = []
        for wt in worker_types:
            entries = [
                (job_id, wt, self._priorities[wt][job_id],
                 self._deficits[wt][job_id],
                 self._allocation.get(job_id, {}).get(wt, 0.0))
                for job_id in self._priorities[wt]
            ]
            queue += sorted(entries, key=lambda e: (e[2], e[3], e[4]), reverse=True)

        for job_id, wt, priority, _, _ in queue:
            if workers_left[wt] == 0:
                continue
            members = job_id.singletons()
            if any(m in already for m in members):
                continue
            tput = self._throughputs[job_id][wt]
            if (job_id.is_pair() and (tput[0] <= 0 or tput[1] <= 0)) or (
                    not job_id.is_pair() and tput <= 0):
                continue
            if self._policy.name.startswith("FIFO") and priority <= 0.0:
                continue
            sfs = {self.acct.jobs[m].scale_factor for m in members}
            if len(sfs) != 1:
                continue
            scale_factor = sfs.pop()
            if scale_factor > workers_left[wt]:
                if self._policy.name == "Isolated_plus":
                    break  # strict priority order
                continue
            workers_left[wt] -= scale_factor
            already.update(members)
            scheduled[wt].append((job_id, scale_factor))
        return scheduled

    def _assign_workers(self, scheduled: dict, worker_types: List[str],
                        serving_assignments: Optional[
                            "collections.OrderedDict"] = None,
                        ) -> "collections.OrderedDict":
        """Map selected jobs to concrete chip ids, sticky where possible.
        `serving_assignments` (replica -> chips, from tier.plan_round)
        are merged in FIRST: their chips are excluded from the training
        pools AND from sticky reuse, and the one-chip-one-job invariant
        below covers both tiers."""
        new_assignments: "collections.OrderedDict[JobIdPair, Tuple[int, ...]]" = (
            collections.OrderedDict(serving_assignments or ()))
        reserved_chips = {w for ids in new_assignments.values() for w in ids}
        prev_types = {
            job_id: self.workers.id_to_type[ids[0]]
            for job_id, ids in self.rounds.current_assignments.items()}

        for wt in worker_types:
            scheduled[wt].sort(key=lambda x: x[1], reverse=True)
            state = {
                # _take_workers pops chips off the inner server lists, so
                # copy both levels — but they are plain lists of ints, and
                # deepcopy here ran every round on the hot path.
                # Serving-reserved chips never enter the pools, and
                # seeding `assigned` with them blocks sticky reuse too.
                "servers": ([list(s)
                             for s in self.workers.type_to_server_ids[wt]]
                            if not reserved_chips else
                            [[w for w in s if w not in reserved_chips]
                             for s in self.workers.type_to_server_ids[wt]]),
                "assigned": set(reserved_chips),
                "ptr": 0,
            }
            scale_factors = sorted({sf for _, sf in scheduled[wt]}, reverse=True)
            for current_sf in scale_factors:
                # Sticky pass: keep jobs on their previous workers —
                # unless any of those chips has since been marked dead.
                for job_id, sf in scheduled[wt]:
                    if sf != current_sf or prev_types.get(job_id) != wt:
                        continue
                    prev_ids = self.rounds.current_assignments[job_id]
                    if any(w in self.workers.dead for w in prev_ids):
                        continue
                    if all(w not in state["assigned"] for w in prev_ids):
                        new_assignments[job_id] = prev_ids
                        state["assigned"].update(prev_ids)
                # Fill pass.
                for job_id, sf in scheduled[wt]:
                    if sf != current_sf or job_id in new_assignments:
                        continue
                    if (self._policy.name != "shockwave"
                            and job_id not in self._allocation):
                        continue
                    ids = self._take_workers(state, sf)
                    if ids is None:
                        raise RuntimeError(f"could not assign workers to {job_id}")
                    new_assignments[job_id] = tuple(ids)
                    if self._policy.name == "shockwave":
                        self._allocation.setdefault(job_id, {})[wt] = -1.0

        # Invariant: each chip assigned at most once.
        seen: Dict[int, int] = {}
        for ids in new_assignments.values():
            for w in ids:
                seen[w] = seen.get(w, 0) + 1
                if seen[w] > 1:
                    raise RuntimeError(f"worker {w} multiply assigned")

        for job_id in new_assignments:
            for m in job_id.singletons():
                if self._simulate:
                    self.acct.latest_timestamps[m] = self.get_current_timestamp()
                    self._running_jobs.add(m)
        return new_assignments

    @staticmethod
    def _take_workers(state, count: int):
        """Strided assignment walking server lists to minimize spread."""
        taken = []
        servers = state["servers"]
        while len(taken) < count and state["ptr"] < len(servers):
            server = servers[state["ptr"]]
            if not server:
                state["ptr"] += 1
                continue
            w = server.pop(0)
            if w not in state["assigned"]:
                taken.append(w)
                state["assigned"].add(w)
        return taken if len(taken) == count else None

    def _schedule_jobs_on_workers(self) -> "collections.OrderedDict":
        serving_assignments = None
        reserved = None
        if self._serving_tier is not None:
            # Serving plans FIRST: the tier retires/spawns/drains
            # replicas, reserves their chips, and shrinks the capacity
            # row the MILP sees — training budgets over the remainder.
            with self._obs.phase(obs_names.SPAN_SERVING_PLAN,
                                 round=self.rounds.num_completed_rounds):
                serving_assignments = self._serving_tier.plan_round()
            reserved = dict(self._serving_tier.last_reserved)
        if self._policy.name != "shockwave":
            self._update_priorities()
        worker_types = [wt for wt in ("v100", "p100", "k80")
                        if wt in self.workers.type_to_server_ids]
        if not worker_types:
            worker_types = sorted(self.workers.type_to_server_ids)
        if "Perf" not in self._policy.name and "Packing" not in self._policy.name:
            self._worker_type_shuffler.shuffle(worker_types)

        scheduled = self._select_jobs_for_round(worker_types, reserved)
        if self._vectorized:
            assignments = simcore.assign_workers(self, scheduled,
                                                 worker_types,
                                                 serving_assignments)
        else:
            assignments = self._assign_workers(scheduled, worker_types,
                                               serving_assignments)

        int_assignments = {}
        for job_id, ids in assignments.items():
            # Packed pairs are recorded as a tuple of member ids (sorted),
            # singles as the bare int — consumers use _in_recorded_round.
            key = (tuple(sorted(m.integer_job_id()
                                for m in job_id.singletons()))
                   if job_id.is_pair() else job_id.integer_job_id())
            int_assignments[key] = ids
        self._record_round(int_assignments)
        return assignments

    @staticmethod
    def _in_recorded_round(sched: Dict, int_id: int) -> bool:
        """Membership in a recorded round's schedule for either key form:
        bare int ids (single jobs) or member-id tuples (packed pairs)."""
        return int_id in sched or any(
            isinstance(k, tuple) and int_id in k for k in sched)

    def _record_round(self, int_assignments: Dict[int, Sequence[int]]):
        """Per-round bookkeeping shared by the live scheduler and the
        replay path — keeping it in one place keeps the replay leg's
        metrics structurally identical to the free run's."""
        if self._vectorized:
            simcore.record_round(self, int_assignments)
            return
        self.rounds.per_round_schedule.append(int_assignments)
        self.rounds.jobs_in_round.append(len(self.acct.jobs))
        for job_id in self.acct.jobs:
            int_id = job_id.integer_job_id()
            if self._in_recorded_round(int_assignments, int_id):
                self.rounds.num_scheduled_rounds[int_id] += 1
            else:
                self.rounds.num_queued_rounds[int_id] += 1
        # The round stamp anchors obs.explain's per-round attribution
        # (the physical mid-round records NEXT round under the current
        # counter; the explainer's monotonic rule resolves it).
        self._emit("round_recorded",
                   round=self.rounds.num_completed_rounds,
                   assignments=[
                       [list(k) if isinstance(k, tuple) else k, list(ids)]
                       for k, ids in int_assignments.items()])

    def _execute_forced_assignments(
            self, recorded: Dict[int, Sequence[int]]
    ) -> "collections.OrderedDict":
        """Schedule-replay: execute one recorded physical round verbatim
        (see simulate()'s forced_schedule). Entries whose job already
        completed in the replay are dropped (logged, as is the
        shouldn't-happen not-yet-arrived case — a lost lease would
        contaminate the timing-model attribution); recorded chip ids
        map identically onto this cluster, so the replay must be
        constructed with the physical run's cluster_spec. Packed pairs
        are not replayable (physical mode never packs — no MPS analog
        on TPU)."""
        assignments: "collections.OrderedDict[JobIdPair, Tuple[int, ...]]" = (
            collections.OrderedDict())
        seen_chips: Set[int] = set()
        pair_keys = [k for k in recorded if isinstance(k, tuple)]
        if pair_keys:
            # Physical mode never packs (no MPS analog on TPU), so a
            # recorded pair key means a packed SIM pickle was passed.
            self.log.warning("replay: dropping packed-pair entries %s "
                             "(pair replay unsupported)", pair_keys)
        for int_id in sorted(k for k in recorded
                             if not isinstance(k, tuple)):
            job_id = JobIdPair(int_id)
            if job_id not in self.acct.jobs:
                if job_id in self._completed_jobs:
                    self.log.info(
                        "replay: job %s already completed; dropping its "
                        "recorded lease", int_id)
                else:
                    self.log.warning(
                        "replay: job %s NOT YET ARRIVED at its recorded "
                        "round — lost lease will inflate its completion "
                        "delta", int_id)
                continue
            ids = tuple(recorded[int_id])
            for w in ids:
                if w not in self.workers.id_to_type:
                    raise RuntimeError(
                        f"recorded worker {w} absent from replay cluster "
                        f"(cluster_spec mismatch with the physical run)")
                if w in seen_chips:
                    raise RuntimeError(
                        f"recorded round assigns worker {w} twice "
                        f"(corrupt per_round_schedule)")
                seen_chips.add(w)
            assignments[job_id] = ids
            self.acct.latest_timestamps[job_id] = self.get_current_timestamp()
            self._running_jobs.add(job_id)
        self._record_round({j.integer_job_id(): ids
                            for j, ids in assignments.items()})
        return assignments

    # ------------------------------------------------------------------
    # Dynamic adaptation (Accordion / GNS)
    # ------------------------------------------------------------------

    def _current_epoch(self, job_id: JobIdPair) -> int:
        job = self.acct.jobs[job_id]
        return constants.num_epochs_for(
            job.model, job.batch_size, self.acct.total_steps_run[job_id])

    def _at_max_bs(self, model: str, bs: int) -> bool:
        return constants.MAX_BS.get(model) == bs

    def _simulate_accordion(self, job_id: JobIdPair):
        """Oracle for the accordion workload's critical-regime detector
        (reference: scheduler.py:1658-1726)."""
        job = self.acct.jobs[job_id]
        model, bs, bs0 = job.model, job.batch_size, self.acct.original_bs[job_id]
        epoch = self._current_epoch(job_id)
        if model == "Transformer":
            return
        if model == "LM":
            critical = epoch < 10
        elif model == "Recommendation":
            head = {512: 30, 1024: 30, 2048: 40, 4096: 10, 8192: 10}[bs0]
            critical = epoch < head
        elif model == "ResNet-50":
            critical = (epoch % 30) < 10
        elif model == "ResNet-18":
            head = 20 if bs0 == 256 else 10
            critical = (epoch < head or 150 <= epoch < 160 or 250 <= epoch < 260)
        else:
            return
        min_bs = {"ResNet-18": 16, "ResNet-50": 16, "Transformer": 16,
                  "LM": 5, "Recommendation": 512}
        if bs == bs0 and not critical:
            if not self._at_max_bs(model, bs):
                self._bs_flags[job_id]["big_bs"] = True
        elif bs != bs0 and critical:
            if bs != min_bs.get(model):
                self._bs_flags[job_id]["small_bs"] = True

    def _simulate_gns(self, job_id: JobIdPair):
        """Oracle for the GNS workload's noise-scale batch doubling
        (reference: scheduler.py:1604-1656)."""
        if self._vectorized:
            # O(1) point queries instead of rebuilding the full
            # per-epoch schedule every round (same decision, pinned by
            # the gns_bs_at equivalence test).
            simcore.simulate_gns(self, job_id)
            return
        from ..core.adaptation import gns_bs_schedule
        job = self.acct.jobs[job_id]
        model, bs = job.model, job.batch_size
        bs0 = self.acct.original_bs[job_id]
        epoch = self._current_epoch(job_id)
        schedule = gns_bs_schedule(model, bs0, max(760, epoch + 2), job.scale_factor)
        if schedule[epoch + 1] > bs or schedule[epoch] > bs:
            if not self._at_max_bs(model, bs):
                self._bs_flags[job_id]["big_bs"] = True

    def _scale_bs_and_iters(self, job_id: JobIdPair):
        """Apply a pending batch-size change: rewrite command, swap oracle
        throughput, and rescale step counts preserving epoch progress
        (reference: scheduler.py:4731-4931)."""
        flags = self._bs_flags.get(job_id)
        if not flags or not (flags["big_bs"] or flags["small_bs"]):
            return
        job = self.acct.jobs.get(job_id)
        if job is None:
            return
        model, mode = job.model, job.mode
        old_bs = job.batch_size
        bs0 = self.acct.original_bs[job_id]
        if self._at_max_bs(model, bs0) or model not in constants.MAX_BS:
            flags["big_bs"] = flags["small_bs"] = False
            return
        if mode == "gns":
            new_bs = 2 * old_bs
        elif mode == "accordion":
            new_bs = constants.MAX_BS[model] if flags["big_bs"] else bs0
        else:
            new_bs = old_bs
        job.update_bs(new_bs)

        key = (job.job_type, job.scale_factor)
        profiled_types = [
            wt for wt in self.workers.worker_types
            if key in (self._oracle_throughputs or {}).get(wt, {})]
        # Simulation has no way to measure the new batch size on worker
        # types the oracle missed, so require full coverage there. Physical
        # mode learns online: unprofiled types (e.g. TPU workers against a
        # GPU-profiled oracle) get a seed extrapolated from the measured
        # throughput (steps/s roughly inversely proportional to bs) and the
        # EMA corrects it from the next round's report.
        if self._simulate and self._oracle_throughputs is not None \
                and len(profiled_types) < len(self.workers.worker_types):
            self.log.error("job %s requested unprofiled bs %s; reverting",
                         job_id, key)
            job.update_bs(old_bs)
            flags["big_bs"] = flags["small_bs"] = False
            return
        for wt in self.workers.worker_types:
            if wt in profiled_types:
                self._throughputs[job_id][wt] = \
                    self._oracle_throughputs[wt][key]["null"]
            else:
                measured = self._throughputs[job_id].get(wt, DEFAULT_THROUGHPUT)
                self._throughputs[job_id][wt] = measured * old_bs / new_bs
        if self._job_packing:
            # Pair entries are keyed by job_type and are now stale.
            self._populate_pair_throughputs(job_id)

        # Rescale the step budget so total *epochs* are preserved.
        spe_old = constants.steps_per_epoch(model, old_bs)
        spe_new = constants.steps_per_epoch(model, new_bs)
        total_epochs = math.ceil(job.total_steps / spe_old)
        new_total_steps = math.ceil(job.total_steps * old_bs / new_bs)
        if math.ceil(new_total_steps / spe_new) != total_epochs:
            new_total_steps = spe_new * total_epochs
        job.total_steps = new_total_steps

        completed_epochs = math.ceil(self.acct.total_steps_run[job_id] / spe_old)
        new_steps_run = completed_epochs * spe_new
        self.acct.total_steps_run[job_id] = new_steps_run
        for wt in self.acct.steps_run[job_id]:
            self.acct.steps_run[job_id][wt] = new_steps_run
        self.log.info("[BS rescale] job %s: bs %d->%d, steps -> %d",
                    job_id, old_bs, new_bs, new_total_steps)
        flags["big_bs"] = flags["small_bs"] = False

    # ------------------------------------------------------------------
    # Done callback
    # ------------------------------------------------------------------

    def done_callback(self, job_id: JobIdPair, worker_id: int,
                      all_num_steps: Sequence[int],
                      all_execution_times: Sequence[float],
                      iterator_logs: Optional[Sequence[str]] = None):
        """Handle completion of one worker's micro-task for a job round."""
        a = self.acct
        # Pair keys (packing) accumulate run time on both members.
        run_time = float(np.max(all_execution_times))
        for m in job_id.singletons():
            a.run_time_per_worker.setdefault(m, {}).setdefault(worker_id, 0.0)
            a.run_time_per_worker[m][worker_id] += run_time

        members = job_id.singletons()
        is_active = {m: m in a.jobs for m in members}
        if not any(is_active.values()):
            return

        worker_type = self.workers.id_to_type[worker_id]
        scale_factor = len(self.rounds.current_assignments.get(job_id, (worker_id,)))
        self._in_progress_updates.setdefault(job_id, []).append(
            (worker_id, list(all_num_steps), list(all_execution_times)))
        if iterator_logs:
            self._iterator_log_buffers.setdefault(job_id, []).append(
                (worker_id, list(iterator_logs)))
        if len(self._in_progress_updates[job_id]) < scale_factor:
            return

        updates = sorted(self._in_progress_updates[job_id], key=lambda u: u[0])
        self._in_progress_updates[job_id] = []
        self._finalize_microtask(job_id, worker_type, scale_factor, updates)

    def _finalize_microtask(self, job_id: JobIdPair, worker_type: str,
                            scale_factor: int, updates: list) -> None:
        """Aggregate one complete micro-task (all of a gang's per-worker
        updates staged and sorted by worker id). Shared by the
        per-worker ``done_callback`` staging path and the simulator's
        batched completion (simcore.complete_microtask_batch), so both
        execution modes run the exact same accounting arithmetic."""
        a = self.acct
        to_remove: List[JobIdPair] = []
        members = job_id.singletons()
        is_active = {m: m in a.jobs for m in members}

        def member_over_deadline(m: JobIdPair) -> bool:
            if m not in a.jobs:
                return True
            run_time_so_far = (sum(a.run_time_per_worker[m].values())
                               / a.jobs[m].scale_factor)
            return run_time_so_far > int(a.jobs[m].duration * DEADLINE_SLACK)

        over_deadline = {m: member_over_deadline(m) for m in members}

        self.rounds.completed_in_round.add(job_id)
        if self._journal is not None and not self._replaying:
            self._emit("microtask_done", key=encode_job_key(job_id),
                       worker_type=worker_type,
                       ts=self.get_current_timestamp(),
                       # Exact dispatch stamps, so a replayed completion
                       # lands on the same JCT the live run recorded.
                       latest={m.integer_job_id():
                               self.acct.latest_timestamps.get(m)
                               for m in members if is_active[m]},
                       updates=[[w, list(s), [float(t) for t in times]]
                                for w, s, times in updates])

        # Fold the round's iterator logs into each live member's timeline.
        # Each worker's logs are index-aligned with the members (like
        # all_num_steps), and each element is a whole multi-line blob;
        # split so every line carries the greppable ITERATOR prefix.
        log_buffers = sorted(self._iterator_log_buffers.pop(job_id, []),
                             key=lambda u: u[0])
        # Serving replicas piggyback measured request telemetry on the
        # same log channel (serving/measured.py wire lines): route the
        # deltas to the tier's per-service merge and keep them out of
        # the human-readable timeline. Ingestion happens even for a
        # drained replica's final report — the service outlives it.
        measured_marker = None
        if (self._serving_tier is not None and log_buffers
                and job_id in self._serving_job_ids):
            from ..serving import measured as measured_mod
            measured_marker = measured_mod.MEASURED_REPORT_MARKER
            for _w_id, blobs in log_buffers:
                for blob in blobs:
                    for delta in measured_mod.find_reports(blob):
                        self._serving_tier.ingest_measured(job_id, delta)
        for j, m in enumerate(members):
            if not is_active[m]:
                continue
            tl = self._job_timelines.setdefault(m.integer_job_id(), [])
            for w_id, blobs in log_buffers:
                if j >= len(blobs):
                    continue
                tl.extend(f"t={self.get_current_timestamp():.1f} "
                          f"ITERATOR worker={w_id} {line}"
                          for line in blobs[j].splitlines()
                          if measured_marker is None
                          or measured_marker not in line)

        micro_task_succeeded = True
        agg_steps = [0] * len(members)
        agg_times = [0.0] * len(members)
        all_worker_ids = sorted(u[0] for u in updates)
        for _, num_steps_u, times_u in updates:
            for j, m in enumerate(members):
                if not is_active[m]:
                    continue
                if num_steps_u[j] <= 0 and times_u[j] <= 0:
                    micro_task_succeeded = False
            for j in range(len(members)):
                agg_steps[j] += num_steps_u[j]
                agg_times[j] = max(agg_times[j], times_u[j])

        self._obs.inc(obs_names.MICROTASKS_TOTAL,
                      outcome="ok" if micro_task_succeeded else "failed")
        if not micro_task_succeeded:
            self.log.info("[Micro-task failed] job %s", job_id)
            if not job_id.is_pair() and is_active[job_id]:
                a.failures[job_id] += 1
                if a.failures[job_id] >= MAX_FAILED_ATTEMPTS:
                    self.log.info("[Job failed] job %s dropped after %d attempts",
                                job_id, a.failures[job_id])
                    to_remove.append(job_id)
            self._need_to_update_allocation = True
        else:
            if not job_id.is_pair():
                a.failures[job_id] = 0
            prices = self._config.per_worker_type_prices
            for m, steps, exec_time in zip(members, agg_steps, agg_times):
                if not is_active[m]:
                    continue
                if prices is not None:
                    self._job_cost_so_far[m] += (
                        prices[worker_type] * exec_time / 3600.0 * scale_factor)
                self._job_timelines.setdefault(m.integer_job_id(), []).append(
                    f"t={self.get_current_timestamp():.1f} MICROTASK "
                    f"workers={all_worker_ids} steps={steps} "
                    f"time={exec_time:.1f}")
                if m in self._running_jobs:
                    self._running_jobs.remove(m)
                    a.steps_run[m][worker_type] += steps
                    a.total_steps_run[m] += steps
                    self._steps_run_in_current_lease[m] = 0
                    if self._get_remaining_steps(m) <= 0 or over_deadline[m]:
                        to_remove.append(m)
            max_time = max(agg_times)
            if job_id in a.job_time:
                a.job_time[job_id][worker_type] += max_time
                if job_id not in self._serving_job_ids:
                    # Serving time stays out of the fair-share
                    # denominator (replicas run by reservation).
                    a.worker_type_time[worker_type] += max_time
            for w in all_worker_ids:
                self.workers.cumulative_time[w] += max_time

        self._update_throughput(job_id, worker_type, agg_steps, agg_times)

        for m in members:
            self._scale_bs_and_iters(m)
        for m in to_remove:
            self._remove_job(m)
        for m in members:
            flags = self._bs_flags.get(m)
            if flags and (flags["big_bs"] or flags["small_bs"]):
                self._need_to_update_allocation = True

    # ------------------------------------------------------------------
    # Shockwave planner sync
    # ------------------------------------------------------------------

    def _update_shockwave_planner(self):
        """End-of-round epoch-progress + waiting-delay sync, and periodic
        re-optimization trigger (reference: scheduler.py:2270-2374)."""
        planner = self._shockwave_planner
        scheduled = (self._scheduled_jobs_in_current_round if self._simulate
                     else self._scheduled_jobs_in_prev_round) or []
        for int_id in scheduled:
            job_id = JobIdPair(int_id)
            if job_id in self._completed_jobs:
                if int_id in planner.metadata:
                    planner.mark_progress(int_id, planner.metadata[int_id].epochs)
                continue
            steps = self.acct.total_steps_run.get(job_id, 0)
            job = self.acct.jobs[job_id]
            epoch = math.floor(
                steps / constants.steps_per_epoch(job.model, job.batch_size))
            planner.mark_progress(int_id, epoch)
        active = {j.integer_job_id() for j in self.acct.jobs}
        for int_id in active - set(scheduled):
            planner.add_waiting_delay(int_id, self._time_per_iteration)
        planner.increment_round()
        self._rounds_since_reopt += 1
        if self._shockwave_job_completed or self._rounds_since_reopt >= REOPT_ROUNDS:
            self._shockwave_job_completed = False
            self._rounds_since_reopt = 0
            planner.request_resolve()

    # ------------------------------------------------------------------
    # Simulation
    # ------------------------------------------------------------------

    #: Integrity magic for simulation checkpoints (durable_io footer).
    SIM_CHECKPOINT_MAGIC = b"SWTPUC1\n"

    def save_simulation_checkpoint(self, path: str, queued, running,
                                   remaining_jobs, current_round) -> None:
        """Persist the full simulator state — including the in-flight
        micro-task heap — so a resumed run re-enters the event loop with
        identical state (reference: scheduler.py:1518-1594). Written
        through durable_io (CRC footer + fsync + atomic rename + .prev
        retention): a multi-hour sweep resuming from a torn checkpoint
        would silently produce garbage results."""
        import pickle
        from ..core.durable_io import write_durable
        # _obs is excluded: its clock is a bound method of this
        # scheduler (pickling it would drag a ghost scheduler copy into
        # the checkpoint), and metrics are telemetry, not sim state — a
        # resumed run keeps its own fresh bundle. _whatif likewise (it
        # holds a scheduler back-reference and its logs are telemetry);
        # a resumed run reconstructs the plane from config.
        write_durable(path, pickle.dumps({
            "scheduler": {k: v for k, v in self.__dict__.items()
                          if k not in ("_obs", "_whatif")},
            "queued": queued,
            "running": running,
            "remaining_jobs": remaining_jobs,
            "current_round": current_round,
        }, protocol=pickle.HIGHEST_PROTOCOL), self.SIM_CHECKPOINT_MAGIC)
        self.log.info("Saved simulation checkpoint to %s (round %d, %d jobs left)",
                    path, current_round, remaining_jobs)

    def _load_simulation_checkpoint(self, path: str):
        import pickle
        from ..core.durable_io import FOOTER_CORRUPT, FOOTER_OK, verify_footer

        def read_generation(gen_path: str, required: bool):
            """One checkpoint generation, or None when unreadable.
            FOOTER_MISSING = legacy footer-less checkpoint: loadable."""
            try:
                with open(gen_path, "rb") as f:
                    blob = f.read()
            except OSError:
                if required:
                    raise
                return None
            status, body = verify_footer(blob, self.SIM_CHECKPOINT_MAGIC)
            if status == FOOTER_CORRUPT:
                return None
            try:
                return pickle.loads(body if status == FOOTER_OK else blob)
            except Exception:  # noqa: BLE001 - any unpickle failure is
                # corruption for fallback purposes
                return None

        state = read_generation(path, required=True)
        if state is None:
            # The .prev generation write_durable retains exists exactly
            # for this moment (same fallback chain as trainer
            # checkpoints, models/train_common.load_checkpoint).
            state = read_generation(path + ".prev", required=False)
            if state is None:
                raise ValueError(
                    f"simulation checkpoint {path!r} failed its CRC "
                    "check and no loadable .prev generation exists; "
                    "re-run from the trace")
            self.log.warning("simulation checkpoint %s corrupt; resumed "
                             "from the previous generation", path)
        self.__dict__.update(state["scheduler"])
        # The checkpoint replaced _shockwave_planner with the unpickled
        # one, whose obs/journal hooks were dropped at pickle time (they
        # are bound into the saving scheduler); re-wire them to THIS
        # scheduler so post-resume planner spans and journal events land
        # in the live bundle, not a dangling ghost.
        if self._shockwave_planner is not None:
            self._shockwave_planner.obs = self._obs
            self._shockwave_planner.journal = self._emit_event
        if self._serving_tier is not None:
            # The tier pickles without its scheduler reference.
            self._serving_tier.bind(self)
        return (state["queued"], state["running"], state["remaining_jobs"],
                state["current_round"])

    def simulate(self, cluster_spec: Optional[Dict[str, int]] = None,
                 arrival_times: Sequence[float] = (), jobs: Sequence[Job] = (),
                 num_chips_per_server: Optional[Dict[str, int]] = None,
                 checkpoint_file: Optional[str] = None,
                 checkpoint_threshold: Optional[float] = None,
                 resume_from: Optional[str] = None,
                 forced_schedule: Optional[Sequence[Dict[int, Sequence[int]]]]
                 = None,
                 fault_events: Optional[Sequence[dict]] = None) -> float:
        """Discrete-event simulation of a trace. Returns the makespan.

        With `checkpoint_file` + `checkpoint_threshold` in (0, 1), the full
        simulator state is pickled once that fraction of trace jobs has
        completed (a threshold of 1.0 never fires: the loop exits when the
        last job completes). With `resume_from`, the trace arguments are
        ignored and simulation continues from the pickled state.

        With `forced_schedule` (one {integer_job_id: worker_ids} dict per
        round, i.e. a physical metric pickle's per_round_schedule), the
        live policy is bypassed and the recorded schedule is executed
        verbatim — the schedule-replay leg of the fidelity methodology:
        physical-vs-replay deltas isolate the simulator's pure timing
        model (rates, cold charges, drains) from scheduling-decision
        divergence (reference analog: reproduce/analyze_fidelity.py
        compares free-running runs only). Rounds past the end of the
        recording fall back to the live policy so a slower replay can
        finish its stragglers.

        With `fault_events` (the Monte Carlo sweep's and the chaos
        campaign's deterministic fault injection — the sim-side analog
        of runtime/faults.py), each event dict is applied at the first
        round boundary at or after its ``at`` timestamp:
        ``{"at": t, "kill": [worker_ids]}`` retires chips from capacity
        (deregister_workers); ``{"at": t, "revive": [worker_ids],
        "worker_type": wt}`` returns them; ``{"at": t, "degrade":
        [worker_ids], "factor": f}`` makes those chips run every
        micro-task at ``f`` of oracle speed (a gray failure: capacity
        unchanged, throughput silently slashed — gangs run at the
        slowest member's factor); ``{"at": t, "restore": [worker_ids]}``
        returns them to full speed. Events must be sorted by ``at``.
        None (the default) leaves the canonical replay path untouched.
        """
        if resume_from is not None:
            queued, running, remaining_jobs, current_round = (
                self._load_simulation_checkpoint(resume_from))
        else:
            for worker_type in sorted(cluster_spec):
                chips = (num_chips_per_server or {}).get(worker_type, 1)
                for _ in range(cluster_spec[worker_type] // chips):
                    self.register_worker(worker_type, num_chips=chips)

            # Stamp trace positions: job ids are assigned at ADMISSION,
            # and what-if admission deferral can reorder admissions, so
            # the positional profile lookup rides this stamp (identity
            # — and the stamp unused — on every non-deferring path).
            for position, job in enumerate(jobs):
                job.trace_position = position
            queued = list(zip(arrival_times, jobs))
            if any(b < a for (a, _), (b, _) in zip(queued, queued[1:])):
                # Ids (and the positional profiles list) follow FILE
                # order while admission is gated on the head's arrival:
                # an out-of-order line is held back to its
                # predecessor's arrival. Loud, because the fix belongs
                # in the trace, not in a reordering here (which would
                # desynchronize job ids from the profiles list).
                self.log.warning(
                    "trace arrivals are not sorted; out-of-order jobs "
                    "will be admitted late (sort the trace by arrival)")
            remaining_jobs = len(jobs)
            self._current_timestamp = (arrival_times[0]
                                       if len(arrival_times) else 0.0)
            current_round = 0
            # heap of (-finish_time, job_id, worker_ids, steps, dispatch_time)
            running: List[tuple] = []
        num_trace_jobs = remaining_jobs + len(self._completed_jobs)
        checkpoint_saved = resume_from is not None
        return self._sim_event_loop(
            queued, running, remaining_jobs, current_round,
            num_trace_jobs=num_trace_jobs,
            checkpoint_file=checkpoint_file,
            checkpoint_threshold=checkpoint_threshold,
            checkpoint_saved=checkpoint_saved,
            forced_schedule=forced_schedule,
            fault_queue=list(fault_events) if fault_events else [])

    @staticmethod
    def _requeue_deferred(queued: list, job, new_arrival: float) -> None:
        """Re-insert a deferral-gated job keeping `queued` sorted by
        arrival (stable: it lands AFTER same-arrival entries, so file
        order among ties is preserved)."""
        import bisect
        idx = bisect.bisect_right([a for a, _ in queued], new_arrival)
        queued.insert(idx, (new_arrival, job))

    def _sim_event_loop(self, queued, running, remaining_jobs,
                        current_round, num_trace_jobs: int = 0,
                        checkpoint_file: Optional[str] = None,
                        checkpoint_threshold: Optional[float] = None,
                        checkpoint_saved: bool = True,
                        forced_schedule=None, fault_queue=None,
                        schedule_first: bool = False) -> float:
        """The discrete-event loop `simulate()` runs — split out so a
        what-if twin (whatif/fork.rollforward) can re-enter it from a
        forked mid-run state. With ``schedule_first`` the first
        iteration skips the event-advance head (checkpoint, clock,
        drain, arrivals, faults) and immediately schedules a round at
        the frozen clock: a twin forked at the simulator's clean round
        boundary — heap drained, arrivals admitted, next round not yet
        planned — continues exactly where its parent's loop stood.
        Returns the final simulated timestamp (makespan semantics as
        documented on simulate())."""
        fault_queue = fault_queue or []
        forced_resolve = False
        while remaining_jobs > 0:
            if schedule_first:
                # Fork re-entry: the parent already ran this
                # iteration's head before the fork point.
                schedule_first = False
            else:
                # Checkpoint at the top of the iteration so a resumed
                # run re-enters the loop with byte-identical local
                # state.
                if (not checkpoint_saved and checkpoint_file is not None
                        and checkpoint_threshold is not None
                        and num_trace_jobs > 0
                        and (num_trace_jobs - remaining_jobs)
                        / num_trace_jobs >= checkpoint_threshold):
                    self.save_simulation_checkpoint(
                        checkpoint_file, queued, running, remaining_jobs,
                        current_round)
                    checkpoint_saved = True

                next_arrival = queued[0][0] if queued else None

                # Advance the clock to the next event.
                max_ts = 0.0
                if running and -running[0][0] > max_ts:
                    max_ts = -running[0][0]
                if max_ts > 0:
                    if (self._deployment_faithful
                            and self._sim_round_start is not None):
                        # Wall-clocked rounds (see _deployment_faithful):
                        # a round never rolls before its full duration
                        # even when every micro-task finished early.
                        max_ts = max(max_ts, self._sim_round_start
                                     + self._time_per_iteration)
                    self._current_timestamp = max_ts
                    forced_resolve = False
                elif next_arrival is not None:
                    # max(): a burned replay round may already have
                    # pushed the clock past this arrival — never rewind
                    # it.
                    target = max(self._current_timestamp, next_arrival)
                    if self._serving_live():
                        # A live service must be consulted every round
                        # even while idle — jumping straight to a
                        # far-future arrival would skip its load ramp
                        # (no scale-up, no SLO accounting for the gap).
                        # Bound the jump to one round; the loop walks
                        # the rest round by round.
                        target = min(target, self._current_timestamp
                                     + self._time_per_iteration)
                    self._current_timestamp = target
                    forced_resolve = False
                elif self.acct.jobs and not forced_resolve:
                    # Dead air: jobs are waiting but the allocation-
                    # reset interval hasn't elapsed, so the stale
                    # allocation excludes them all. Force a re-solve
                    # rather than deadlocking (the reference would
                    # crash here: its scheduler.py:1913 assigns a None
                    # timestamp).
                    forced_resolve = True
                    self._last_reset_time = (
                        self._current_timestamp
                        - self._config
                        .minimum_time_between_allocation_resets)
                    self._need_to_update_allocation = True
                elif self._serving_live():
                    # Nothing running and no arrivals, but a serving
                    # service is within its lifetime (possibly at zero
                    # replicas): roll the clock one round so the
                    # autoscaler keeps being consulted and the service
                    # can scale back up / retire.
                    self._current_timestamp += self._time_per_iteration
                    forced_resolve = False
                elif fault_queue:
                    # Nothing can run until an injected fault resolves
                    # (e.g. every remaining job needs more chips than
                    # the surviving capacity): advance to the next
                    # fault event (typically a revive) instead of
                    # declaring deadlock.
                    self._current_timestamp = max(
                        self._current_timestamp,
                        float(fault_queue[0]["at"]))
                    forced_resolve = False
                else:
                    self.log.warning("no running jobs and no arrivals; "
                                     "stopping")
                    break

                # Drain jobs finishing this round.
                while running:
                    (neg_finish, job_id, worker_ids, all_num_steps,
                     dispatch_time) = running[0]
                    finish_time = -neg_finish
                    if finish_time > self._current_timestamp:
                        break
                    slowdown = 1.0
                    # Time actually spent this round; using the dispatch
                    # timestamp (not the previous round's end) keeps
                    # idle cluster gaps and a nonzero first arrival from
                    # inflating the measurement.
                    execution_time = finish_time - dispatch_time
                    # Reference-parity flat post-preemption charge —
                    # replaced by the measured charges for calibrated
                    # worker types; an uncalibrated type in a partially
                    # calibrated oracle keeps the flat charge rather
                    # than costing nothing.
                    if (current_round >= 2
                            and not self._worker_type_calibrated(
                                self.workers.id_to_type[worker_ids[0]])):
                        prev_sched = self.rounds.per_round_schedule[
                            current_round - 2]
                        for m in job_id.singletons():
                            if not self._in_recorded_round(
                                    prev_sched, m.integer_job_id()):
                                # Preempted last round: charge
                                # checkpoint/restore. The charge must
                                # never exceed the round itself (a
                                # sub-20s round would go NEGATIVE and
                                # synthesize a failure); at canonical
                                # 120s rounds the near-full-round guard
                                # already implies this, so the replay
                                # math is untouched.
                                if (execution_time
                                        > PREEMPTION_OVERHEAD_S and
                                        self._time_per_iteration - 5
                                        < execution_time):
                                    slowdown = ((execution_time
                                                 - PREEMPTION_OVERHEAD_S)
                                                / execution_time)
                                    execution_time -= PREEMPTION_OVERHEAD_S
                                break
                    all_execution_times = []
                    for m in job_id.singletons():
                        all_execution_times.append(execution_time)
                        self.acct.latest_timestamps[m] = finish_time
                    self._in_progress_updates[job_id] = []
                    scale_factor = len(worker_ids)
                    adj_steps = [int(s * slowdown) for s in all_num_steps]
                    assigned = [0] * len(adj_steps)
                    per_worker_steps = []
                    for i in range(scale_factor):
                        if i == scale_factor - 1:
                            per_worker = [adj_steps[j] - assigned[j]
                                          for j in range(len(adj_steps))]
                        else:
                            per_worker = [s // scale_factor
                                          for s in adj_steps]
                        for j in range(len(per_worker)):
                            assigned[j] += per_worker[j]
                        per_worker_steps.append(per_worker)
                    if self._vectorized:
                        simcore.complete_microtask_batch(
                            self, job_id, worker_ids, per_worker_steps,
                            all_execution_times)
                    else:
                        for i, worker_id in enumerate(worker_ids):
                            self.done_callback(job_id, worker_id,
                                               per_worker_steps[i],
                                               all_execution_times)
                    for m in job_id.singletons():
                        if m not in self.acct.jobs:
                            remaining_jobs -= 1
                    heapq.heappop(running)

                # Adaptation oracles run between rounds.
                for job_id in list(self.acct.jobs):
                    mode = self.acct.jobs[job_id].mode
                    if mode == "accordion":
                        self._simulate_accordion(job_id)
                    elif mode == "gns":
                        self._simulate_gns(job_id)

                if (self._shockwave_planner is not None
                        and self._current_timestamp != 0.0
                        and self._scheduled_jobs_in_current_round
                        is not None):
                    self._update_shockwave_planner()

                assert not running

                # Admit arrivals — through the what-if admission gate
                # when a plane is configured (mode "gate" may defer a
                # candidate by re-queueing it at a later arrival; the
                # default always-admit plane returns 0.0 untouched).
                while queued and queued[0][0] <= self._current_timestamp:
                    arrival_time, job = queued.pop(0)
                    if self._whatif is not None:
                        defer_s = self._whatif.gate_admission(
                            job, arrival_time, queued)
                        if defer_s > 0:
                            if not hasattr(job, "deferred_from"):
                                # First deferral: remember the ORIGINAL
                                # arrival — the job's fairness clock.
                                job.deferred_from = arrival_time
                            self._requeue_deferred(
                                queued, job,
                                self._current_timestamp + defer_s)
                            continue
                    # A deferred job is admitted AT ITS ORIGINAL
                    # ARRIVAL stamp: start_timestamps (and therefore
                    # JCT, FTF rho and the SLO deadline) include every
                    # second the gate made it wait — admission control
                    # must beat always-admit on honest accounting, not
                    # by laundering queueing delay out of the metric.
                    self.add_job(job, timestamp=getattr(
                        job, "deferred_from", arrival_time))

                # Apply due fault-injection events (sweep scenarios
                # only; the queue is empty on the canonical replay
                # path).
                while (fault_queue and float(fault_queue[0]["at"])
                        <= self._current_timestamp):
                    event = fault_queue.pop(0)
                    if event.get("kill"):
                        self.deregister_workers(
                            [int(w) for w in event["kill"]])
                        self._obs.inc(obs_names.SIM_FAULT_EVENTS_TOTAL,
                                      action="kill")
                    if event.get("revive"):
                        self.revive_workers(
                            [int(w) for w in event["revive"]],
                            event["worker_type"])
                        self._obs.inc(obs_names.SIM_FAULT_EVENTS_TOTAL,
                                      action="revive")
                    if event.get("degrade"):
                        factor = float(event.get("factor", 0.1))
                        if not 0.0 < factor <= 1.0:
                            raise ValueError(f"degrade factor must be in "
                                             f"(0, 1], got {factor!r}")
                        for w in event["degrade"]:
                            self._sim_degraded[int(w)] = factor
                        self.log.warning("[Fault] chips %s degraded to "
                                         "%.2fx speed",
                                         list(event["degrade"]), factor)
                        self._obs.inc(obs_names.SIM_FAULT_EVENTS_TOTAL,
                                      action="degrade")
                    if event.get("restore"):
                        for w in event["restore"]:
                            self._sim_degraded.pop(int(w), None)
                        self.log.info("[Fault] chips %s restored to full "
                                      "speed", list(event["restore"]))
                        self._obs.inc(obs_names.SIM_FAULT_EVENTS_TOTAL,
                                      action="restore")

                if not self.acct.jobs and not self._serving_live():
                    if not queued:
                        break
                    continue

                # The clean fork point: heap drained, arrivals
                # admitted, next round not yet planned. Knob sweeps,
                # forecasts and the capture hook run here.
                if self._whatif is not None:
                    self._whatif.on_round_boundary(current_round, queued,
                                                   remaining_jobs)

            # Schedule the next round.
            if (forced_schedule is not None
                    and current_round < len(forced_schedule)):
                assignments = self._execute_forced_assignments(
                    forced_schedule[current_round])
                if not assignments:
                    # The recorded round ran only jobs this replay has
                    # already finished (clock skew between the two
                    # runs): burn the round so later recorded rounds
                    # keep their physical indices.
                    self.rounds.current_assignments = assignments
                    self._current_timestamp += self._time_per_iteration
                    self._sim_round_start = self._current_timestamp
                    current_round += 1
                    self.rounds.num_completed_rounds += 1
                    if (self._config.max_rounds is not None
                            and self.rounds.num_completed_rounds
                            >= self._config.max_rounds):
                        break
                    continue
            else:
                with self._obs.phase(obs_names.SPAN_SOLVE,
                                     round=current_round):
                    assignments = self._schedule_jobs_on_workers()
            if self._serving_tier is not None:
                # Services retired by this round's serving plan leave
                # the trace's remaining-jobs budget.
                remaining_jobs -= self._serving_tier.take_retired_count()
            for job_id in self.rounds.current_assignments:
                if any(m in self.acct.jobs for m in job_id.singletons()):
                    self.rounds.num_lease_opportunities += 1
            warm_jobs = set()
            for job_id in assignments:
                if job_id in self.rounds.current_assignments:
                    if set(self.rounds.current_assignments[job_id]) == set(
                            assignments[job_id]):
                        self.rounds.num_lease_extensions += 1
                        # Same workers as last round: the physical lease
                        # would extend, so no new process is spawned.
                        warm_jobs.add(job_id)
            self.rounds.current_assignments = assignments

            for job_id, worker_ids in assignments.items():
                worker_type = self.workers.id_to_type[worker_ids[0]]
                overhead = drain = 0.0
                if (job_id not in warm_jobs
                        and self._worker_type_calibrated(worker_type)):
                    overhead = self._cold_dispatch_overhead(
                        worker_type, job_id) or 0.0
                    drain = self._cold_round_drain(worker_type, job_id)
                rate_scale = 1.0
                if self._sim_degraded:
                    # Injected gray failure: the gang runs at its
                    # slowest member's speed. Empty dict (every
                    # canonical path) skips this entirely, so the
                    # default float math is untouched.
                    rate_scale = min(self._sim_degraded.get(w, 1.0)
                                     for w in worker_ids)
                all_num_steps, finish_time = self._steps_and_finish_time(
                    job_id, worker_type, overhead, rate_scale=rate_scale)
                # Post-lease dead time shifts the cycle without eating
                # the step budget (see _round_drain above). It is also
                # excluded from execution-time accounting — shifting the
                # recorded dispatch timestamp by the drain keeps
                # execution_time = finish - dispatch equal to
                # overhead + compute, so run-time/deadline/cost
                # accounting never accrues phantom drain seconds.
                finish_time += drain
                heapq.heappush(
                    running, (-finish_time, job_id, worker_ids, all_num_steps,
                              self._current_timestamp + drain))
            self._sim_round_start = self._current_timestamp

            current_round += 1
            self.rounds.num_completed_rounds += 1
            self._obs_update_round_gauges()
            if (self._config.max_rounds is not None
                    and self.rounds.num_completed_rounds >= self._config.max_rounds):
                break

        # Deployment-faithful mode: when the trace drained fully, rewind
        # the exit clock from the padded final-round boundary to the
        # last completion — the stamp the physical driver tears down at
        # (get_last_completion_time) — so makespan AND every
        # current-timestamp-based metric (utilization denominators,
        # timelines) share the physical clock. Unfinished exits
        # (max_rounds / no runnable work) keep the elapsed event clock,
        # matching run_physical's all_jobs_completed fallback. Default
        # mode is untouched: its exit clock already equals the last
        # completion (replay parity).
        if (self._deployment_faithful and remaining_jobs == 0
                and self._last_completion_time > 0):
            self._current_timestamp = self._last_completion_time
        self.log.info("Simulation done: makespan %.1fs (%.2fh)",
                    self._current_timestamp, self._current_timestamp / 3600)
        return self._current_timestamp

    def _cold_dispatch_overhead(self, worker_type: str, job_id: JobIdPair):
        """Measured cold-dispatch charge for this job on this worker
        type under the calibrated model, or None when not calibrated.
        Precedence: an explicit config entry for THIS worker type beats
        everything (an operator override must not be shadowed by stale
        oracle metadata), but types the config dict does not cover fall
        through to the oracle; within the oracle, the deployed in-lease
        shortfall (by-type, then scalar) beats the solo spawn->exit
        proxy (by-type, then scalar) — the shortfall was measured
        through the real runtime, so it is the more faithful
        step-budget charge. Pairs charge the slower-starting member."""
        explicit = self._config.dispatch_overhead_s or {}
        if worker_type in explicit:
            return explicit[worker_type]
        # A worker type the explicit dict does NOT cover falls through
        # to the oracle values — otherwise a type calibrated only via
        # oracle metadata would silently pay no startup cost while
        # _worker_type_calibrated still disabled the flat charge.
        typed = self._per_type_max(
            self._shortfall_by_type.get(worker_type, {}), job_id)
        if typed is not None:
            return typed
        if worker_type in self._lease_shortfall:
            return self._lease_shortfall[worker_type]
        typed = self._per_type_max(
            self._dispatch_overhead_by_type.get(worker_type, {}), job_id)
        if typed is not None:
            return typed
        return (self._dispatch_overhead or {}).get(worker_type)

    def _worker_type_calibrated(self, worker_type: str) -> bool:
        """Whether any calibration entry covers this worker type — the
        per-type switch between measured charges and the reference's
        flat post-preemption charge (a partially calibrated oracle must
        not zero out its uncalibrated types)."""
        return (worker_type in (self._config.dispatch_overhead_s or {})
                or worker_type in (self._dispatch_overhead or {})
                or worker_type in self._dispatch_overhead_by_type
                or worker_type in self._lease_shortfall
                or worker_type in self._shortfall_by_type
                or worker_type in self._round_drain
                or worker_type in self._round_drain_by_type
                or worker_type in self._round_drain_by_sf)

    def _per_type_max(self, by_type: Dict[str, float], job_id: JobIdPair):
        """Largest per-job-type calibration value among the pair's
        members (the slower-starting member gates the pair), or None
        when no member's job type is profiled."""
        typed = [by_type[self.acct.jobs[m].job_type]
                 for m in job_id.singletons()
                 if m in self.acct.jobs
                 and self.acct.jobs[m].job_type in by_type]
        return max(typed) if typed else None

    def _cold_round_drain(self, worker_type: str, job_id: JobIdPair) -> float:
        """Post-lease dead time for a cold dispatch of this job. For
        gangs (sf>1) a per-scale-factor measurement wins; otherwise the
        per-type measurement wins over the per-worker-type mean."""
        sf = max((self.acct.jobs[m].scale_factor
                  for m in job_id.singletons() if m in self.acct.jobs),
                 default=1)
        if sf > 1:
            by_sf = self._round_drain_by_sf.get(worker_type, {})
            if str(sf) in by_sf:
                return by_sf[str(sf)]
        typed = self._per_type_max(
            self._round_drain_by_type.get(worker_type, {}), job_id)
        if typed is not None:
            return typed
        return self._round_drain.get(worker_type, 0.0)

    def _steps_and_finish_time(self, job_id: JobIdPair, worker_type: str,
                               overhead: float = 0.0,
                               rate_scale: float = 1.0):
        """Oracle-throughput step count and finish time for the next round.

        With `overhead` > 0 (calibrated cold-dispatch model), the first
        `overhead` seconds of the round are process startup: the step
        budget shrinks and a final partial round's completion is pushed
        back by the startup time — matching what the physical dispatcher
        actually measures (spawn -> first step).

        `rate_scale` < 1 is an injected gray failure (simulate()'s
        degrade fault events): the oracle rate is multiplied before any
        other math, so a degraded round produces proportionally fewer
        steps in the same wall window. The default of exactly 1.0 skips
        the multiply — canonical replays stay bit-identical."""
        now = self.get_current_timestamp()
        budget = max(self._time_per_iteration - overhead, 1.0)
        max_finish = now
        all_num_steps = []
        for m in job_id.singletons():
            tput = self._oracle_step_throughput(job_id, worker_type, m)
            if rate_scale != 1.0:
                tput *= rate_scale
            if tput <= 0:
                raise RuntimeError(f"zero throughput for {m} on {worker_type}")
            num_steps = int(tput * budget)
            if overhead > 0 or rate_scale != 1.0:
                # Calibrated / degraded model only: at least one step
                # per dispatch, else a near-round-sized overhead (or a
                # deep degrade) would zero the round — a zero-step
                # completion is the micro-task FAILURE signal, and an
                # injected slowdown must never charge the job a
                # failure. The default path stays reference-exact.
                num_steps = max(num_steps, 1)
            num_steps = min(num_steps, self._get_remaining_steps(m))
            all_num_steps.append(num_steps)
            max_finish = max(max_finish, now + overhead + num_steps / tput)
            self._running_jobs.add(m)
        return all_num_steps, max_finish

    def _oracle_step_throughput(self, job_id, worker_type, member):
        if (self._oracle_truth is not None and not job_id.is_pair()
                and (member.integer_job_id(), worker_type)
                in self._oracle_predicted):
            # Oracle-managed entry (learned/prior-seeded, never
            # profiled): execute the micro-task at the TRUE rate from
            # the held-out truth table while _throughputs keeps the
            # planner's converging estimate — the cold-start acceptance
            # methodology (reproduce/oracle/). Absent a truth row the
            # estimate itself drives execution, as before.
            job = self.acct.jobs.get(member)
            if job is not None:
                entry = self._oracle_truth.get(worker_type, {}).get(
                    (job.job_type, job.scale_factor))
                if entry is not None and entry.get("null", 0.0) > 0.0:
                    return entry["null"]
        # Both pair and single entries are kept in sync with the oracle (and
        # refreshed on batch-size rescale), so read the scheduler's view.
        if job_id.is_pair():
            idx = job_id.as_tuple().index(member[0])
            return self._throughputs[job_id][worker_type][idx]
        return self._throughputs[job_id][worker_type]

    # ------------------------------------------------------------------
    # Metrics
    # ------------------------------------------------------------------

    def get_average_jct(self, job_ids=None):
        ct = self.acct.completion_times
        if not ct:
            return None
        # Serving replicas "complete" at scale-down/retirement — a JCT
        # is meaningless for them (serving quality lives in
        # serving_summary()), so they stay out of training aggregates.
        job_ids = sorted(j for j in (job_ids if job_ids is not None
                                     else ct.keys())
                         if j not in self._serving_job_ids)
        times = [ct[j] for j in job_ids if ct[j] is not None]
        if not times:
            return None
        import scipy.stats
        return (float(np.mean(times)),
                float(scipy.stats.mstats.gmean(times)),
                float(scipy.stats.hmean(times)),
                times)

    def get_finish_time_fairness(self, job_ids=None):
        """Per-job rho = JCT / (isolated runtime * contention factor), with
        both static and Themis-style contention factors
        (reference: scheduler.py:2865-2964)."""
        ct = self.acct.completion_times
        if not ct:
            return [], []
        num_chips = len(self.workers.worker_ids)
        job_ids = sorted(j for j in (job_ids if job_ids is not None
                                     else ct.keys())
                         if j not in self._serving_job_ids)
        static_list, themis_list = [], []
        for job_id in job_ids:
            completion_time = ct[job_id]
            if completion_time is None:
                continue
            int_id = job_id.integer_job_id()
            profile = self._profile_for(int_id)
            exclusive = (sum(profile["duration_every_epoch"])
                         if profile is not None else None)
            if exclusive is None:
                continue
            static_cf = max(1.0, self._num_jobs_in_trace / num_chips)
            static_list.append(round(completion_time / (exclusive * static_cf), 5))
            start_r = self.rounds.job_start_round.get(int_id, 0)
            end_r = self.rounds.job_end_round.get(int_id, start_r)
            window = self.rounds.jobs_in_round[start_r:end_r]
            themis_cf = max(1.0, float(np.mean(window)) / num_chips) if window else 1.0
            themis_list.append(round(completion_time / (exclusive * themis_cf), 5))
        return static_list, themis_list

    def get_total_cost(self) -> float:
        """Accumulated $ cost across jobs, priced per worker type per hour
        (reference: scheduler.py:3060-3067)."""
        return float(sum(self._job_cost_so_far.values()))

    def get_num_slo_violations(self) -> int:
        """Jobs whose completion timestamp exceeded SLO * isolated duration
        + arrival (reference: scheduler.py:3069-3084)."""
        violations = 0
        for job_id, deadline in self._slo_deadlines.items():
            finished_at = self.acct.latest_timestamps.get(job_id)
            if job_id in self._completed_jobs and finished_at is not None:
                if finished_at > deadline:
                    violations += 1
            elif self.get_current_timestamp() > deadline:
                violations += 1  # still running past its deadline
        return violations

    def save_job_timelines(self, timeline_dir: str) -> None:
        """Dump each job's event timeline (submit / micro-tasks / complete)
        to <dir>/job_id=N.log (reference: scheduler.py:3109-3128)."""
        import os
        os.makedirs(timeline_dir, exist_ok=True)
        for int_id in sorted(self._job_timelines):
            path = os.path.join(timeline_dir, f"job_id={int_id}.log")
            # Telemetry dump, not durable state: a torn log costs nothing.
            with open(path, "w") as f:  # swtpu-check: ignore[durability]
                f.write("\n".join(self._job_timelines[int_id]) + "\n")

    def get_cluster_utilization(self):
        utils = []
        now = self.get_current_timestamp()
        for worker_id, busy in self.workers.cumulative_time.items():
            total = now - self.workers.start_times[worker_id]
            if total > 0:
                utils.append(round(busy / total, 5))
        return (float(np.mean(utils)) if utils else 0.0), utils

    def get_envy_ratios(self):
        ratios = {}
        for int_id in range(self._job_id_counter):
            s = self.rounds.num_scheduled_rounds.get(int_id, 0)
            q = self.rounds.num_queued_rounds.get(int_id, 0)
            if s + q > 0:
                ratios[int_id] = s / (s + q)
        values = list(ratios.values())
        pairwise = [abs(a - b) for i, a in enumerate(values)
                    for b in values[:i]]
        return ratios, pairwise

    def get_num_lease_extensions(self):
        opp = self.rounds.num_lease_opportunities
        ext = self.rounds.num_lease_extensions
        return ((100.0 * ext / opp) if opp else 0.0, ext, opp)

    def get_makespan(self) -> float:
        return self._current_timestamp

    def get_last_completion_time(self) -> float:
        """Scheduler-clock timestamp of the last job completion. The
        physical driver reports this as makespan — matching the
        reference's measurement (poll is_done, then stamp elapsed;
        run_scheduler_with_trace.py:120-155) — so round-drain and
        shutdown time after the final completion don't inflate it."""
        return self._last_completion_time

    def get_num_completed_jobs(self) -> int:
        """Completed TRACE jobs: training jobs plus retired serving
        services. Serving replicas (internal autoscaling artifacts, not
        trace jobs) are excluded."""
        return len([j for j in self._completed_jobs
                    if j not in self._serving_job_ids])

    def get_throughput_timeline(self):
        """Per-job {round: (throughput, batch_size)} measurement history."""
        return {job_id: dict(tl)
                for job_id, tl in self._throughput_timeline.items()}

    def get_solve_stats(self):
        """Per-solve MILP quality telemetry (shockwave planner only):
        list of dicts with path/status/mip_gap/wall_s per re-solve, or
        [] for LP policies."""
        if self._shockwave_planner is None:
            return []
        from dataclasses import asdict
        return [asdict(s) for s in self._shockwave_planner.solve_stats]
