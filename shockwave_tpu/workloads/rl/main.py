#!/usr/bin/env python3
"""A3C RL workload (trace: "A3C").

CLI parity with the reference's rl/main.py — the trace command is
`python3 main.py --env PongDeterministic-v4 --workers 4 --amsgrad True`
with `--max-steps` appended by the dispatcher
(reference: workloads/pytorch/rl/main.py).

The reference runs `--workers` asynchronous actor processes; here the
actors are a batch dimension of a vectorized pure-JAX environment and one
tick = one n-step unroll + update, fully compiled (see models/a3c.py).
Like the reference (rl/main.py:184-187), the lease iterator wraps the
tick counter: one iterator step == one update.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                *[".."] * 3))

import jax
import optax

from shockwave_tpu.models.a3c import (ActorCritic, build_a3c_update,
                                      env_observe, env_reset)
from shockwave_tpu.models.train_common import (checkpoint_path, common_parser,
                                               enable_compile_cache,
                                               load_checkpoint, parse_args,
                                               save_checkpoint_rank0)
from shockwave_tpu.runtime.iterator import LeaseIterator

INFINITY = 10 ** 9


class _TickLoader:
    """An 'epoch' of update ticks for the lease iterator to meter."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self):
        return self._n

    def __iter__(self):
        return iter(range(self._n))


def main():
    p = common_parser("A3C", steps_args=("--max-steps",))
    p.add_argument("--env", default="PongDeterministic-v4",
                   help="kept for trace-command parity; the built-in "
                        "vectorized catch/pong environment is always used")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--amsgrad", default="True")
    p.add_argument("--unroll", type=int, default=20)
    p.add_argument("--seed", type=int, default=1)
    args = parse_args(p)
    enable_compile_cache()

    model = ActorCritic()
    rng = jax.random.PRNGKey(args.seed)
    env_state = env_reset(rng, args.workers)
    params = model.init(rng, env_observe(env_state))["params"]
    tx = optax.adam(args.lr)
    train_state = {"params": params, "opt_state": tx.init(params),
                   "rng": rng, "step": jax.numpy.zeros((), jax.numpy.int32)}
    update = build_a3c_update(model, tx, unroll=args.unroll)

    budget = args.num_steps if args.num_steps is not None else INFINITY
    ckpt = checkpoint_path(args.checkpoint_dir)

    def load(path):
        return load_checkpoint(path, jax.device_get(train_state))

    if args.enable_lease_iterator:
        iterator = LeaseIterator(_TickLoader(budget), args.checkpoint_dir,
                                 load_checkpoint_func=load,
                                 save_checkpoint_func=save_checkpoint_rank0,
                                 synthetic_data=args.synthetic_data)
        restored = iterator.load_checkpoint(ckpt)
    else:
        iterator = None
        restored = load(ckpt)
    if restored is not None:
        restored["rng"] = jax.numpy.asarray(restored["rng"],
                                            train_state["rng"].dtype)
        train_state = restored
    start_step = int(train_state["step"])

    steps_done, window_steps = 0, 0
    metrics = None
    try:
        for _ in (iterator if iterator is not None else range(budget)):
            train_state, env_state, metrics = update(train_state, env_state)
            if iterator is not None:
                iterator.set_sync_ref(metrics["loss"])
            steps_done += 1
            window_steps += 1
            if window_steps >= args.throughput_estimation_interval:
                jax.block_until_ready(metrics["loss"])
                print(f"[THROUGHPUT_ESTIMATION]\t{time.time()}\t"
                      f"{start_step + steps_done}", flush=True)
                window_steps = 0
            if start_step + steps_done >= budget:
                if iterator is not None:
                    iterator.complete()
                break
    finally:
        if metrics is not None:
            jax.block_until_ready(metrics["loss"])
        if iterator is not None:
            iterator.save_checkpoint(ckpt, train_state)
        else:
            save_checkpoint_rank0(ckpt, train_state)
    print(f"TRAINED {steps_done} steps (cumulative {start_step + steps_done})",
          flush=True)


if __name__ == "__main__":
    main()
