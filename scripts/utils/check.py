#!/usr/bin/env python3
"""One-shot local/CI check driver: ruff + swtpu-check + fsck smoke.

    python scripts/utils/check.py

Runs, in order:

1. ``ruff check .`` — generic Python hygiene (config in pyproject.toml).
   Skipped with a warning when ruff is not installed (the runtime image
   does not ship it; CI installs it).
2. ``python -m shockwave_tpu.analysis`` — the repo-aware invariant
   analyzer (lock discipline, journal coverage, durability,
   determinism, exception hygiene).
3. ``scripts/utils/fsck_journal.py --help`` — smoke-check that the
   offline journal validator stays importable and argparse-clean.

Exit status is non-zero iff any check that RAN failed; a skipped check
never masks a failure.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def _run(label: str, argv: list) -> bool:
    print(f"=== {label}: {' '.join(argv)}")
    proc = subprocess.run(argv, cwd=REPO)
    status = "OK" if proc.returncode == 0 else f"FAILED (exit {proc.returncode})"
    print(f"=== {label}: {status}")
    return proc.returncode == 0


def main() -> int:
    results = {}

    if shutil.which("ruff"):
        results["ruff"] = _run("ruff", ["ruff", "check", "."])
    else:
        print("=== ruff: SKIPPED (not installed; `pip install ruff` or "
              "rely on CI)")

    results["swtpu-check"] = _run(
        "swtpu-check", [sys.executable, "-m", "shockwave_tpu.analysis"])

    results["fsck-smoke"] = _run(
        "fsck-smoke", [sys.executable,
                       os.path.join("scripts", "utils", "fsck_journal.py"),
                       "--help"])

    failed = [name for name, ok in results.items() if not ok]
    if failed:
        print(f"check.py: FAILED ({', '.join(failed)})")
        return 1
    print(f"check.py: all {len(results)} check(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
