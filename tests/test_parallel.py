"""Parallel layer tests on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.parallel.mesh import (data_parallel_sharding, make_mesh,
                                         replicate, shard_batch)
from shockwave_tpu.parallel.ring_attention import (reference_attention,
                                                   ring_attention)


@pytest.fixture(scope="module")
def devices():
    ds = jax.devices()
    if len(ds) < 8:
        pytest.skip("needs 8 virtual devices")
    return ds


class TestMesh:
    def test_make_mesh_shapes(self, devices):
        mesh = make_mesh()
        assert mesh.devices.size == len(devices)
        mesh = make_mesh(dp=2, tp=2, sp=2)
        assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}

    def test_mismatched_mesh_raises(self, devices):
        with pytest.raises(AssertionError):
            make_mesh(dp=3, tp=3, sp=1)

    def test_shard_and_replicate(self, devices):
        mesh = make_mesh()
        batch = jnp.arange(16.0).reshape(16, 1)
        sharded = shard_batch(mesh, batch)
        assert sharded.sharding.spec == jax.sharding.PartitionSpec("dp")
        params = {"w": jnp.ones((4, 4))}
        rep = replicate(mesh, params)
        assert rep["w"].sharding.is_fully_replicated

    def test_dp_gradient_allreduce(self, devices):
        """A jit'd loss over a dp-sharded batch must equal the unsharded one
        (XLA inserts the cross-chip reduction)."""
        mesh = make_mesh()
        batch_sh, repl_sh = data_parallel_sharding(mesh)
        w = jax.device_put(jnp.ones((4,)), repl_sh)
        x = jnp.arange(32.0).reshape(8, 4)

        def loss(w, x):
            return jnp.mean((x @ w) ** 2)

        g_sharded = jax.jit(jax.grad(loss))(w, jax.device_put(x, batch_sh))
        g_local = jax.grad(loss)(jnp.ones((4,)), x)
        np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_local),
                                   rtol=1e-6)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, devices, causal):
        mesh = make_mesh(dp=1, tp=1, sp=8)
        rng = jax.random.PRNGKey(0)
        b, s, h, d = 2, 64, 4, 16
        q, k, v = (jax.random.normal(key, (b, s, h, d), jnp.float32)
                   for key in jax.random.split(rng, 3))
        expected = reference_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-3, atol=2e-3)

    def test_long_sequence_sharded_memory(self, devices):
        # Just exercises a longer sequence through the ring path.
        mesh = make_mesh(dp=1, tp=1, sp=8)
        rng = jax.random.PRNGKey(1)
        q = k = v = jax.random.normal(rng, (1, 512, 2, 8), jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=True)
        assert out.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(out)))
