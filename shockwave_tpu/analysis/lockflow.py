"""Interprocedural held-locks dataflow: static deadlock & blocking-
under-lock detection, cross-checked against the runtime sanitizer.

PR 14's race detector proves every cross-thread field *holds a* lock;
this module proves two properties about the locks themselves:

- **deadlock** — every acquire-while-holding site (and every call made
  with a lock held whose callees transitively acquire more locks)
  contributes an edge to ONE static lock-order graph. A cycle in that
  graph reachable from two or more thread roots (or one self-concurrent
  root — a gRPC/HTTP handler pool) is a deadlock an unlucky
  interleaving can hit, including interleavings no explorer seed
  schedules. Lock identities are the sanitizer's display names
  (``maybe_wrap(lock, "PhysicalScheduler._lock")``), so the runtime
  order graph the sanitizer exports (``SWTPU_SANITIZE_GRAPH_OUT``) is
  directly comparable: CI asserts **runtime edges ⊆ static edges**
  every explorer run — the dynamic tool audits the static tool's
  soundness.

- **hold-discipline** — a taxonomy of blocking operations (gRPC stub
  methods and the ``runtime/clients.py`` wrappers, ``os.fsync``,
  subprocess ``wait``/``communicate``, ``time.sleep``, timeout-less
  ``Condition.wait``, queue/socket ops, the planner MILP solve) is a
  finding whenever one is statically reachable with any lock held. A
  blocking call under a lock is a latency cliff for every thread that
  wants that lock — and under the scheduler ``_cv`` it stalls the round
  pipeline the paper's restart-overhead numbers depend on.

Verdicts can be *documented* instead of restructured, mirroring the
race detector's ``_EXTERNALLY_SYNCHRONIZED``:

- ``_LOCK_ORDER_JUSTIFIED = frozenset({"A->B", ...})`` (class body) —
  the named directed edges are sanctioned; a cycle is reported only if
  at least one of its edges is NOT justified. Stale entries (naming an
  edge the analysis no longer sees) are themselves findings.
- ``_HOLD_DISCIPLINE_JUSTIFIED = frozenset({"method:kind", ...})``
  (class body; ``"method:*"`` covers every kind) — the named method may
  perform that class of blocking call under a lock, with the
  declaration's comment carrying the justification (e.g. a bounded-
  deadline RPC that is part of the dispatch protocol). Stale entries
  are findings too.

The dataflow itself: for every function in the memoized call graph,
a lexical walk folds the held-lock set through ``with self._lock:``
frames, ``@requires_lock`` contracts (implies the receiver's canonical
``_lock``), Condition aliasing (``_cv`` ≡ ``_lock``), and explicit
statement-level ``self._cv.release()`` / ``.acquire()`` toggles (the
release-sleep-reacquire idiom in ``_finish_round``). Acquire facts and
blocking facts then propagate bottom-up through a fixpoint over the
call graph, so "calls a helper that fsyncs" is the same finding as
fsyncing inline. Thread-root reachability (analysis/threads.py) scopes
findings to code a real thread can execute.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .core import (Finding, RepoIndex, SourceFile, call_name, const_str,
                   decorated_requires_lock, finding, is_self_attr,
                   literal_str_set)
from .threads import (CALLBACK_ROOT_KWARGS, RPC_SERVE_FUNCS,
                      SELF_CONCURRENT_KINDS, CallGraph, FuncInfo, FuncKey,
                      discover_thread_roots)

PASS_DEADLOCK = "deadlock"
PASS_HOLD = "hold-discipline"

#: Class-body registry of sanctioned lock-order edges ("A->B" strings,
#: sanitizer display names).
ORDER_REGISTRY_NAME = "_LOCK_ORDER_JUSTIFIED"
#: Class-body registry of sanctioned blocking-under-lock sites
#: ("method:kind", or "method:*" for every kind).
HOLD_REGISTRY_NAME = "_HOLD_DISCIPLINE_JUSTIFIED"

#: Mirrors races.DEFAULT_LOCK_ATTRS: honored as locks even without a
#: detected constructor assignment.
DEFAULT_LOCK_ATTRS = frozenset({"_lock", "_cv"})

#: RPC wrapper methods looked up BY NAME when the receiver cannot be
#: resolved (clients pulled out of dicts: `self._worker_connections[w]`,
#: `host["client"]`). Deliberately excludes generic names like "reset"
#: or "shutdown" — `self.breaker.reset()` is not an RPC.
RPC_FALLBACK_METHODS = frozenset({
    "run_job", "kill_job", "notify_done", "register_worker",
    "update_lease", "ping",
})

#: Known blocking sinks seeded by (file, bare function name, kind):
#: the resolver reaches these through normal call edges, and the
#: fixpoint then carries the fact to every caller.
BLOCKING_SINKS: Tuple[Tuple[str, FrozenSet[str], str], ...] = (
    ("shockwave_tpu/runtime/resilience.py",
     frozenset({"call_with_retry"}), "rpc"),
    ("shockwave_tpu/shockwave/milp.py",
     frozenset({"plan_schedule", "_solve"}), "solve"),
)

#: The same sinks BY NAME, for call sites the resolver cannot follow
#: (cross-module `from .milp import plan_schedule` — module functions
#: resolve per-file only). A call to one of these names that resolves
#: to nothing still records the blocking fact.
SINK_NAME_KINDS = {
    "call_with_retry": "rpc",
    "plan_schedule": "solve",
    "_solve": "solve",
}

#: Callees whose *blocking* facts are NOT propagated to callers (their
#: acquire facts still are). One entry today: `_emit_audit` events ride
#: DurabilityLayer.record's sync=False non-fsync path by design
#: (physical.py documents it at the call site), so attributing an
#: fsync to every audit emitter would be a false positive.
FACT_STOP_FUNCS = frozenset({"_emit_audit"})

#: Human-readable blurb per blocking kind, for the finding message.
KIND_BLURB = {
    "rpc": "a gRPC call",
    "fsync": "an fsync-backed durable write",
    "solve": "a MILP solve",
    "sleep": "time.sleep",
    "cv-wait": "a timeout-less Condition.wait on a DIFFERENT lock",
    "event-wait": "a timeout-less Event.wait",
    "wait": "a timeout-less .wait()",
    "subprocess": "a subprocess wait/communicate",
    "queue": "a blocking queue op",
    "socket": "a blocking socket op",
}


# ----------------------------------------------------------------------
# Per-function facts
# ----------------------------------------------------------------------

@dataclass(frozen=True)
class _Acquire:
    lock: str
    line: int
    held_before: FrozenSet[str]


@dataclass(frozen=True)
class _CallSite:
    targets: Tuple[FuncKey, ...]
    line: int
    held: FrozenSet[str]


@dataclass(frozen=True)
class _Prim:
    kind: str
    detail: str
    line: int
    held: FrozenSet[str]
    #: For cv-wait: the lock the condition wraps (waiting on your OWN
    #: cv releases it — only ADDITIONAL held locks are a finding).
    cv_lock: Optional[str] = None


@dataclass
class _Facts:
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)
    prims: List[_Prim] = field(default_factory=list)
    #: Locks this function acquires anywhere (for callee summaries).
    acquired_locks: Set[str] = field(default_factory=set)
    #: Locks held at function entry (@requires_lock contract).
    entry_held: FrozenSet[str] = frozenset()


@dataclass(frozen=True)
class _BFact:
    """One transitive blocking fact in a function's summary."""
    kind: str
    detail: str
    cv_lock: Optional[str]
    #: Locks the fact's path EXPLICITLY RELEASED before blocking (the
    #: release-sleep-reacquire idiom): a caller holding one of these
    #: is not actually holding it at the blocking site.
    shed: FrozenSet[str]
    #: Locks already reported as held over this fact deeper in the
    #: chain: a caller re-holding one adds nothing new.
    blamed: FrozenSet[str]


def _problem_locks(kind: str, cv_lock: Optional[str],
                   held: FrozenSet[str]) -> FrozenSet[str]:
    """The held locks that make a blocking fact a finding: waiting on
    your OWN condition releases its lock, so only other locks count."""
    if kind == "cv-wait" and cv_lock is not None:
        return held - {cv_lock}
    return held


# ----------------------------------------------------------------------
# Lock identity
# ----------------------------------------------------------------------

def _family(graph: CallGraph, cls: str) -> List[str]:
    out = list(graph.mro(cls))
    for sub in graph.subclasses(cls):
        if sub not in out:
            out.append(sub)
    return out


def _is_lock_attr(graph: CallGraph, cls: str, attr: str) -> bool:
    if attr in DEFAULT_LOCK_ATTRS:
        return True
    return any(graph.sync_fields.get((name, attr)) == "lock"
               for name in _family(graph, cls))


def _sync_kind(graph: CallGraph, cls: str, attr: str) -> Optional[str]:
    for name in graph.mro(cls):
        kind = graph.sync_fields.get((name, attr))
        if kind is not None:
            return kind
    return None


def lock_display(graph: CallGraph, cls: str, attr: str) -> str:
    """The sanitizer display name for `cls.attr`: the `maybe_wrap`
    label when one exists anywhere in the class family, else
    ``Class._attr`` anchored at the family member that declares the
    lock (so `Scheduler._cv` and `PhysicalScheduler._lock` are ONE
    graph node, matching the one runtime lock object)."""
    canon = graph.canonical_lock(cls, attr)
    fam = _family(graph, cls)
    for name in fam:
        label = graph.lock_names.get((name, canon))
        if label is not None:
            return label
    for name in fam:
        if graph.sync_fields.get((name, canon)) == "lock":
            return f"{name}.{canon}"
    return f"{cls}.{canon}"


def _module_locks(src: SourceFile) -> Dict[str, str]:
    """Top-level `VAR = threading.Lock()/RLock()/Condition()` in one
    module: var name -> display name (`file.py:VAR`)."""
    out: Dict[str, str] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        name = call_name(node.value)
        tail = name.rsplit(".", 1)[-1]
        if tail in ("Lock", "RLock", "Condition"):
            var = node.targets[0].id
            out[var] = f"{src.rel}:{var}"
    return out


# ----------------------------------------------------------------------
# Registries (family-wide, mirroring races._class_registry)
# ----------------------------------------------------------------------

def _harvest_registry(graph: CallGraph, cls: str, registry_name: str
                      ) -> Dict[str, Tuple[SourceFile, int]]:
    """Registry entries declared anywhere in the class family:
    entry -> (declaring source, declaration line)."""
    out: Dict[str, Tuple[SourceFile, int]] = {}
    for name in _family(graph, cls):
        info = graph.classes.get(name)
        if info is None:
            continue
        for stmt in info.node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == registry_name):
                declared = literal_str_set(stmt.value)
                for entry in declared or ():
                    out.setdefault(entry, (info.src, stmt.lineno))
    return out


# ----------------------------------------------------------------------
# The held-locks walk (one function)
# ----------------------------------------------------------------------

#: Local import-alias map for the two modules the taxonomy names
#: directly (lockflow must not import passes.py — circular).
_TAXONOMY_MODULES = {"time", "os"}


def _local_aliases(tree: ast.AST) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _TAXONOMY_MODULES:
                    aliases[alias.asname or alias.name] = alias.name
        elif (isinstance(node, ast.ImportFrom)
              and node.module in _TAXONOMY_MODULES):
            for alias in node.names:
                aliases[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}")
    return aliases


def _canonical_name(name: str, aliases: Dict[str, str]) -> str:
    head, _, rest = name.partition(".")
    base = aliases.get(head)
    if base is None:
        return name
    return f"{base}.{rest}" if rest else base


def _has_timeout(node: ast.Call) -> bool:
    """`.wait()`/`.get()` with any positional arg or a timeout=
    keyword is bounded — not in the blocking taxonomy."""
    if node.args:
        return True
    return any(kw.arg == "timeout" for kw in node.keywords)


def _queue_get_nonblocking(node: ast.Call) -> bool:
    """`q.get(False)` / `q.get(block=False)` / `q.get_nowait()`."""
    if node.args and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is False:
        return True
    return any(kw.arg == "block"
               and isinstance(kw.value, ast.Constant)
               and kw.value.value is False
               for kw in node.keywords)


class _FunctionScanner:
    """Folds the held-lock set through one function body, recording
    acquires, resolvable call sites, and blocking primitives."""

    def __init__(self, analysis: "LockflowAnalysis", fi: FuncInfo):
        self.a = analysis
        self.graph = analysis.graph
        self.fi = fi
        self.cls = fi.cls
        self.facts = _Facts()
        self.aliases = analysis.aliases_for(fi.src)
        self.mod_locks = analysis.module_locks_for(fi.src)
        self.local_types = self.graph._local_types(fi)

    # -- lock identity of an expression --------------------------------

    def lock_of(self, expr: ast.AST) -> Optional[str]:
        """Display name when `expr` denotes a lock this analysis
        tracks: `self._lock`, a module-level lock var, or
        `self.<obj>.<lockattr>` through attribute type inference."""
        graph, cls = self.graph, self.cls
        if is_self_attr(expr) and cls is not None \
                and _is_lock_attr(graph, cls, expr.attr):
            return lock_display(graph, cls, expr.attr)
        if isinstance(expr, ast.Name) and expr.id in self.mod_locks:
            return self.mod_locks[expr.id]
        if (isinstance(expr, ast.Attribute)
                and is_self_attr(expr.value) and cls is not None):
            for owner in sorted(graph.attr_classes(cls, expr.value.attr)):
                if _is_lock_attr(graph, owner, expr.attr):
                    return lock_display(graph, owner, expr.attr)
        return None

    # -- recording ------------------------------------------------------

    def record_acquire(self, lock: str, line: int,
                       held: FrozenSet[str]) -> None:
        if lock in held:
            return  # re-entrant: no new edge, no new hold
        self.facts.acquires.append(_Acquire(lock, line, held))
        self.facts.acquired_locks.add(lock)

    def record_prim(self, kind: str, detail: str, line: int,
                    held: FrozenSet[str],
                    cv_lock: Optional[str] = None) -> None:
        self.facts.prims.append(_Prim(kind, detail, line, held, cv_lock))

    # -- call classification --------------------------------------------

    def handle_call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        name = _canonical_name(call_name(node), self.aliases)
        if name == "time.sleep":
            self.record_prim("sleep", "time.sleep", node.lineno, held)
            return
        if name == "os.fsync":
            self.record_prim("fsync", "os.fsync", node.lineno, held)
            return
        fn = node.func
        if isinstance(fn, ast.Attribute):
            method = fn.attr
            recv = fn.value
            if method == "wait" and not _has_timeout(node):
                lock = self.lock_of(recv) if not isinstance(recv, ast.Name) \
                    else None
                kind = None
                if is_self_attr(recv) and self.cls is not None:
                    sk = _sync_kind(self.graph, self.cls, recv.attr)
                    if sk == "lock":
                        # locks have no .wait — a "lock"-kind field
                        # with .wait IS a Condition (incl. aliased _cv)
                        kind = ("cv-wait",
                                lock_display(self.graph, self.cls,
                                             recv.attr))
                    elif sk == "event":
                        kind = ("event-wait", None)
                if kind is None and isinstance(recv, ast.Name) \
                        and recv.id in self.mod_locks:
                    kind = ("cv-wait", self.mod_locks[recv.id])
                if kind is not None:
                    self.record_prim(kind[0], call_name(node), node.lineno,
                                     held, cv_lock=kind[1])
                elif not self.resolve_targets(node):
                    self.record_prim("wait", call_name(node), node.lineno,
                                     held)
                return
            if method == "communicate":
                self.record_prim("subprocess", call_name(node),
                                 node.lineno, held)
                return
            if method in ("get",) and is_self_attr(recv) \
                    and self.cls is not None \
                    and _sync_kind(self.graph, self.cls, recv.attr) == "queue" \
                    and not _queue_get_nonblocking(node):
                self.record_prim("queue", call_name(node), node.lineno, held)
                return
            if method in ("recv", "accept", "sendall"):
                if not self.resolve_targets(node):
                    self.record_prim("socket", call_name(node), node.lineno,
                                     held)
                    return
            if is_self_attr(recv, "_stub"):
                self.record_prim("rpc", f"self._stub.{method}",
                                 node.lineno, held)
                return
        targets = self.resolve_targets(node)
        if targets:
            self.facts.calls.append(
                _CallSite(tuple(sorted(targets, key=str)), node.lineno,
                          held))
        elif isinstance(fn, ast.Attribute) \
                and fn.attr in RPC_FALLBACK_METHODS:
            # Unresolvable receiver (a client out of a dict) with an
            # unmistakable RPC wrapper name.
            self.record_prim("rpc", call_name(node) or f"?.{fn.attr}",
                             node.lineno, held)
        else:
            tail = (call_name(node) or "").rsplit(".", 1)[-1]
            sink_kind = SINK_NAME_KINDS.get(tail)
            if sink_kind is not None:
                self.record_prim(sink_kind, call_name(node) or tail,
                                 node.lineno, held)

    def resolve_targets(self, node: ast.Call) -> List[FuncKey]:
        return self.graph.resolve_callable(node.func, self.fi,
                                           self.local_types)

    # -- the walk -------------------------------------------------------

    def scan_stmts(self, stmts: Iterable[ast.stmt],
                   held: FrozenSet[str]) -> FrozenSet[str]:
        for stmt in stmts:
            held = self.scan(stmt, held)
        return held

    def scan(self, node: ast.AST, held: FrozenSet[str]) -> FrozenSet[str]:
        graph = self.graph
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return held  # its own FuncKey; analyzed separately
        if isinstance(node, ast.Lambda):
            # Runs later, on whatever thread calls it — but its facts
            # belong to the enclosing function's summary (the notify
            # lambdas), with NO lexical locks.
            self.scan(node.body, frozenset())
            return held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                lock = self.lock_of(item.context_expr)
                if lock is not None:
                    if lock not in inner:
                        self.record_acquire(lock, item.context_expr.lineno,
                                            frozenset(inner))
                    inner.add(lock)
                else:
                    self.scan(item.context_expr, held)
            self.scan_stmts(node.body, frozenset(inner))
            return held
        # Explicit statement-level toggles: `self._cv.release()` ...
        # `self._cv.acquire()` (the release-sleep-reacquire idiom).
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("acquire", "release"):
                lock = self.lock_of(call.func.value)
                if lock is not None:
                    if call.func.attr == "release":
                        return frozenset(held - {lock})
                    if lock not in held:
                        self.record_acquire(lock, node.lineno, held)
                    return frozenset(held | {lock})
        if isinstance(node, ast.Try):
            held = self.scan_stmts(node.body, held)
            for handler in node.handlers:
                self.scan_stmts(handler.body, held)
            held = self.scan_stmts(node.orelse, held)
            held = self.scan_stmts(node.finalbody, held)
            return held
        if isinstance(node, (ast.If, ast.While)):
            self.scan(node.test, held)
            self.scan_stmts(node.body, held)
            self.scan_stmts(node.orelse, held)
            return held
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.scan(node.iter, held)
            self.scan_stmts(node.body, held)
            self.scan_stmts(node.orelse, held)
            return held
        if isinstance(node, ast.Call):
            self.handle_call(node, held)
            for child in ast.iter_child_nodes(node):
                self.scan(child, held)
            return held
        for child in ast.iter_child_nodes(node):
            self.scan(child, held)
        return held

    def run(self) -> _Facts:
        base: FrozenSet[str] = frozenset()
        if self.cls is not None and decorated_requires_lock(self.fi.node):
            base = frozenset({lock_display(self.graph, self.cls, "_lock")})
        self.facts.entry_held = base
        self.scan_stmts(self.fi.node.body, base)
        return self.facts


# ----------------------------------------------------------------------
# Whole-tree analysis
# ----------------------------------------------------------------------

def _bare(key: FuncKey) -> str:
    return key.name.rsplit(":", 1)[-1].rsplit(".", 1)[-1]


class LockflowAnalysis:
    """Per-index lockflow state: facts, summaries, the static
    lock-order graph, and root reachability. Memoized on the index
    (pure static data; both passes share one build)."""

    def __init__(self, index: RepoIndex):
        self.index = index
        self.graph: CallGraph = index.call_graph()
        self._aliases: Dict[str, Dict[str, str]] = {}
        self._mod_locks: Dict[str, Dict[str, str]] = {}
        self.facts: Dict[FuncKey, _Facts] = {}
        #: Transitive lock sets: every lock `key` (or a callee) acquires.
        self.acq_summary: Dict[FuncKey, FrozenSet[str]] = {}
        #: Transitive blocking facts (shed/blame-annotated _BFacts).
        self.blocks_summary: Dict[FuncKey, FrozenSet[_BFact]] = {}
        #: Static order graph: lock -> {lock acquired while held}, each
        #: edge annotated with its first recording site.
        self.edges: Dict[Tuple[str, str], Tuple[SourceFile, int, FuncKey]] \
            = {}
        self._build()

    # -- per-file caches ------------------------------------------------

    def aliases_for(self, src: SourceFile) -> Dict[str, str]:
        got = self._aliases.get(src.rel)
        if got is None:
            got = self._aliases[src.rel] = _local_aliases(src.tree)
        return got

    def module_locks_for(self, src: SourceFile) -> Dict[str, str]:
        got = self._mod_locks.get(src.rel)
        if got is None:
            got = self._mod_locks[src.rel] = _module_locks(src)
        return got

    # -- construction ---------------------------------------------------

    def _build(self) -> None:
        graph = self.graph
        sink_kinds: Dict[FuncKey, str] = {}
        for rel, names, kind in BLOCKING_SINKS:
            for key, fi in graph.funcs.items():
                if fi.src.rel == rel and _bare(key) in names:
                    sink_kinds[key] = kind
        for key in sorted(graph.funcs, key=str):
            fi = graph.funcs[key]
            self.facts[key] = _FunctionScanner(self, fi).run()

        # Bottom-up fixpoint over acquire + blocking summaries.
        acq: Dict[FuncKey, Set[str]] = {
            key: set(f.acquired_locks) for key, f in self.facts.items()}
        blocks: Dict[FuncKey, Set[_BFact]] = {}
        for key, f in self.facts.items():
            own: Set[_BFact] = set()
            for p in f.prims:
                problem = _problem_locks(p.kind, p.cv_lock, p.held)
                own.add(_BFact(p.kind, p.detail, p.cv_lock,
                               frozenset(f.entry_held - p.held),
                               frozenset(problem)))
            if key in sink_kinds:
                own.add(_BFact(sink_kinds[key], str(key), None,
                               frozenset(), frozenset()))
            blocks[key] = own
        changed = True
        while changed:
            changed = False
            for key, f in self.facts.items():
                for site in f.calls:
                    for target in site.targets:
                        tacq = acq.get(target)
                        if tacq and not tacq <= acq[key]:
                            acq[key] |= tacq
                            changed = True
                        if _bare(target) in FACT_STOP_FUNCS:
                            continue
                        for fact in list(blocks.get(target, ())):
                            eff = site.held - fact.shed
                            problem = _problem_locks(fact.kind,
                                                     fact.cv_lock, eff)
                            base = fact.detail.split(" via ")[0]
                            nf = _BFact(
                                fact.kind, f"{base} via {target}",
                                fact.cv_lock,
                                frozenset(fact.shed
                                          | (f.entry_held - site.held)),
                                frozenset(fact.blamed | problem))
                            if nf not in blocks[key]:
                                blocks[key].add(nf)
                                changed = True
        self.acq_summary = {k: frozenset(v) for k, v in acq.items()}
        self.blocks_summary = {k: frozenset(v) for k, v in blocks.items()}

        # The static lock-order graph: direct acquires-while-holding
        # plus calls-under-lock into functions that acquire more.
        for key in sorted(self.facts, key=str):
            f = self.facts[key]
            fi = self.graph.funcs[key]
            for acq_fact in f.acquires:
                for outer in sorted(acq_fact.held_before):
                    self._add_edge(outer, acq_fact.lock, fi.src,
                                   acq_fact.line, key)
            for site in f.calls:
                if not site.held:
                    continue
                for target in site.targets:
                    for inner in sorted(self.acq_summary.get(target, ())):
                        for outer in sorted(site.held):
                            self._add_edge(outer, inner, fi.src,
                                           site.line, key)

        # Root reachability (the races.py pattern, including <main>).
        roots, _ = discover_thread_roots(self.index)
        root_reach: Dict[Tuple[str, str], Set[FuncKey]] = {}
        for root in roots:
            rid = (str(root.key), root.kind)
            if rid not in root_reach:
                root_reach[rid] = graph.reachable(root.key)
        self.func_roots: Dict[FuncKey, Set[Tuple[str, str]]] = {}
        for rid, reach in root_reach.items():
            for key in reach:
                self.func_roots.setdefault(key, set()).add(rid)
        touched = {key.cls for key in self.func_roots if key.cls}
        families: Set[str] = set()
        for cls in touched:
            families.update(graph.mro(cls))
            families.update(graph.subclasses(cls))
        MAIN = ("<main>", "main")
        for cls in sorted(families):
            info = graph.classes[cls]
            for mname, fi in info.methods.items():
                if mname.startswith("_") or "." in mname:
                    continue
                for key in graph.reachable(fi.key):
                    self.func_roots.setdefault(key, set()).add(MAIN)

    def _add_edge(self, outer: str, inner: str, src: SourceFile,
                  line: int, key: FuncKey) -> None:
        if outer == inner:
            return
        self.edges.setdefault((outer, inner), (src, line, key))

    # -- queries ---------------------------------------------------------

    def adjacency(self) -> Dict[str, Set[str]]:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        return adj

    def edge_roots(self, a: str, b: str) -> Set[Tuple[str, str]]:
        site = self.edges.get((a, b))
        if site is None:
            return set()
        return set(self.func_roots.get(site[2], set()))


_ANALYSIS_ATTR = "_lockflow_analysis"


def lockflow_analysis(index: RepoIndex) -> LockflowAnalysis:
    got = getattr(index, _ANALYSIS_ATTR, None)
    if got is None:
        got = LockflowAnalysis(index)
        setattr(index, _ANALYSIS_ATTR, got)
    return got


def static_lock_order_graph(index: RepoIndex) -> dict:
    """The static order graph in the sanitizer's export shape:
    {"nodes": [...], "edges": ["A->B", ...]} — the containment gate
    compares the runtime export against exactly this."""
    analysis = lockflow_analysis(index)
    nodes: Set[str] = set()
    edges: Set[str] = set()
    for (a, b) in analysis.edges:
        nodes.add(a)
        nodes.add(b)
        edges.add(f"{a}->{b}")
    return {"nodes": sorted(nodes), "edges": sorted(edges)}


# ----------------------------------------------------------------------
# Pass: deadlock
# ----------------------------------------------------------------------

def check_deadlock(index: RepoIndex) -> List[Finding]:
    """Static lock-order acyclicity: every acquire-while-holding edge
    (direct or through a call chain) joins one order graph; a cycle
    whose edges are reachable from >= 2 distinct thread roots (or one
    self-concurrent handler-pool root) is a deadlock some interleaving
    can hit. `_LOCK_ORDER_JUSTIFIED = frozenset({"A->B"})` in a class
    body sanctions an edge; stale entries are findings."""
    analysis = lockflow_analysis(index)
    graph = analysis.graph
    findings: List[Finding] = []

    # Harvest every _LOCK_ORDER_JUSTIFIED across the tree (anchored at
    # the declaring class; edges are global names so a single registry
    # covers the process-wide graph).
    justified: Dict[str, Tuple[SourceFile, int]] = {}
    for cls in sorted(graph.classes):
        for entry, where in _harvest_registry(
                graph, cls, ORDER_REGISTRY_NAME).items():
            justified.setdefault(entry, where)
    used: Set[str] = set()

    adj = analysis.adjacency()
    reported: Set[FrozenSet[str]] = set()
    for (a, b) in sorted(analysis.edges):
        # Shortest path b -> a closes the cycle through edge (a, b).
        path = _shortest_path(adj, b, a)
        if path is None:
            continue
        cycle_nodes = frozenset(path)
        if cycle_nodes in reported:
            continue
        reported.add(cycle_nodes)
        # The cycle: a -> b, then the path b .. a edge by edge.
        cycle_edges = [(a, b)] + list(zip(path, path[1:]))
        roots: Set[Tuple[str, str]] = set()
        for (x, y) in cycle_edges:
            roots |= analysis.edge_roots(x, y)
        concurrent = (len({r for r in roots}) > 1
                      or any(kind in SELF_CONCURRENT_KINDS
                             for _, kind in roots))
        edge_strs = [f"{x}->{y}" for (x, y) in cycle_edges]
        hits = [e for e in edge_strs if e in justified]
        if hits:
            used.update(hits)
            continue
        if not concurrent:
            continue
        src, line, key = analysis.edges[(a, b)]
        root_names = sorted({entry for entry, _ in roots})
        f = finding(
            src, line, PASS_DEADLOCK,
            f"lock-order cycle {' / '.join(edge_strs)} (closed here in "
            f"{key}); reachable from {len(roots)} thread root(s) "
            f"({', '.join(root_names[:3])}"
            f"{', ...' if len(root_names) > 3 else ''}) — an unlucky "
            "interleaving deadlocks. Restructure so one order holds "
            "everywhere, or sanction the edge in "
            f"{ORDER_REGISTRY_NAME} with a written justification")
        if f is not None:
            findings.append(f)

    for entry in sorted(justified):
        a, _, b = entry.partition("->")
        src, line = justified[entry]
        if (a, b) not in analysis.edges:
            f = finding(src, line, PASS_DEADLOCK,
                        f"stale {ORDER_REGISTRY_NAME} entry '{entry}': "
                        "the static graph has no such edge — delete it")
            if f is not None:
                findings.append(f)
    return findings


def _shortest_path(adj: Dict[str, Set[str]], src: str, dst: str
                   ) -> Optional[List[str]]:
    """BFS path src..dst (inclusive), deterministic (sorted
    neighbors); None when unreachable."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    frontier = [src]
    seen = {src}
    while frontier:
        nxt: List[str] = []
        for node in frontier:
            for neigh in sorted(adj.get(node, ())):
                if neigh in seen:
                    continue
                seen.add(neigh)
                prev[neigh] = node
                if neigh == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                nxt.append(neigh)
        frontier = nxt
    return None


# ----------------------------------------------------------------------
# Pass: hold-discipline
# ----------------------------------------------------------------------

def check_hold_discipline(index: RepoIndex) -> List[Finding]:
    """No blocking operation under a lock: gRPC calls, fsync, MILP
    solves, time.sleep, timeout-less Condition/Event waits, subprocess
    wait/communicate, blocking queue/socket ops — inline OR through any
    resolvable call chain — are findings when a lock is held and the
    code is reachable from a thread root. One finding per
    (function, kind), matching `_HOLD_DISCIPLINE_JUSTIFIED` entries
    "method:kind" (or "method:*"); stale entries are findings."""
    analysis = lockflow_analysis(index)
    graph = analysis.graph
    findings: List[Finding] = []

    # (function, kind) -> [(line, detail, sorted-held-tuple)]
    sites: Dict[Tuple[FuncKey, str], List[Tuple[int, str, tuple]]] = {}

    def add_site(key: FuncKey, kind: str, line: int, detail: str,
                 held: Iterable[str]) -> None:
        sites.setdefault((key, kind), []).append(
            (line, detail, tuple(sorted(held))))

    for key in sorted(analysis.facts, key=str):
        if not analysis.func_roots.get(key):
            continue  # unreached: dead code / construction helpers
        f = analysis.facts[key]
        for prim in f.prims:
            problem = _problem_locks(prim.kind, prim.cv_lock, prim.held)
            if problem:
                add_site(key, prim.kind, prim.line, prim.detail, problem)
        for site in f.calls:
            if not site.held:
                continue
            for target in site.targets:
                if _bare(target) in FACT_STOP_FUNCS:
                    continue
                for fact in sorted(
                        analysis.blocks_summary.get(target, ()),
                        key=lambda b: (b.kind, b.detail,
                                       tuple(sorted(b.shed)),
                                       tuple(sorted(b.blamed)))):
                    eff = site.held - fact.shed
                    problem = _problem_locks(fact.kind, fact.cv_lock, eff)
                    new = problem - fact.blamed
                    if not new:
                        continue  # already reported deeper, or shed
                    base = fact.detail.split(" via ")[0]
                    add_site(key, fact.kind, site.line,
                             f"{base} via {target}", new)

    # Registry: harvested per declaring-class family, matched by the
    # finding function's class family.
    used: Set[Tuple[str, str]] = set()   # (cls-anchor, entry)
    registry_memo: Dict[str, Dict[str, Tuple[SourceFile, int]]] = {}

    def registry_for(cls: str) -> Dict[str, Tuple[SourceFile, int]]:
        got = registry_memo.get(cls)
        if got is None:
            got = registry_memo[cls] = _harvest_registry(
                graph, cls, HOLD_REGISTRY_NAME)
        return got

    for (key, kind) in sorted(sites, key=lambda t: (str(t[0]), t[1])):
        entries = sites[(key, kind)]
        entries.sort()
        line, detail, held = entries[0]
        fi = graph.funcs[key]
        method = _bare(key)
        if key.cls is not None:
            reg = registry_for(key.cls)
            hit = None
            for candidate in (f"{method}:{kind}", f"{method}:*"):
                if candidate in reg:
                    hit = candidate
                    break
            if hit is not None:
                used.add((key.cls, hit))
                continue
        f = finding(
            fi.src, line, PASS_HOLD,
            f"{KIND_BLURB.get(kind, kind)} ({detail}) reachable with "
            f"lock(s) {', '.join(sorted(held))} held in {key} "
            f"({len(entries)} site(s)): move the blocking work outside "
            "the lock, or sanction it with "
            f"{HOLD_REGISTRY_NAME} entry '{method}:{kind}' and a "
            "written justification")
        if f is not None:
            findings.append(f)

    # Stale registry entries: walk every declaration once.
    seen_decl: Set[Tuple[str, int, str]] = set()
    for cls in sorted(graph.classes):
        reg = _harvest_registry(graph, cls, HOLD_REGISTRY_NAME)
        for entry, (src, line) in reg.items():
            decl = (src.rel, line, entry)
            if decl in seen_decl:
                continue
            seen_decl.add(decl)
            if any(entry == e and
                   (cls == c or cls in _family(graph, c)
                    or c in _family(graph, cls))
                   for (c, e) in used):
                continue
            method, _, kind = entry.partition(":")
            matched = any(
                _bare(key) == method and (kind == "*" or k == kind)
                and key.cls is not None
                and (key.cls in _family(graph, cls)
                     or cls in _family(graph, key.cls))
                for (key, k) in sites)
            if matched:
                continue  # suppressed-by-registry but keyed elsewhere
            f = finding(src, line, PASS_HOLD,
                        f"stale {HOLD_REGISTRY_NAME} entry '{entry}': "
                        "no such blocking-under-lock site remains — "
                        "delete it")
            if f is not None:
                findings.append(f)
    return findings
