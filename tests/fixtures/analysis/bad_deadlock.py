"""Seeded violation for the deadlock pass: two spawned threads acquire
the same two locks in opposite orders — a lock-order cycle reachable
from two distinct thread roots, so an unlucky interleaving deadlocks.
The finding anchors at the first (alphabetically) edge's acquire site:
taking _lock_b while holding _lock_a."""
import threading


class Clash:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        threading.Thread(target=self._loop_ab, daemon=True).start()
        threading.Thread(target=self._loop_ba, daemon=True).start()

    def _loop_ab(self):
        with self._lock_a:
            with self._lock_b:  # SEEDED
                pass

    def _loop_ba(self):
        with self._lock_b:
            with self._lock_a:
                pass
