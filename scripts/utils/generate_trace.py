#!/usr/bin/env python3
"""Generate a synthetic job trace (TSV, 12 fields per line).

Equivalent of the reference's scripts/utils/generate_trace.py, driving
shockwave_tpu.core.generator. Example:

    python scripts/utils/generate_trace.py --num_jobs 120 --lam 0.2 \
        --throughputs_file data/tacc_throughputs.json \
        --scale_factor_mix 0.6 0.3 0.09 0.01 --mode_mix 0 0.5 0.5 \
        --output_file /tmp/trace.trace
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from shockwave_tpu.core.generator import generate_trace
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.trace import job_to_trace_line


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", type=int, required=True)
    p.add_argument("-l", "--lam", type=float, default=0.0,
                   help="Mean Poisson interarrival time in seconds")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--throughputs_file", type=str, required=True)
    p.add_argument("-a", "--min_duration", type=float, default=0.2,
                   help="Minimum job duration in hours")
    p.add_argument("-b", "--max_duration", type=float, default=5.0,
                   help="Maximum job duration in hours")
    p.add_argument("-n", "--num_durations", type=int, default=100)
    p.add_argument("--duration_logspace", action="store_true", default=True)
    p.add_argument("--duration_linspace", dest="duration_logspace",
                   action="store_false")
    p.add_argument("--generate_multi_gpu_jobs", action="store_true",
                   default=True)
    p.add_argument("--generate_dynamic_jobs", action="store_true",
                   default=True)
    p.add_argument("--scale_factor_mix", type=float, nargs=4, default=None,
                   help="P(scale factor = 1, 2, 4, 8)")
    p.add_argument("--mode_mix", type=float, nargs=3,
                   default=(0.34, 0.33, 0.33),
                   help="P(static, accordion, gns)")
    p.add_argument("--output_file", type=str, required=True)
    args = p.parse_args()

    throughputs = read_throughputs(args.throughputs_file)
    jobs, arrivals = generate_trace(
        num_jobs=args.num_jobs,
        throughputs=throughputs,
        lam=args.lam,
        seed=args.seed,
        generate_multi_gpu_jobs=args.generate_multi_gpu_jobs,
        generate_dynamic_jobs=args.generate_dynamic_jobs,
        scale_factor_mix=args.scale_factor_mix,
        mode_mix=args.mode_mix,
        min_duration_hours=args.min_duration,
        max_duration_hours=args.max_duration,
        num_durations=args.num_durations,
        logspace=args.duration_logspace,
    )
    with open(args.output_file, "w") as f:
        for job, arrival in zip(jobs, arrivals):
            f.write(job_to_trace_line(job, arrival) + "\n")
    by_mode, by_sf = {}, {}
    for job in jobs:
        by_mode[job.mode] = by_mode.get(job.mode, 0) + 1
        by_sf[job.scale_factor] = by_sf.get(job.scale_factor, 0) + 1
    print(f"Wrote {len(jobs)} jobs to {args.output_file}")
    print(f"  modes: {sorted(by_mode.items())}")
    print(f"  scale factors: {sorted(by_sf.items())}")


if __name__ == "__main__":
    main()
