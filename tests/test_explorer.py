"""Seeded interleaving explorer (analysis/explorer.py).

Two halves:

- Determinism contract: a thread's perturbation-decision trace is a
  pure function of (seed, thread name, per-thread event counter), so
  the same seed reproduces the same interleaving schedule and a
  different seed genuinely explores a different one.
- The 20-seed pipelined-solve smoke: one real PhysicalScheduler
  (shockwave policy, background solve thread, what-if plane) plus a
  live HA lease controller, driven through the planner-kick ->
  background-solve -> commit cycle and a what-if capture/rollout under
  20 different exploration seeds — with the sanitizer's lock-order,
  ownership and hold-time checks asserted clean on every schedule.
"""
import os
import threading
import time

import pytest

from shockwave_tpu.analysis import explorer, sanitizer

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DATA = os.path.join(REPO, "data")


@pytest.fixture(autouse=True)
def _clean_explorer():
    explorer.uninstall()
    sanitizer.monitor().reset()
    yield
    explorer.uninstall()
    sanitizer.monitor().reset()


def _locked_workload(n_ops=25):
    """Two named threads running a fixed lock-op script against two
    SanitizedLocks; returns the explorer's per-thread traces."""
    a = sanitizer.SanitizedLock(threading.RLock(), "explorertest.A")
    b = sanitizer.SanitizedLock(threading.RLock(), "explorertest.B")

    def body(first, second):
        for _ in range(n_ops):
            with first:
                with second:
                    pass

    t1 = threading.Thread(target=body, args=(a, b), name="exp-t1")
    t2 = threading.Thread(target=body, args=(a, b), name="exp-t2")
    t1.start(), t2.start()
    t1.join(), t2.join()
    # Only this workload's threads: a sanitize-enabled scheduler from an
    # earlier test may have a background thread in its (bounded, <=1 s)
    # post-shutdown linger whose lock ops would otherwise pollute the
    # trace — decisions are per-thread pure (asserted below), so the
    # filter cannot mask a determinism break.
    return {name: events
            for name, events in explorer.active().trace().items()
            if name.startswith("exp-")}


class TestExplorerDeterminism:
    def test_same_seed_reproduces_the_same_interleaving_schedule(self):
        explorer.install(1234)
        first = _locked_workload()
        explorer.install(1234)
        second = _locked_workload()
        assert first == second
        # The schedule is non-trivial: both threads decided, and at
        # least one perturbation actually fired.
        assert set(first) == {"exp-t1", "exp-t2"}
        actions = [a for trace in first.values() for (_, _, _, a) in trace]
        assert any(a != explorer.ACTION_NONE for a in actions)

    def test_different_seed_explores_a_different_schedule(self):
        explorer.install(1234)
        first = _locked_workload()
        explorer.install(4321)
        second = _locked_workload()
        assert first != second

    def test_decisions_are_independent_of_other_threads(self):
        """A thread's decision sequence must not depend on global event
        order: computing decisions for one thread alone matches that
        thread's slice of the two-thread run."""
        explorer.install(77)
        two_thread = _locked_workload()
        h = explorer._fnv64(b"exp-t1")
        # Recompute directly from the pure mix function.
        recomputed = []
        for counter, point, lock, action in two_thread["exp-t1"]:
            hval = explorer._mix(77, h, counter)
            if hval < explorer._YIELD_AT:
                expect = explorer.ACTION_NONE
            elif hval < explorer._SLEEP_AT:
                expect = explorer.ACTION_YIELD
            else:
                expect = explorer.ACTION_SLEEP
            recomputed.append(expect)
            assert action == expect, (counter, point, lock)
        assert recomputed  # the thread actually recorded events

    def test_env_installation_and_garbage_value(self, monkeypatch):
        monkeypatch.setenv(explorer.ENV_VAR, "99")
        explorer._env_checked = False
        got = explorer.install_from_env()
        assert got is not None and got.seed == 99
        monkeypatch.setenv(explorer.ENV_VAR, "not-a-seed")
        explorer._env_checked = False
        explorer._active = None
        assert explorer.install_from_env() is None  # logged, stays off

    def test_inert_when_not_installed(self):
        assert explorer.active() is None
        lock = sanitizer.SanitizedLock(threading.RLock(), "explorertest.C")
        with lock:
            pass  # on_lock_event with no explorer: no-op, no crash
        assert sanitizer.monitor().report()["violations"] == []


class TestRuntimeStaticContainment:
    """The deadlock pass's soundness audit: every lock-order edge the
    sanitizer OBSERVES must be predicted by the static lockflow graph
    (runtime ⊆ static). CI enforces the same containment over the
    20-seed smoke's exported graph via ``--assert-contains``."""

    def _static_graph(self):
        from shockwave_tpu.analysis import __main__ as main_mod
        from shockwave_tpu.analysis.core import cached_index
        from shockwave_tpu.analysis.lockflow import static_lock_order_graph
        index = cached_index(
            REPO, include_dirs=main_mod.DEFAULT_INCLUDE_DIRS,
            exclude_globs=main_mod.DEFAULT_EXCLUDE_GLOBS)
        return static_lock_order_graph(index)

    @staticmethod
    def _real_edges(graph):
        """Drop synthetic test-lock edges (sanitytest.*/explorertest.*
        names the sanitizer unit tests create in this same process)."""
        return [e for e in graph["edges"]
                if "test." not in e]

    def test_observed_edges_contained_in_static_graph(self):
        """Drive a real-named nesting, then check every real-named
        edge the process has EVER observed (the cumulative graph
        survives reset) appears in the static graph."""
        static = self._static_graph()
        assert static["edges"], "static graph must not be vacuous"
        # A real scheduler-order nesting so the check can never pass
        # on an empty runtime graph.
        a = sanitizer.SanitizedLock(threading.RLock(),
                                    "PhysicalScheduler._lock")
        b = sanitizer.SanitizedLock(threading.RLock(), "Tracer._lock")
        with a:
            with b:
                pass
        runtime = sanitizer.monitor().cumulative_graph()
        real = self._real_edges(runtime)
        assert "PhysicalScheduler._lock->Tracer._lock" in real
        missing = sorted(set(real) - set(static["edges"]))
        assert missing == [], (
            f"runtime lock-order edges the static analyzer missed: "
            f"{missing}")

    def test_assert_contains_cli_gate(self, tmp_path):
        """The CI gate end-to-end: a contained graph file exits 0, an
        inverted edge exits 1 naming the uncovered edge."""
        import json
        import subprocess
        import sys

        a = sanitizer.SanitizedLock(threading.RLock(),
                                    "PhysicalScheduler._lock")
        b = sanitizer.SanitizedLock(threading.RLock(), "Tracer._lock")
        with a:
            with b:
                pass
        runtime = sanitizer.monitor().cumulative_graph()
        good = tmp_path / "runtime.json"
        good.write_text(json.dumps(
            {"nodes": runtime["nodes"],
             "edges": self._real_edges(runtime)}))
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--assert-contains", str(good)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "containment OK" in out.stdout

        bad = tmp_path / "inverted.json"
        bad.write_text(json.dumps(
            {"nodes": [], "edges":
             ["Tracer._lock->PhysicalScheduler._lock"]}))
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--assert-contains", str(bad)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1
        assert "Tracer._lock->PhysicalScheduler._lock" in out.stderr

    def test_graph_out_env_exports_at_exit(self, tmp_path):
        """SWTPU_SANITIZE_GRAPH_OUT: a subprocess that nests two
        instrumented locks dumps the cumulative graph at interpreter
        exit, surviving an intervening reset()."""
        import json
        import subprocess
        import sys

        out_path = tmp_path / "graph.json"
        env = dict(os.environ,
                   SWTPU_SANITIZE="1",
                   SWTPU_SANITIZE_GRAPH_OUT=str(out_path))
        script = (
            "import threading\n"
            "from shockwave_tpu.analysis import sanitizer\n"
            "a = sanitizer.maybe_wrap(threading.RLock(), 'ga')\n"
            "b = sanitizer.maybe_wrap(threading.RLock(), 'gb')\n"
            "with a:\n"
            "    with b:\n"
            "        pass\n"
            "sanitizer.monitor().reset()\n")
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, cwd=REPO,
                             env=env)
        assert out.returncode == 0, out.stdout + out.stderr
        graph = json.loads(out_path.read_text())
        assert graph["edges"] == ["ga->gb"]
        assert graph["nodes"] == ["ga", "gb"]


def _shockwave_scheduler(port):
    from shockwave_tpu.core.job import Job
    from shockwave_tpu.core.oracle import read_throughputs
    from shockwave_tpu.core.profiles import build_profiles
    from shockwave_tpu.sched.physical import PhysicalScheduler
    from shockwave_tpu.sched.scheduler import SchedulerConfig
    from shockwave_tpu.solver import get_policy

    jobs = [Job(None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=steps, duration=10000)
            for steps in (150, 800)]
    throughputs = read_throughputs(
        os.path.join(DATA, "tacc_throughputs.json"))
    sched = PhysicalScheduler(
        get_policy("shockwave", seed=0),
        throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
        profiles=build_profiles(jobs, throughputs),
        config=SchedulerConfig(
            time_per_iteration=2.0, max_rounds=8,
            shockwave={"num_gpus": 2},
            whatif={"forecast_interval_rounds": 1,
                    "forecast_samples": 1,
                    "forecast_horizon_rounds": 2}),
        expected_num_workers=2, port=port)
    for job in jobs:
        sched.add_job(job)
    return sched


@pytest.mark.runtime
@pytest.mark.timeout(300)
class TestExplorerSmoke:
    def test_twenty_seed_pipelined_solve_smoke(self, tmp_path):
        """>=20 exploration seeds over the REAL cross-thread critical
        sections: planner kick (round loop, under the scheduler cv) ->
        background MILP solve (_planner_solve_loop thread) -> commit;
        what-if capture under the lock -> background rollout
        (_whatif_loop thread) -> status read through the health path;
        HA lease renewal/deadman ticking throughout. The sanitizer's
        checks must hold on EVERY seeded schedule."""
        import socket

        from shockwave_tpu.sched.ha import HAConfig, HAController

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        sched = _shockwave_scheduler(port)
        ha = HAController(str(tmp_path), HAConfig(lease_interval_s=0.02),
                          port=port)
        ha.start()
        plane = sched._whatif
        seeds_run = 0
        try:
            for seed in range(20):
                explorer.install(seed)
                sanitizer.monitor().reset()

                # -- planner-commit critical sections ------------------
                with sched._cv:
                    sched._shockwave_planner.request_resolve()
                    sched._maybe_kick_planner_solve()
                deadline = time.time() + 30
                while time.time() < deadline:
                    with sched._cv:
                        if not sched._planner_busy:
                            break
                    time.sleep(0.005)
                with sched._cv:
                    assert not sched._planner_busy, "solve thread stuck"
                    sched._commit_planner_result()
                    assert sched._shockwave_planner.schedules

                # -- whatif capture (locked) + background rollout ------
                with sched._lock:
                    blob = plane._capture()
                rollouts_before = plane.status()["rollouts"]
                sched._whatif_work.put(("forecast", seed, blob))
                while time.time() < deadline:
                    if plane.status()["rollouts"] > rollouts_before:
                        break
                    time.sleep(0.005)
                assert plane.status()["rollouts"] > rollouts_before, \
                    "background rollout never completed"

                # -- health-path reads (exporter-thread shape) ---------
                payload = sched.obs_health()
                assert payload.get("whatif", {}).get("forks", 0) >= 1 \
                    or payload.get("status") == "busy"

                stats = explorer.active().stats()
                assert stats["events"] > 0
                report = sanitizer.monitor().report()
                assert report["violations"] == [], (
                    f"seed {seed}: {report['violations']}")
                seeds_run += 1
        finally:
            explorer.uninstall()
            ha.stop()
            sched._done_event.set()
            sched._server.stop(grace=0)
        assert seeds_run >= 20
        # Across the whole sweep at least some seeds genuinely
        # perturbed the schedule (the last explorer's stats prove the
        # hook fired; perturbation odds per event are ~55%).
        sanitizer.monitor().reset()
