"""Shared model/dataset constants for the workload families.

The five active model families match the reference's job table
(reference: scheduler/job_table.py:110-130); dataset sizes match
scheduler/scheduler.py:73-81 so that step<->epoch conversions agree
with the reference simulator exactly.
"""
import math

# Samples per epoch for each dataset.
DATASET_SIZES = {
    "CIFAR-10": 50000,
    "ImageNet": 100000,
    "Multi30k": 10000,
    "Wikitext-2": 59675,
    "ML-20M": 117907,
    "Pong": 4,
    "monet2photo": 6287,
}

# Model family -> dataset it trains on.
MODEL_DATASET = {
    "ResNet-18": "CIFAR-10",
    "ResNet-50": "ImageNet",
    "Transformer": "Multi30k",
    "LM": "Wikitext-2",
    "Recommendation": "ML-20M",
    "A3C": "Pong",
    "CycleGAN": "monet2photo",
}

# Largest batch size with a profiled throughput entry; adaptation never
# scales past these (reference: scheduler/scheduler.py:4756-4761).
MAX_BS = {
    "LM": 80,
    "ResNet-18": 256,
    "ResNet-50": 128,
    "Transformer": 128,
    "Recommendation": 8192,
    "A3C": 4,
    "CycleGAN": 1,
}

# Families whose job_type carries no "(batch size N)" suffix; the value is
# the implicit batch size their profiles are keyed under.
DEFAULT_BS = {
    "A3C": 4,
    "CycleGAN": 1,
}


def oracle_job_type(model: str, batch_size: int) -> str:
    """The job_type string used as the throughput-oracle key."""
    if model in DEFAULT_BS:
        return model
    return f"{model} (batch size {batch_size})"

def dataset_size(model: str) -> int:
    return DATASET_SIZES[MODEL_DATASET[model]]


def steps_per_epoch(model: str, batch_size: int) -> int:
    return math.ceil(dataset_size(model) / batch_size)


def num_epochs_for(model: str, batch_size: int, num_steps: int) -> int:
    """Total epochs implied by a step budget at a fixed batch size."""
    return math.ceil(num_steps / steps_per_epoch(model, batch_size))
