#!/usr/bin/env python3
"""Emit the gang (sf>1) fidelity trace from the calibrated CPU oracle.

Mirrors the reference's multi-GPU trace mix (scheduler/utils.py:96-106
scales jobs across 1/2/4/8 GPUs): two sf=2 gangs plus four sf=1 singles
on a 2-chip worker force gang dispatch, consensus leases, the exit
barrier, and gang preemption/redispatch cycles under max_min_fairness.

Step budgets are sized from the measured deployed rates (steps =
rate * target_runtime) so the trace's `duration` column matches each
job's isolated runtime — the force-complete deadline (1.5x duration)
then never fakes a completion.

Usage:
    python reproduce/fidelity/make_gang_trace.py \
        [--oracle reproduce/fidelity/cpu_throughputs.json] \
        [--output reproduce/fidelity/fidelity_cpu_gang.trace]
"""
import argparse
import json
import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, REPO)

from shockwave_tpu.core.job import Job  # noqa: E402
from shockwave_tpu.core.job_table import JOB_TABLE  # noqa: E402
from shockwave_tpu.core.trace import job_to_trace_line  # noqa: E402

# (family, scale_factor, target isolated runtime s, arrival s)
MIX = [
    ("ResNet-18 (batch size 32)", 2, 450, 0),
    ("LM (batch size 20)", 1, 420, 20),
    ("Recommendation (batch size 512)", 1, 420, 45),
    ("LM (batch size 20)", 2, 400, 80),
    ("Recommendation (batch size 512)", 1, 360, 120),
    ("ResNet-18 (batch size 32)", 1, 400, 150),
]


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--oracle",
                   default=os.path.join(os.path.dirname(__file__),
                                        "cpu_throughputs.json"))
    p.add_argument("--worker_type", default="cpu")
    p.add_argument("--output",
                   default=os.path.join(os.path.dirname(__file__),
                                        "fidelity_cpu_gang.trace"))
    args = p.parse_args()

    with open(args.oracle) as f:
        rows = json.load(f)[args.worker_type]
    by_model = {t.model: t for t in JOB_TABLE}

    lines = []
    for family, sf, runtime, arrival in MIX:
        key = f"('{family}', {sf})"
        if key not in rows:
            raise SystemExit(
                f"{key} missing from {args.oracle} — run "
                f"scripts/profiling/measure_deployed.py --scale_factor {sf} "
                f"first")
        rate = rows[key]["null"]
        steps = max(int(rate * runtime), sf)
        t = by_model[family]
        job = Job(None, family, t.command, t.working_directory,
                  t.num_steps_arg, needs_data_dir=True, total_steps=steps,
                  duration=runtime, scale_factor=sf, mode="static")
        lines.append(job_to_trace_line(job, float(arrival)))

    with open(args.output, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.output} ({len(lines)} jobs, "
          f"{sum(1 for _, sf, _, _ in MIX if sf > 1)} gangs)")


if __name__ == "__main__":
    main()
