"""Diurnal / bursty request-load model for serving services.

Request arrivals are Poisson with a time-varying rate: a sinusoidal
day-curve between `base_rps` (trough) and `peak_rps` (peak) modulated by
multiplicative traffic spikes — either explicit (start, duration,
multiplier) triples from the trace, or drawn deterministically from a
seed (`seeded_spikes`). Everything here is a pure function of (spec,
time): the simulator, the autoscaler, and the analytic latency model
all read the same curve, so SLO attainment is evaluated
deterministically (bit-identical replays).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class Spike:
    """One multiplicative traffic burst, offsets relative to service
    start."""
    start: float
    duration: float
    multiplier: float

    def active(self, t: float) -> bool:
        return self.start <= t < self.start + self.duration


def seeded_spikes(seed: int, lifetime_s: float, num_spikes: int,
                  multiplier: float, duration_s: float) -> Tuple[Spike, ...]:
    """Deterministic spike draw: starts uniform over the middle of the
    service lifetime (never in the last 10% — a spike the service
    retires under says nothing about the autoscaler)."""
    if num_spikes <= 0:
        return ()
    rng = np.random.RandomState(seed)
    starts = np.sort(rng.uniform(0.05, 0.85, size=num_spikes)) * lifetime_s
    return tuple(Spike(float(s), float(duration_s), float(multiplier))
                 for s in starts)


class DiurnalLoad:
    """lambda(t): requests/s at `t` seconds after service start."""

    def __init__(self, base_rps: float, peak_rps: float, period_s: float,
                 phase_s: float = 0.0, spikes: Sequence[Spike] = ()):
        if base_rps < 0 or peak_rps < base_rps:
            raise ValueError(
                f"need 0 <= base_rps <= peak_rps, got {base_rps}/{peak_rps}")
        self.base_rps = float(base_rps)
        self.peak_rps = float(peak_rps)
        self.period_s = float(period_s)
        self.phase_s = float(phase_s)
        self.spikes = tuple(spikes)

    def rate(self, t: float) -> float:
        """Instantaneous arrival rate. With phase 0 the service starts
        at the trough and peaks half a period in."""
        if self.period_s > 0:
            swing = (self.peak_rps - self.base_rps) * 0.5
            day = self.base_rps + swing * (
                1.0 - math.cos(2.0 * math.pi
                               * (t + self.phase_s) / self.period_s))
        else:
            day = self.base_rps
        mult = 1.0
        for spike in self.spikes:
            if spike.active(t):
                mult *= spike.multiplier
        return day * mult

    def mean_rate(self, t0: float, t1: float, samples: int = 16) -> float:
        """Mean rate over [t0, t1), midpoint-sampled (deterministic)."""
        if t1 <= t0:
            return self.rate(t0)
        step = (t1 - t0) / samples
        return sum(self.rate(t0 + (i + 0.5) * step)
                   for i in range(samples)) / samples

    def peak_rate(self, t0: float, t1: float, samples: int = 16) -> float:
        """Max sampled rate over [t0, t1) — what the autoscaler
        provisions for, so a spike starting mid-round is already covered
        at the round's dispatch."""
        if t1 <= t0:
            return self.rate(t0)
        step = (t1 - t0) / samples
        edges = [self.rate(t0), self.rate(t1 - 1e-9)]
        return max(edges + [self.rate(t0 + (i + 0.5) * step)
                            for i in range(samples)])

    def offered(self, t0: float, t1: float, samples: int = 16) -> float:
        """Expected requests arriving in [t0, t1)."""
        return self.mean_rate(t0, t1, samples) * max(t1 - t0, 0.0)


__all__ = ["Spike", "seeded_spikes", "DiurnalLoad"]
