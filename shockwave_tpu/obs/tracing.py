"""Nestable span tracer with Chrome-trace (Perfetto) JSON export.

Spans are recorded as complete ("ph": "X") events keyed by thread id, so
nesting falls out of the viewer's per-track stacking — no explicit
parent bookkeeping. The event buffer is a bounded ring (oldest spans
drop first) so a long-lived scheduler cannot grow without bound.

The clock is injected (see obs/clock.py): under the simulator's virtual
clock the trace is laid out in simulated seconds; under wall clocks it
lines up with logs and journal records. Export is plain
``json.dump`` — traces are telemetry, not durable state.

View an exported trace in ``chrome://tracing`` / https://ui.perfetto.dev,
or summarize it with ``python -m shockwave_tpu.obs.report <trace>``.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import List, Optional

from .clock import Clock, wall_clock

#: Default ring size: a 360 s-round physical run emits ~10 spans/round
#: plus one per journal fsync; 200k events covers days of rounds.
DEFAULT_MAX_EVENTS = 200_000


class Tracer:
    def __init__(self, clock: Optional[Clock] = None, enabled: bool = True,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self._clock: Clock = clock or wall_clock
        self._enabled = enabled
        self._events: "deque[dict]" = deque(maxlen=max_events)
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "Tracer._lock")

    # Rides inside pickled scheduler objects (simulation checkpoints);
    # locks are recreated on load.
    def __getstate__(self):
        state = dict(self.__dict__)
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "Tracer._lock")

    @property
    def enabled(self) -> bool:
        return self._enabled

    @contextmanager
    def span(self, name: str, **args):
        """Record one span covering the block. `args` must be
        JSON-serializable; they land in the trace event's `args` and are
        what the report CLI groups by (e.g. ``round=N``)."""
        if not self._enabled:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            t1 = self._clock()
            event = {"name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
                     "tid": threading.get_ident(), "args": args}
            with self._lock:
                self._events.append(event)

    def events(self) -> List[dict]:
        """Snapshot of recorded spans, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def export_chrome_trace(self, path: str) -> str:
        """Write the buffer as Chrome-trace JSON; returns `path`."""
        pid = os.getpid()
        trace = {
            "displayTimeUnit": "ms",
            "traceEvents": [
                {"name": e["name"], "ph": "X", "cat": "swtpu",
                 "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6,
                 "pid": pid, "tid": e["tid"], "args": e["args"]}
                for e in self.events()],
        }
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        return path
