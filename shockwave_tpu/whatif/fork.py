"""State forking: detach a digital twin of a live scheduler.

One fork primitive serves the whole what-if plane (plane.py), the
mid-run sweep seeding (`sweep_scenarios.py --from_state`) and the twin
shadow validator (`chaos_campaign.py --twin_schedules`):

- `capture(sched)` pickles the scheduler's journal snapshot
  (`Scheduler.snapshot_state` — the SAME serializer crash recovery
  uses, no second one) into a detached blob. This is the only step
  that must run under the scheduler lock in physical mode; it is
  instrumented as the `whatif_fork` round phase and the
  `swtpu_whatif_fork_seconds` histogram so the lock hold-time it adds
  is first-class telemetry.
- `thaw(sched, blob)` builds a fresh SIMULATION-mode scheduler and
  restores the blob into it (`restore_state`). The twin shares the
  parent's read-only oracle/calibration tables and profiles by
  reference; everything mutable arrives through the pickle round trip,
  so the twin cannot write back into the live scheduler.
- `rollforward(twin, ...)` re-enters the simulator's event loop from
  the forked round boundary (`Scheduler._sim_event_loop` with
  ``schedule_first=True``: the first action is scheduling a round at
  the frozen clock, exactly what the parent would have done next), with
  an optional horizon bound and fault-event injection.
- `load_twin(...)` seeds a twin from durable state on disk instead of
  a live object: a journal state dir (snapshot + replay, conservative
  round-boundary re-entry, like crash recovery) or a simulation
  checkpoint file (exact resume, in-flight micro-task heap included).

Twins never journal (no durability layer is attached), never own a
what-if plane themselves (``whatif=None`` — no recursive forking), and
carry their own fresh Observability bundle on the virtual clock.
"""
from __future__ import annotations

import os
import pickle
from dataclasses import replace
from typing import List, Optional, Sequence, Tuple

from ..obs import names as obs_names

#: Attributes the twin shares with its parent BY REFERENCE: read-only
#: oracle/calibration tables (nothing in the rollforward path mutates
#: them) and the positional profiles list. Everything mutable rides the
#: snapshot pickle instead.
_SHARED_READONLY_ATTRS = (
    "_oracle_throughputs", "_dispatch_overhead",
    "_dispatch_overhead_by_type", "_lease_shortfall",
    "_shortfall_by_type", "_round_drain", "_round_drain_by_type",
    "_round_drain_by_sf", "_deployment_faithful", "_profiles",
)


def capture(sched) -> bytes:
    """Freeze the scheduler's durable state into a detached blob.

    Physical callers hold the scheduler lock; the copy is the only
    lock-held cost of a fork (thawing and rolling happen on detached
    data). The policy rides along so the twin continues with the exact
    policy state (internal RNG included) the parent had at the fork.
    """
    with sched.obs.phase(obs_names.SPAN_WHATIF_FORK,
                         round=sched.rounds.num_completed_rounds):
        t0 = sched.obs.clock()
        blob = pickle.dumps(
            {"state": sched.snapshot_state(),
             "policy": sched._policy,
             "clock": sched.get_current_timestamp(),
             "sim_round_start": getattr(sched, "_sim_round_start", None)},
            protocol=pickle.HIGHEST_PROTOCOL)
        sched.obs.observe(obs_names.WHATIF_FORK_SECONDS,
                          max(sched.obs.clock() - t0, 0.0))
    return blob


def twin_config(config):
    """The twin's SchedulerConfig: the parent's, with everything that
    would touch the outside world (journal, obs endpoint, trace export)
    or recurse (the what-if plane itself) stripped, and the horizon
    bound cleared for the rollforward to set."""
    return replace(config, whatif=None, state_dir=None, resume=False,
                   obs_port=None, obs_trace_path=None, max_rounds=None,
                   snapshot_interval_rounds=0, ha=None)


def thaw(sched, blob: bytes, seed: Optional[int] = None):
    """Materialize one detached twin from a captured blob.

    `seed` != None reseeds the twin's tie-break RNGs (worker-type
    shuffler + scheduler RNG) — the Monte-Carlo axis of a K-sample
    rollout set; None keeps the parent's exact RNG state (the fidelity
    contract: a seedless twin continues bit-identically).
    """
    from ..sched.scheduler import Scheduler
    payload = pickle.loads(blob)
    twin = Scheduler(payload["policy"], simulate=True,
                     profiles=sched._profiles,
                     config=twin_config(sched._config))
    for attr in _SHARED_READONLY_ATTRS:
        setattr(twin, attr, getattr(sched, attr))
    twin.restore_state(payload["state"])
    # A physical parent's clock is wall time (the `_current_timestamp`
    # field it snapshots is stale); every parent's live clock rides the
    # blob explicitly, so the twin's virtual clock continues from the
    # fork instant in either mode.
    twin._current_timestamp = payload["clock"]
    twin._sim_round_start = payload["sim_round_start"]
    if not sched._simulate:
        # Physical parent: the restored allocation is whatever the
        # async allocation thread last committed, and the reset stamp
        # is wall-clock — a short twin horizon would never re-solve
        # even as the twin's own decisions free or claim capacity.
        # Re-enter conservatively (the crash-recovery stance): re-plan
        # on the first round and whenever twin-side state changes
        # demand it. Simulation parents keep their exact fields — the
        # fidelity contract requires the twin to re-solve exactly when
        # the parent would have.
        twin._need_to_update_allocation = True
        twin._last_reset_time = 0.0
        # The live physical scheduler re-solves continuously on its
        # allocation thread; the virtual twin only re-solves at the
        # reset interval, which can exceed a whole rollout horizon.
        # Round-granularity resets keep the twin's allocation tracking
        # its own capacity decisions (serving scale-ups/downs) the way
        # the live allocation thread would.
        twin._config = replace(
            twin._config,
            minimum_time_between_allocation_resets=twin
            ._time_per_iteration)
    if seed is not None:
        import random as _random

        import numpy as _np
        twin._rng = _np.random.RandomState(seed)
        twin._worker_type_shuffler = _random.Random(seed + 5)
    return twin


def fork_twin(sched, seed: Optional[int] = None):
    """capture + thaw in one call (simulation-mode callers; physical
    callers split the two around the lock)."""
    return thaw(sched, capture(sched), seed=seed)


def default_remaining_jobs(twin, queued: Sequence = ()) -> int:
    """A remaining-work count for re-entering the event loop: active
    non-serving jobs, live services, and not-yet-admitted arrivals.
    The loop only needs it positive while work exists — the
    empty-system break is the real exit — but an exact count keeps the
    deployment-faithful exit-clock rewind armed."""
    active = sum(1 for j in twin.acct.jobs if j not in twin._serving_job_ids)
    services = (sum(1 for s in twin._serving_tier.services.values()
                    if not s.retired)
                if twin._serving_tier is not None else 0)
    return active + services + len(queued)


def rollforward(twin, queued: Sequence[Tuple[float, object]] = (),
                running: Optional[List[tuple]] = None,
                horizon_rounds: Optional[int] = None,
                fault_events: Optional[Sequence[dict]] = None,
                remaining_jobs: Optional[int] = None,
                schedule_first: Optional[bool] = None) -> float:
    """Roll a thawed twin forward on the virtual clock.

    With `horizon_rounds` the rollout stops after that many additional
    rounds; None runs the twin's workload to drain. `queued` is the
    not-yet-admitted arrival tail (deep-copy it first if the caller
    reuses the jobs — ``simulate`` mutates Job objects). `running` is a
    checkpoint's in-flight micro-task heap (exact resume); with the
    default empty heap the first action is scheduling a fresh round at
    the frozen clock (``schedule_first``), which is exactly what the
    parent's loop would do next at a fork point. Returns the twin's
    clock at exit (the horizon end, or the drain makespan).
    """
    if horizon_rounds is not None:
        twin._config.max_rounds = (twin.rounds.num_completed_rounds
                                   + int(horizon_rounds))
    running = list(running or [])
    if schedule_first is None:
        # An exact checkpoint resume re-enters at the loop head (its
        # heap drains first); a boundary fork schedules immediately.
        schedule_first = not running
    if remaining_jobs is None:
        remaining_jobs = default_remaining_jobs(twin, queued)
    if remaining_jobs <= 0:
        return twin.get_current_timestamp()
    with twin.obs.span(obs_names.SPAN_WHATIF_ROLLOUT):
        return twin._sim_event_loop(
            list(queued), running, remaining_jobs,
            twin.rounds.num_completed_rounds,
            fault_queue=list(fault_events or []),
            schedule_first=schedule_first)


def load_twin(path: str, policy, profiles, config,
              throughputs_file: Optional[str] = None
              ) -> Tuple[object, list, list, Optional[int]]:
    """Seed a twin from durable state on disk.

    `path` is either a journal state DIR (snapshot.pkl + journal
    segments — restored via ``restore_from_durable_state``, then
    re-entered conservatively at a round boundary, the same contract
    crash recovery honors) or a simulation CHECKPOINT file (the full
    pickled simulator, resumed exactly — in-flight heap included).
    Returns ``(twin, queued, running, remaining_jobs)``;
    `remaining_jobs` is None for state dirs (derive from the twin).
    """
    from ..sched.scheduler import Scheduler
    # Unlike thaw() there is no live parent to share oracle tables
    # with, so the twin reads the throughputs file itself (replayed
    # job_added events re-derive initial throughputs from it).
    twin = Scheduler(policy, simulate=True, profiles=profiles,
                     throughputs_file=throughputs_file,
                     config=twin_config(config))
    if os.path.isdir(path):
        from ..sched import journal
        twin.restore_from_durable_state(journal.load_state(path))
        return twin, [], [], None
    queued, running, remaining_jobs, _ = (
        twin._load_simulation_checkpoint(path))
    return twin, queued, running, remaining_jobs
