"""ResNets in flax.linen, shaped for the MXU.

ResNet-18 (CIFAR-10 stem) and ResNet-50 (ImageNet stem). Convolutions are
NHWC (TPU-native layout); compute dtype defaults to bfloat16 with fp32
params and batch-norm statistics.

Capability parity with the reference workloads
(workloads/pytorch/image_classification/{cifar10,imagenet}/main.py);
the architecture itself is standard He et al. '15.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    small_stem: bool = False  # CIFAR-style 3x3 stem without max-pool
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.small_stem:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                     name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i, conv=conv, norm=norm,
                    act=nn.relu, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=ResNetBlock,
                   num_classes=10, small_stem=True)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                   num_classes=1000)
