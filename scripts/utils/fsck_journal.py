#!/usr/bin/env python3
"""Offline validator for a scheduler durability state dir.

Checks, without touching the live scheduler:
- snapshot integrity (CRC footer + unpickle), including the .prev
  fallback,
- every journal segment's framing and CRCs, reporting a torn tail
  (recoverable: recovery discards it) separately from deeper corruption,
- sequence-number sanity: strictly increasing, and the post-snapshot
  event stream starts at snapshot.last_seq + 1 or earlier (gaps below
  the snapshot horizon are expected — compaction deletes covered
  segments).

Exit codes: 0 = clean, 1 = recoverable damage (torn tail / snapshot
fell back to .prev), 2 = state unusable or not found.

Usage:
    python scripts/utils/fsck_journal.py <state_dir> [--verbose]
"""
import argparse
import collections
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.sched.journal import (SNAPSHOT_NAME, TAIL_CLEAN,  # noqa: E402
                                         JournalError, _read_snapshot_file,
                                         list_segments, read_journal)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("state_dir")
    p.add_argument("--verbose", action="store_true",
                   help="print every record type histogram per segment")
    args = p.parse_args()

    rc = 0
    if not os.path.isdir(args.state_dir):
        print(f"ERROR: {args.state_dir} is not a directory")
        return 2

    # -- snapshot ------------------------------------------------------
    snap_path = os.path.join(args.state_dir, SNAPSHOT_NAME)
    last_seq = 0
    snapshot = None
    if os.path.exists(snap_path) or os.path.exists(snap_path + ".prev"):
        snapshot = _read_snapshot_file(snap_path)
        if snapshot is not None:
            last_seq = int(snapshot.get("last_seq", 0))
            print(f"snapshot: OK (covers seq <= {last_seq})")
        else:
            snapshot = _read_snapshot_file(snap_path + ".prev")
            if snapshot is not None:
                last_seq = int(snapshot.get("last_seq", 0))
                print(f"snapshot: current CORRUPT, .prev OK "
                      f"(covers seq <= {last_seq})")
                rc = max(rc, 1)
            else:
                print("snapshot: CORRUPT (current and .prev both "
                      "unreadable)")
                rc = 2
    else:
        print("snapshot: none (journal-only state)")

    # -- segments ------------------------------------------------------
    segments = list_segments(args.state_dir)
    if not segments and snapshot is None:
        print("no journal segments found")
        return 2 if rc == 0 else rc

    total = 0
    replayable = 0
    prev_seq = None
    prev_replayable_seq = None
    types: collections.Counter = collections.Counter()
    for path in segments:
        try:
            records, tail = read_journal(path)
        except JournalError as e:
            print(f"{os.path.basename(path)}: UNREADABLE ({e})")
            rc = 2
            continue
        seg_types = collections.Counter(r.get("type", "?") for r in records)
        types.update(seg_types)
        total += len(records)
        for r in records:
            seq = int(r.get("seq", 0))
            if prev_seq is not None and seq <= prev_seq:
                print(f"{os.path.basename(path)}: seq {seq} not "
                      f"increasing (prev {prev_seq})")
                rc = 2
            prev_seq = seq
            if seq > last_seq:
                # The replayable stream must be gapless: sequences are
                # allocated one at a time, so a jump means a lost
                # segment (or manual deletion) — recovery would
                # silently skip the missing events.
                expected = (last_seq if prev_replayable_seq is None
                            else prev_replayable_seq) + 1
                if seq != expected:
                    print(f"{os.path.basename(path)}: GAP in replayable "
                          f"stream — expected seq {expected}, found "
                          f"{seq} (events lost?)")
                    rc = 2
                prev_replayable_seq = seq
                replayable += 1
        status = "OK" if tail == TAIL_CLEAN else "TORN TAIL (recoverable)"
        if tail != TAIL_CLEAN:
            rc = max(rc, 1)
        print(f"{os.path.basename(path)}: {len(records)} records, {status}")
        if args.verbose and seg_types:
            for etype, count in sorted(seg_types.items()):
                print(f"    {etype}: {count}")

    print(f"total: {total} journal records, {replayable} replayable past "
          f"the snapshot horizon")
    if types and not args.verbose:
        top = ", ".join(f"{t}={c}" for t, c in types.most_common(6))
        print(f"event mix: {top}")
    print({0: "CLEAN", 1: "RECOVERABLE DAMAGE", 2: "UNUSABLE"}[rc])
    return rc


if __name__ == "__main__":
    sys.exit(main())
