"""Discrete-event simulator tests: tiny synthetic traces + canonical parity."""
import json
import os
import subprocess
import sys

import pytest

from shockwave_tpu.core.job import Job
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy

REPO = os.path.join(os.path.dirname(__file__), "..")
DATA = os.path.join(REPO, "data")


def make_job(job_type="ResNet-18 (batch size 32)", total_steps=10000,
             duration=1000, scale_factor=1, mode="static"):
    return Job(None, job_type, f"python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=duration,
               scale_factor=scale_factor, mode=mode)


def run_sim(jobs, arrivals, policy_name="max_min_fairness", num_workers=2,
            round_duration=120.0, **cfg):
    policy = get_policy(policy_name, seed=0)
    sched = Scheduler(
        policy, simulate=True,
        throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
        config=SchedulerConfig(time_per_iteration=round_duration, **cfg))
    makespan = sched.simulate({"v100": num_workers}, arrivals, jobs)
    return sched, makespan


class TestSingleJob:
    def test_runs_to_completion(self):
        # ResNet-18 bs32 on v100: oracle 42.97 steps/s -> 10000 steps ~ 233s.
        sched, makespan = run_sim([make_job(total_steps=10000)], [0.0])
        assert len(sched._completed_jobs) == 1
        jct = sched.get_average_jct()
        assert jct[0] == pytest.approx(makespan, rel=0.01)
        assert makespan == pytest.approx(10000 / 42.97497938, rel=0.01)

    def test_multi_round_job(self):
        sched, makespan = run_sim([make_job(total_steps=50000)], [0.0])
        # 42.97 steps/s -> ~1163s over ~10 rounds of 120 s, preemption-free.
        assert makespan == pytest.approx(50000 / 42.97497938, rel=0.01)
        assert sched.rounds.num_completed_rounds >= 9


class TestZeroOracleFamilies:
    def test_a3c_simulates_with_zeroed_oracle_entry(self):
        """The reference oracle ships 0.0 steps/s for A3C/CycleGAN; the
        simulator must seed from the trace's nominal rate instead of
        raising a misleading "no oracle throughput" KeyError."""
        sched, makespan = run_sim(
            [make_job(job_type="A3C", total_steps=100, duration=100)], [0.0])
        assert len(sched._completed_jobs) == 1
        assert makespan > 0

    def test_missing_oracle_key_still_raises(self):
        with pytest.raises(KeyError):
            run_sim([make_job(job_type="NoSuchModel (batch size 1)",
                              total_steps=10, duration=10)], [0.0])


class TestCalibratedDispatchOverhead:
    """Calibrated cold-dispatch model (reproduce/fidelity/): a measured
    per-worker-type startup charge on every cold dispatch replaces the
    reference-parity flat post-preemption charge."""

    RATE = 42.97497938  # ResNet-18 bs32 on v100 in the reference oracle

    def _run(self, total_steps, overhead, num_workers=1, n_jobs=1):
        jobs = [make_job(total_steps=total_steps) for _ in range(n_jobs)]
        return run_sim(jobs, [0.0] * n_jobs, num_workers=num_workers,
                       dispatch_overhead_s={"v100": overhead})

    def test_single_job_charged_once_then_warm(self):
        # One job on one worker lease-extends every round: only the
        # first dispatch is cold, so exactly one startup charge lands.
        steps = int(self.RATE * 300)
        _, base = self._run(steps, 0.0)
        _, slow = self._run(steps, 25.0)
        assert slow == pytest.approx(base + 25.0, abs=2.0)

    def test_preempted_jobs_charged_every_cold_dispatch(self):
        # Two jobs sharing one worker alternate rounds: every dispatch
        # is cold, so the makespan grows by ~one charge per round.
        steps = int(self.RATE * 115)  # just under one 120 s round each
        sched, slow = self._run(steps, 25.0, n_jobs=2)
        _, base = self._run(steps, 0.0, n_jobs=2)
        rounds = sched.rounds.num_completed_rounds
        assert rounds >= 3
        assert slow > base + 25.0 * (rounds - 1) * 0.8

    def test_oracle_meta_activates_model(self, tmp_path):
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {"dispatch_overhead_s": {"v100": 30.0}}
        path = tmp_path / "oracle_meta.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        assert makespan == pytest.approx(base + 30.0, abs=2.0)

    def test_per_job_type_overhead_wins_over_scalar(self, tmp_path):
        """Measured per-type startup (e.g. ResNet 23 s vs Rec 7 s on the
        loopback host) must override the per-worker-type mean."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "dispatch_overhead_s": {"v100": 10.0},
            "dispatch_overhead_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 40.0}}}
        path = tmp_path / "oracle_meta.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)

        def run(oracle_path):
            policy = get_policy("max_min_fairness", seed=0)
            sched = Scheduler(
                policy, simulate=True, throughputs_file=str(oracle_path),
                config=SchedulerConfig(time_per_iteration=120.0))
            return sched.simulate(
                {"v100": 1}, [0.0], [make_job(total_steps=steps)])

        typed = run(path)
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        # Single job lease-extends after round 1: exactly one cold
        # charge, at the per-type 40 s, not the 10 s scalar.
        assert typed == pytest.approx(base + 40.0, abs=2.0)

    def test_round_drain_shifts_cycle_without_phantom_run_time(
            self, tmp_path):
        """round_drain_s is dead time OUTSIDE the lease: it must push
        completion later but never accrue as job run time (which feeds
        the 1.5x deadline and cost accounting)."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {"dispatch_overhead_s": {"v100": 10.0},
                              "round_drain_s": {"v100": 30.0}}
        path = tmp_path / "oracle_drain.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        # One cold dispatch: +10 s budget loss inside, +30 s drain shift.
        assert makespan == pytest.approx(base + 40.0, abs=2.0)
        run_time = sum(
            sum(per.values())
            for per in sched.acct.run_time_per_worker.values())
        # Accounted run time covers overhead + compute only — the 30 s
        # drain must not appear in it.
        assert run_time <= base + 10.0 + 2.0
        assert run_time >= base - 2.0

    def test_per_type_round_drain_wins_over_scalar(self, tmp_path):
        """The headline fidelity artifact depends on the per-type drain
        path: it must override the per-worker-type mean."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "dispatch_overhead_s": {"v100": 0.0},
            "round_drain_s": {"v100": 5.0},
            "round_drain_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 35.0}}}
        path = tmp_path / "oracle_drain_type.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        # One cold dispatch: the per-type 35 s drain shift, not 5 s.
        assert makespan == pytest.approx(base + 35.0, abs=2.0)

    def test_per_sf_drain_wins_for_gangs(self, tmp_path):
        """Gang (sf>1) cold dispatches charge the per-scale-factor drain
        (measured ~3x the sf=1 cycle excess on the gang fidelity
        artifact), never the sf=1 per-type/scalar calibration."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        rate_sf2 = oracle["v100"]["('ResNet-18 (batch size 32)', 2)"]["null"]
        oracle["__meta__"] = {
            "dispatch_overhead_s": {"v100": 0.0},
            "round_drain_s": {"v100": 5.0},
            "round_drain_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 5.0}},
            "round_drain_s_by_sf": {"v100": {"2": 40.0}}}
        path = tmp_path / "oracle_drain_sf.json"
        path.write_text(json.dumps(oracle))
        steps = int(rate_sf2 * 300)
        job = [make_job(total_steps=steps, scale_factor=2)]
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate({"v100": 2}, [0.0], job)
        _, base = run_sim([make_job(total_steps=steps, scale_factor=2)],
                          [0.0], num_workers=2)
        # One cold gang dispatch: the by-sf 40 s drain shift, not 5 s.
        assert makespan == pytest.approx(base + 40.0, abs=2.0)

    def test_per_type_drain_alone_activates_faithful_mode(self, tmp_path):
        """A by-type-only drain calibration must still flip the
        simulator into deployment-faithful mode."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "round_drain_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 35.0}}}
        path = tmp_path / "oracle_drain_only.json"
        path.write_text(json.dumps(oracle))
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        assert sched._deployment_faithful

    def test_explicit_config_beats_oracle_by_type(self, tmp_path):
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "dispatch_overhead_s": {"v100": 10.0},
            "dispatch_overhead_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 40.0}}}
        path = tmp_path / "oracle_cfg.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0,
                                   dispatch_overhead_s={"v100": 15.0}))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        # The operator's 15 s wins over both oracle values.
        assert makespan == pytest.approx(base + 15.0, abs=2.0)

    def test_lease_shortfall_preferred_over_startup_proxy(self, tmp_path):
        """When both calibration methods wrote the oracle, the deployed
        in-lease shortfall (lease_shortfall_s, measure_deployed.py) must
        win over the solo spawn->exit proxy (dispatch_overhead_s,
        measure_startup.py)."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "dispatch_overhead_s": {"v100": 30.0},
            "lease_shortfall_s": {"v100": 5.0}}
        path = tmp_path / "oracle_shortfall.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        assert makespan == pytest.approx(base + 5.0, abs=2.0)

    def test_explicit_config_falls_through_for_uncovered_type(
            self, tmp_path):
        """An explicit config dict covering only OTHER worker types must
        not zero out a type the oracle calibrated: the uncovered type
        falls through to the oracle values instead of paying nothing."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {
            "dispatch_overhead_s_by_type": {
                "v100": {"ResNet-18 (batch size 32)": 40.0}}}
        path = tmp_path / "oracle_other_type.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 300)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(
                time_per_iteration=120.0,
                dispatch_overhead_s={"v5e": 15.0}))
        makespan = sched.simulate(
            {"v100": 1}, [0.0], [make_job(total_steps=steps)])
        _, base = run_sim([make_job(total_steps=steps)], [0.0],
                          num_workers=1)
        # v100 is absent from the explicit dict -> the oracle's 40 s
        # per-type charge applies, not 0.
        assert makespan == pytest.approx(base + 40.0, abs=2.0)

    def test_uncalibrated_type_keeps_flat_charge(self, tmp_path):
        """A partially calibrated oracle (some other worker type) must
        not zero out preemption costs for uncovered types: they keep
        the reference's flat post-preemption charge."""
        with open(os.path.join(DATA, "tacc_throughputs.json")) as f:
            oracle = json.load(f)
        oracle["__meta__"] = {"dispatch_overhead_s": {"v5e": 7.0}}
        path = tmp_path / "oracle_partial.json"
        path.write_text(json.dumps(oracle))
        steps = int(self.RATE * 115)
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True, throughputs_file=str(path),
            config=SchedulerConfig(time_per_iteration=120.0))
        got = sched.simulate(
            {"v100": 1}, [0.0, 0.0],
            [make_job(total_steps=steps), make_job(total_steps=steps)])
        # Two alternating jobs on the uncalibrated v100: identical to
        # the fully uncalibrated run (flat charge applies), with the
        # wall-clocked round floor being the only faithful-mode effect.
        _, base = run_sim(
            [make_job(total_steps=steps), make_job(total_steps=steps)],
            [0.0, 0.0], num_workers=1)
        assert got >= base * 0.98

    def test_meta_key_invisible_to_throughput_readers(self, tmp_path):
        from shockwave_tpu.core.oracle import (read_oracle_meta,
                                               read_throughputs)
        path = tmp_path / "o.json"
        path.write_text(json.dumps({
            "__meta__": {"dispatch_overhead_s": {"cpu": 9.5}},
            "cpu": {"('A3C', 1)": {"null": 2.0}}}))
        tputs = read_throughputs(str(path))
        assert set(tputs) == {"cpu"}
        assert read_oracle_meta(str(path)) == {
            "dispatch_overhead_s": {"cpu": 9.5}}


class TestScheduleReplay:
    """Schedule-replay mode (fidelity methodology): forced_schedule
    executes a recorded per-round schedule verbatim."""

    RATE = 42.97497938

    def _free_run(self, jobs, arrivals, **cfg):
        return run_sim(jobs, arrivals, num_workers=1, **cfg)

    def _replay(self, jobs, arrivals, forced, **cfg):
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0, **cfg))
        makespan = sched.simulate({"v100": 1}, arrivals, jobs,
                                  forced_schedule=forced)
        return sched, makespan

    def test_self_replay_is_bit_identical(self):
        """Replaying a simulation's own recorded schedule must
        reproduce its metrics exactly (the idempotence property the
        decomposition methodology rests on)."""
        jobs = lambda: [make_job(total_steps=int(self.RATE * 115))
                        for _ in range(3)]
        free, free_span = self._free_run(jobs(), [0.0, 0.0, 0.0])
        recorded = [{j: tuple(ids) for j, ids in rnd.items()}
                    for rnd in free.rounds.per_round_schedule]
        replay, replay_span = self._replay(jobs(), [0.0, 0.0, 0.0], recorded)
        assert replay_span == free_span
        assert (replay.get_average_jct()[3] == free.get_average_jct()[3])

    def test_replay_falls_back_to_policy_after_recording(self):
        """A recording shorter than the replay needs must not starve
        the leftover jobs: rounds past the recording use the live
        policy."""
        # Recording covers only round 0 for job 0; job 1 needs the
        # fallback to ever run.
        steps = int(self.RATE * 115)
        jobs = [make_job(total_steps=steps), make_job(total_steps=steps)]
        sched, makespan = self._replay(jobs, [0.0, 0.0], [{0: (0,)}])
        assert len(sched._completed_jobs) == 2
        assert makespan > 0

    def test_replay_skips_completed_jobs_and_burns_empty_rounds(self):
        """Recorded rounds whose jobs already finished in the replay
        are burned (clock advances a full round) so later recorded
        rounds keep their physical indices."""
        steps = int(self.RATE * 60)  # finishes inside round 0
        jobs = [make_job(total_steps=steps),
                make_job(total_steps=int(self.RATE * 115))]
        # Recording: job 0 twice (second occurrence is already done in
        # the replay), then job 1.
        sched, makespan = self._replay(
            jobs, [0.0, 0.0], [{0: (0,)}, {0: (0,)}, {1: (0,)}])
        assert len(sched._completed_jobs) == 2
        # Job 1 ran in recorded round 2, i.e. after the burned round.
        assert sched.rounds.per_round_schedule[1] == {}
        assert 1 in sched.rounds.per_round_schedule[2]

    def test_rate_override_replaces_oracle_rate(self):
        """rate_override drives both the timing model and completion:
        halving the rate doubles the single-job makespan."""
        steps = int(self.RATE * 115)
        _, base = self._free_run([make_job(total_steps=steps)], [0.0])
        _, slow = self._free_run(
            [make_job(total_steps=steps)], [0.0],
            rate_override={0: self.RATE / 2})
        assert slow == pytest.approx(2 * base, rel=0.02)


class TestContention:
    def test_two_jobs_one_worker_share(self):
        jobs = [make_job(total_steps=20000), make_job(total_steps=20000)]
        sched, makespan = run_sim(jobs, [0.0, 0.0], num_workers=1)
        assert len(sched._completed_jobs) == 2
        # Serial execution of interleaved rounds: ~2x the isolated runtime.
        assert makespan > 350

    def test_deterministic(self):
        jobs1 = [make_job(total_steps=20000), make_job(total_steps=30000)]
        jobs2 = [make_job(total_steps=20000), make_job(total_steps=30000)]
        _, m1 = run_sim(jobs1, [0.0, 100.0], num_workers=1)
        _, m2 = run_sim(jobs2, [0.0, 100.0], num_workers=1)
        assert m1 == m2


class TestMultiChipJobs:
    def test_gang_scheduled(self):
        jobs = [make_job(job_type="ResNet-18 (batch size 32)", total_steps=20000,
                         scale_factor=4)]
        sched, makespan = run_sim(jobs, [0.0], num_workers=4)
        assert len(sched._completed_jobs) == 1
        # All four chips were assigned in round 0.
        assert len(sched.rounds.per_round_schedule[0][0]) == 4

    def test_cannot_fit_waits(self):
        jobs = [make_job(total_steps=10000, scale_factor=4),
                make_job(total_steps=10000, scale_factor=4)]
        sched, _ = run_sim(jobs, [0.0, 0.0], num_workers=4)
        # Only one sf=4 job fits per round on 4 workers.
        for round_sched in sched.rounds.per_round_schedule:
            assert len(round_sched) <= 1


class TestSolverBudgetCap:
    def test_cap_clamped_without_pipelining(self):
        """Without pipelined planning the MILP blocks the physical round
        loop at mid-round, so the scheduler clamps any larger configured
        cap back to the 0.5 default. Simulation never clamps."""
        cfg = SchedulerConfig(
            time_per_iteration=120.0, pipelined_planning=False,
            shockwave={"num_gpus": 4, "solver_budget_cap_rounds": 2.0})
        sim = Scheduler(get_policy("shockwave", seed=0), simulate=True,
                        throughputs_file=os.path.join(
                            DATA, "tacc_throughputs.json"), config=cfg)
        assert sim._shockwave_planner.opts.budget_cap_rounds == 2.0
        phys = Scheduler(get_policy("shockwave", seed=0), simulate=False,
                         throughputs_file=os.path.join(
                             DATA, "tacc_throughputs.json"), config=cfg)
        assert phys._shockwave_planner.opts.budget_cap_rounds == 0.5

    def test_pipelined_physical_keeps_full_budget(self):
        """With pipelined planning (default) the solve runs off the
        round loop, so physical mode keeps the configured cap — and
        defaults to 2.0 rounds (the EXPERIMENTS.md 256-chip setting)
        when the config ships none."""
        cfg = SchedulerConfig(
            time_per_iteration=120.0,
            shockwave={"num_gpus": 4, "solver_budget_cap_rounds": 3.0})
        phys = Scheduler(get_policy("shockwave", seed=0), simulate=False,
                         throughputs_file=os.path.join(
                             DATA, "tacc_throughputs.json"), config=cfg)
        assert phys._shockwave_planner.opts.budget_cap_rounds == 3.0
        cfg_default = SchedulerConfig(
            time_per_iteration=120.0, shockwave={"num_gpus": 4})
        phys_default = Scheduler(
            get_policy("shockwave", seed=0), simulate=False,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=cfg_default)
        assert (phys_default._shockwave_planner.opts.budget_cap_rounds
                == 2.0)


class TestPackedScheduleRecording:
    def test_pair_dispatches_recorded_as_tuple_keys(self):
        # Two same-type jobs on one worker under a packing policy: the
        # pair oracle entries exist in tacc_throughputs.json, so the
        # policy packs them and the record must show the tuple key
        # (previously pairs were silently dropped from
        # per_round_schedule).
        jobs = [make_job(total_steps=30000), make_job(total_steps=30000)]
        sched, _ = run_sim(jobs, [0.0, 0.0],
                           policy_name="max_min_fairness_packed",
                           num_workers=1)
        pair_rounds = [rnd for rnd in sched.rounds.per_round_schedule
                       if (0, 1) in rnd]
        assert pair_rounds, "no packed-pair dispatch recorded"
        assert all(not isinstance(k, tuple) or k == (0, 1)
                   for rnd in sched.rounds.per_round_schedule for k in rnd)
        # Membership helper sees members through the tuple key.
        assert sched._in_recorded_round(pair_rounds[0], 0)
        assert sched._in_recorded_round(pair_rounds[0], 1)
        assert not sched._in_recorded_round(pair_rounds[0], 7)
        # Both members complete and count their scheduled rounds.
        assert len(sched._completed_jobs) == 2
        assert sched.rounds.num_scheduled_rounds[0] >= len(pair_rounds)


class TestAdaptation:
    def test_gns_job_doubles_bs(self):
        # ResNet-18 bs16 sf1 GNS doubles at epoch 31; give it enough epochs.
        steps_per_epoch = 50000 // 16 + 1
        jobs = [make_job(job_type="ResNet-18 (batch size 16)",
                         total_steps=steps_per_epoch * 50, duration=10**6,
                         mode="gns")]
        jobs[0].command = "python3 main.py --batch_size 16"
        sched, _ = run_sim(jobs, [0.0], num_workers=1)
        # After completion the job's recorded type reflects a larger bs.
        assert len(sched._completed_jobs) == 1

    def test_accordion_job_scales_up(self):
        steps_per_epoch = 50000 // 32 + 1
        jobs = [make_job(job_type="ResNet-18 (batch size 32)",
                         total_steps=steps_per_epoch * 40, duration=10**6,
                         mode="accordion")]
        sched, _ = run_sim(jobs, [0.0], num_workers=1)
        assert len(sched._completed_jobs) == 1


class TestMetrics:
    def test_utilization_bounded(self):
        jobs = [make_job(total_steps=30000) for _ in range(3)]
        sched, _ = run_sim(jobs, [0.0, 0.0, 0.0], num_workers=2)
        util, per_worker = sched.get_cluster_utilization()
        assert 0 < util <= 1.0

    def test_envy_ratios(self):
        jobs = [make_job(total_steps=30000) for _ in range(3)]
        sched, _ = run_sim(jobs, [0.0, 0.0, 0.0], num_workers=2)
        ratios, pairwise = sched.get_envy_ratios()
        assert len(ratios) == 3
        assert all(0 <= r <= 1 for r in ratios.values())


@pytest.mark.slow
class TestCanonicalParity:
    """Replay the canonical 120-job trace and compare against the
    reference's shipped result pickles (BASELINE.md)."""

    REFERENCE = {
        # policy: (makespan, avg_jct, unfair_fraction)
        "max_min_fairness": (33207.66, 11274.12, 0.2167),
        "gandiva_fair": (32367.43, 12574.27, 0.4333),
        "max_sum_throughput_perf": (31909.03, 9654.70, 0.225),
        "min_total_duration": (24204.82, 19806.73, 0.7167),
        "allox": (32488.62, 9926.31, 0.2667),
        "finish_time_fairness": (31928.74, 11301.98, 0.175),
        "shockwave": (24197.42, 9958.49, 0.05),
    }

    @pytest.mark.parametrize("policy", sorted(REFERENCE))
    def test_policy_close_to_reference(self, policy):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/drivers/simulate.py"),
             "--trace", os.path.join(DATA, "canonical_120job.trace"),
             "--policy", policy,
             "--throughputs", os.path.join(DATA, "tacc_throughputs.json"),
             "--cluster_spec", "v100:32", "--round_duration", "120"]
            + (["--config", os.path.join(REPO, "configs/tacc_32gpus.json")]
               if policy == "shockwave" else []),
            capture_output=True, text=True, timeout=1800)
        assert out.returncode == 0, out.stderr[-2000:]
        result = json.loads(out.stdout.strip().splitlines()[-1])
        ref_makespan, ref_jct, ref_unfair = self.REFERENCE[policy]
        assert result["makespan"] == pytest.approx(ref_makespan, rel=0.08)
        assert result["avg_jct"] == pytest.approx(ref_jct, rel=0.10)
        assert result["unfair_fraction"] == pytest.approx(ref_unfair, abs=0.08)


class TestSimulatorCheckpoint:
    """Mid-trace checkpoint/restore parity (reference: scheduler.py:1518-1594)."""

    def _make_trace(self):
        jobs = [make_job(total_steps=(i + 1) * 20000, duration=4000)
                for i in range(6)]
        arrivals = [i * 100.0 for i in range(6)]
        return jobs, arrivals

    def test_resume_matches_uninterrupted(self, tmp_path):
        jobs, arrivals = self._make_trace()
        sched_full, makespan_full = run_sim(jobs, arrivals)

        ckpt = str(tmp_path / "sim.ckpt")
        jobs2, arrivals2 = self._make_trace()
        policy = get_policy("max_min_fairness", seed=0)
        sched_a = Scheduler(
            policy, simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan_a = sched_a.simulate(
            {"v100": 2}, arrivals2, jobs2,
            checkpoint_file=ckpt, checkpoint_threshold=0.5)
        assert os.path.exists(ckpt)
        assert makespan_a == pytest.approx(makespan_full)

        # Resume from the checkpoint in a FRESH scheduler; it must finish
        # the remaining jobs and land on the same makespan.
        policy_b = get_policy("max_min_fairness", seed=0)
        sched_b = Scheduler(
            policy_b, simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan_b = sched_b.simulate(resume_from=ckpt)
        assert makespan_b == pytest.approx(makespan_full)
        assert len(sched_b._completed_jobs) == 6
        assert sched_b.get_average_jct() == pytest.approx(
            sched_full.get_average_jct())


class TestDurableSimCheckpoint:
    """Simulation checkpoints now ride core/durable_io (CRC footer,
    atomic rename, .prev retention): a torn checkpoint is rejected
    loudly instead of resuming a multi-hour sweep from garbage, and
    legacy footer-less checkpoints still load."""

    def _save_one(self, path, current_round=3):
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        sched.register_worker("v100", 1)
        sched.add_job(make_job(total_steps=500))
        sched.save_simulation_checkpoint(path, queued=[], running=[],
                                         remaining_jobs=1,
                                         current_round=current_round)
        return sched

    def _load_round(self, path):
        policy = get_policy("max_min_fairness", seed=0)
        sched = Scheduler(
            policy, simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        return sched, sched._load_simulation_checkpoint(path)

    def test_round_trip_and_prev_retention(self, tmp_path):
        path = str(tmp_path / "sim.ckpt")
        self._save_one(path)
        self._save_one(path)  # second generation retains the first
        assert os.path.exists(path + ".prev")
        sched, (queued, running, remaining, rnd) = self._load_round(path)
        assert (queued, running, remaining, rnd) == ([], [], 1, 3)
        assert len(sched.acct.jobs) == 1

    def _corrupt(self, path):
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF  # flip one payload byte
        open(path, "wb").write(bytes(blob))

    def test_corrupt_checkpoint_rejected_loudly(self, tmp_path):
        path = str(tmp_path / "sim.ckpt")
        self._save_one(path)  # single generation: no .prev to fall back to
        self._corrupt(path)
        with pytest.raises(ValueError, match="CRC"):
            self._load_round(path)

    def test_corrupt_current_falls_back_to_prev(self, tmp_path):
        path = str(tmp_path / "sim.ckpt")
        self._save_one(path, current_round=3)   # becomes .prev
        self._save_one(path, current_round=7)   # current generation
        self._corrupt(path)
        _, (_, _, remaining, rnd) = self._load_round(path)
        assert (remaining, rnd) == (1, 3)  # the retained generation

    def test_legacy_footerless_checkpoint_still_loads(self, tmp_path):
        import pickle
        path = str(tmp_path / "sim.ckpt")
        donor = self._save_one(path)
        # Re-write the same state the pre-durability way: bare pickle.
        open(path, "wb").write(pickle.dumps({
            "scheduler": donor.__dict__, "queued": [], "running": [],
            "remaining_jobs": 1, "current_round": 3}))
        sched, (queued, running, remaining, rnd) = self._load_round(path)
        assert (remaining, rnd) == (1, 3)
        assert len(sched.acct.jobs) == 1


class TestCostSLOTimelines:
    """Cost accrual, SLO violation counting, timeline dumps
    (reference: scheduler.py:3060-3128)."""

    def test_cost_accrual(self):
        jobs = [make_job(total_steps=20000, duration=2000) for _ in range(2)]
        sched, makespan = run_sim(
            jobs, [0.0, 0.0],
            per_worker_type_prices={"v100": 3.6})  # $3.6/hr = $0.001/s
        cost = sched.get_total_cost()
        # Two 1-chip jobs, ~465s each of execution: about 0.93 dollars total.
        busy = sum(sched.workers.cumulative_time.values())
        assert cost == pytest.approx(busy * 3.6 / 3600.0, rel=1e-6)
        assert cost > 0

    def test_slo_violations(self):
        fast = make_job(total_steps=2000, duration=2000)
        slow = make_job(total_steps=200000, duration=100)  # impossible SLO
        fast.SLO = 100.0   # generous: 100x duration
        slow.SLO = 1.01    # tight: ~101s deadline for a ~4600s job
        sched, _ = run_sim([fast, slow], [0.0, 0.0])
        assert sched.get_num_slo_violations() == 1

    def test_timeline_dump(self, tmp_path):
        jobs = [make_job(total_steps=20000, duration=2000)]
        sched, _ = run_sim(jobs, [0.0])
        sched.save_job_timelines(str(tmp_path))
        log = (tmp_path / "job_id=0.log").read_text()
        assert "SUBMITTED" in log
        assert "MICROTASK" in log
        assert "COMPLETED" in log


class TestSubEpochJobs:
    def test_priority_ratio_survives_zero_remaining_estimate(self):
        """A single-epoch job's remaining estimate legitimately collapses
        to exactly 0 (reference-parity Dirichlet algebra), so the
        planner's priority ratio must guard the zero fair-share finish
        average instead of dividing by it (hit by the 12-job fidelity
        trace's 70-step jobs)."""
        from shockwave_tpu.shockwave.metadata import JobMetadata
        from shockwave_tpu.shockwave.milp import _relaxation_priorities
        profile = {
            "model": "ResNet-18", "dataset": "CIFAR-10", "num_epochs": 1,
            "bs_every_epoch": [32], "duration_every_epoch": [424.0],
            "mem_every_epoch": [1857], "util_every_epoch": [87.6],
            "num_samples_per_epoch": 50000, "scale_factor": 1,
            "duration": 424,
        }
        meta = JobMetadata(0, profile)
        meta.register_submit(0.0)
        assert meta.dirichlet_posterior_remaining_runtime(0) == 0.0
        priorities = _relaxation_priorities(
            [meta], dirichlet=[0.0], runavg=[0.0], round_index=0,
            round_duration=120.0, future_share=0.5, rhomax=1.0, lam=5.0)
        import math
        assert len(priorities) == 1 and priorities[0] > 0
        assert all(math.isfinite(p) for p in priorities)

    def test_shockwave_simulates_sub_epoch_trace(self):
        """End-to-end: the shockwave policy must plan a trace of
        sub-epoch jobs without the relaxation-priority crash."""
        from shockwave_tpu.core.oracle import read_throughputs
        from shockwave_tpu.core.profiles import build_profiles
        jobs = [make_job(total_steps=50, duration=424) for _ in range(3)]
        tputs = read_throughputs(os.path.join(DATA, "tacc_throughputs.json"))
        sched = Scheduler(
            get_policy("shockwave", seed=0), simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            profiles=build_profiles(jobs, tputs),
            config=SchedulerConfig(
                time_per_iteration=120.0,
                shockwave={"num_gpus": 1, "time_per_iteration": 120.0}))
        makespan = sched.simulate({"v100": 1}, [0.0, 10.0, 20.0], jobs)
        assert len(sched._completed_jobs) == 3
        assert makespan > 0


class TestJobMetadataCaches:
    """The calibration + duration-map caches added for MILP-loop speed
    must be invisible: same results, recomputed only when the shared
    measurement timeline actually changes."""

    def _meta(self):
        from collections import OrderedDict

        from shockwave_tpu.shockwave.metadata import JobMetadata
        profile = {
            "model": "ResNet-18", "dataset": "cifar10", "num_epochs": 4,
            "num_samples_per_epoch": 1000,
            "bs_every_epoch": [32, 32, 64, 64],
            "mem_every_epoch": [1024] * 4,
            "util_every_epoch": [50] * 4,
            "duration_every_epoch": [100.0] * 4,
            "scale_factor": 1, "duration": 400.0,
        }
        meta = JobMetadata(7, profile)
        timeline = OrderedDict()
        meta.attach_throughput_measurements(timeline, round_duration=10.0)
        return meta, timeline

    def test_dmap_cached_until_recalibration(self):
        meta, timeline = self._meta()
        m1 = meta.bs_epoch_duration_map()
        assert m1 == {32: 100.0, 64: 100.0}
        # Cache hits hand out fresh copies: a caller mutating the result
        # must not corrupt the planner's cached durations.
        m1b = meta.bs_epoch_duration_map()
        assert m1b == m1
        m1b[32] = -1.0
        assert meta.bs_epoch_duration_map() == m1
        # Measured sample rate ~4x the profile (>40% deviation): the
        # calibration rescales epoch durations and must drop the cache.
        timeline[1] = (40.0, 32)  # 40 steps/s * bs32 * 10 s = 12800 samples
        m2 = meta.bs_epoch_duration_map()
        assert m2[32] < m1[32]
        # Unchanged timeline -> cached again (same values).
        assert meta.bs_epoch_duration_map() == m2

    def test_same_round_overwrite_invalidates(self):
        meta, timeline = self._meta()
        timeline[1] = (40.0, 32)
        m1 = meta.bs_epoch_duration_map()
        # A second worker's done callback overwrites round 1 with a
        # different measurement: fingerprint must notice (same len/key).
        timeline[1] = (0.1, 32)  # now ~3x SLOWER than profile
        m2 = meta.bs_epoch_duration_map()
        assert m2 is not m1
        assert m2[32] > m1[32]

    def test_dirichlet_matches_uncached_formula(self):
        meta, timeline = self._meta()
        est = meta.dirichlet_posterior_remaining_runtime()
        # Fresh instance, no cache warm-up: identical estimate.
        meta2, _ = self._meta()
        meta2.bs_epoch_duration_map()
        assert meta2.dirichlet_posterior_remaining_runtime() == est
        assert est > 0


class TestLastCompletionTime:
    def test_tracks_final_job_completion(self):
        jobs = [make_job(total_steps=20000, duration=2000),
                make_job(total_steps=40000, duration=4000)]
        sched, _ = run_sim(jobs, [0.0, 0.0])
        last = sched.get_last_completion_time()
        assert last > 0
        # The last completion can't exceed the simulator's final clock,
        # and every recorded JCT must end at or before it.
        assert last <= sched.get_makespan()
        ends = [sched.acct.start_timestamps[j] + d
                for j, d in sched.acct.completion_times.items()]
        assert last == pytest.approx(max(ends))
