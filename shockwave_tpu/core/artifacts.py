"""Timestamped raw-measurement artifacts.

Hardware claims (bench numbers, kernel parity errors, calibration
constants) are only as durable as their raw measurements: the committed
artifact is the evidence, the way the reference's committed oracle
JSONs carry its measured GPU numbers. Every profiling/bench tool
persists through this helper so all artifacts share one format:
device + jax version + UTC capture time + the tool's payload, in a
``<prefix>_<device>_<UTCstamp>.json`` file.
"""
from __future__ import annotations

import datetime
import json
import os
from typing import Optional


def save_measurement(dir_path: str, prefix: str, payload: dict,
                     device_kind: Optional[str] = None):
    """Write ``payload`` (stamped with provenance) to a timestamped JSON
    under ``dir_path``; returns (path, stamped_record). The
    ``measured_at`` stamp is what consumers (e.g. bench.py's
    committed-artifact fallback) sort on, so it is always set here."""
    import jax

    now = datetime.datetime.now(datetime.timezone.utc)
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    record = {
        "device": device_kind,
        "jax_version": jax.__version__,
        "measured_at": now.isoformat(timespec="seconds"),
        **payload,
    }
    os.makedirs(dir_path, exist_ok=True)
    name = (f"{prefix}_{device_kind.replace(' ', '_')}_"
            f"{now.strftime('%Y%m%dT%H%M%SZ')}.json")
    path = os.path.join(dir_path, name)
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    return path, record
